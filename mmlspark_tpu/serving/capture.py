"""Traffic capture: an opt-in, bounded, non-blocking journal of served
request/reply rows — the feedstock of the retrain->redeploy loop.

The capture sink rides the serving data plane the way shadow traffic
does (PR 7): the encoder stage offers each COMMITTED batch's rows to a
shallow queue and never waits — when the writer thread is behind, the
batch is dropped and counted (``serving_capture_dropped_total``); the
live path pays one sampling-tick check per batch. A dedicated writer
thread formats rows as JSON lines into **rotating segments**
(``segment-000001.jsonl``) with a byte-size rotation threshold and a
bounded segment count, so capture disk usage is O(max_segments x
max_segment_bytes) however long the worker lives.

Every row is self-describing: wall timestamp, request id, trace id
(the observability correlation key), the model version that served it,
the request payload, and the reply — a
:class:`~mmlspark_tpu.streaming.traffic.TrafficLogSource` turns the
segments back into frames for ``NNLearner.fit_stream``.

This is also the home of the PR 7 follow-up, **shadow-output
sampling**: the rollout shadow thread offers a sampled slice of each
mirrored batch here (``kind="shadow"`` rows carrying the live AND
staged outputs side by side) for offline diffing beyond the in-process
mismatch counters.
"""

from __future__ import annotations

import json
import os
import threading
import time
from queue import Empty, Full, Queue
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.logs import get_logger

logger = get_logger("serving.capture")

SEGMENT_PREFIX = "segment-"
SEGMENT_SUFFIX = ".jsonl"


def _py(v: Any) -> Any:
    """JSON-encodable view of a payload/reply value."""
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, dict):
        return {k: _py(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_py(x) for x in v]
    return v


class TrafficCapture:
    """Bounded, non-blocking traffic journal for one serving worker.

    ``sample_every``: capture every Nth committed batch (1 = all).
    ``shadow_sample_every``: same cadence for mirrored shadow batches
    (0 disables shadow sampling). ``shadow_rows_per_batch`` bounds the
    rows written per sampled shadow batch (diff evidence, not a full
    mirror).
    """

    def __init__(self, directory: str,
                 sample_every: int = 1,
                 shadow_sample_every: int = 1,
                 shadow_rows_per_batch: int = 16,
                 max_segment_bytes: int = 4 << 20,
                 max_segments: int = 64,
                 queue_depth: int = 256):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.sample_every = max(int(sample_every), 1)
        self.shadow_sample_every = max(int(shadow_sample_every), 0)
        self.shadow_rows_per_batch = max(int(shadow_rows_per_batch), 1)
        self.max_segment_bytes = max(int(max_segment_bytes), 1 << 10)
        self.max_segments = max(int(max_segments), 2)
        self._q: "Queue[Tuple[str, Any]]" = Queue(
            maxsize=max(int(queue_depth), 1))
        self._tick = 0
        self._shadow_tick = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fh = None
        self._seg_path: Optional[str] = None
        self._seg_bytes = 0
        # restart continues with a FRESH segment after the newest on
        # disk: a consumer mid-way through an old segment never sees it
        # grow again under its cursor
        self._seg_idx = self._next_segment_index()
        self.n_rows = 0
        self.n_shadow_rows = 0
        self.n_dropped_batches = 0
        self.n_segments_rotated = 0
        self.n_segments_pruned = 0
        self.n_write_errors = 0

    # -- hot path (encoder / shadow threads) ---------------------------------

    def offer(self, version: str, committed: List[Any]) -> None:
        """Offer one committed batch's requests+replies. Called by the
        encoder stage AFTER the batch committed; never blocks. Each
        element needs ``.rid``/``.trace``/``.payload``/``.reply``
        (the server's pending-request shape)."""
        if not committed:
            return
        self._tick += 1
        if self._tick % self.sample_every:
            return
        rows = [(p.rid, p.trace, p.payload, p.reply) for p in committed]
        try:
            self._q.put_nowait(("traffic", (version, time.time(), rows)))
        except Full:
            self.n_dropped_batches += 1
            return
        self._ensure_writer()

    def offer_shadow(self, live_version: str, staged_version: str,
                     df, live_out, shadow_out) -> None:
        """Offer a sampled slice of one mirrored batch (live vs staged
        outputs side by side). Called from the rollout shadow thread;
        never blocks."""
        if not self.shadow_sample_every:
            return
        self._shadow_tick += 1
        if self._shadow_tick % self.shadow_sample_every:
            return
        try:
            self._q.put_nowait((
                "shadow",
                (live_version, staged_version, time.time(),
                 df, live_out, shadow_out)))
        except Full:
            self.n_dropped_batches += 1
            return
        self._ensure_writer()

    # -- writer thread -------------------------------------------------------

    def _ensure_writer(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="traffic-capture")
            self._thread.start()

    def _writer_loop(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._q.get(timeout=0.2)
            except Empty:
                continue
            self._write_item(item)

    def _write_item(self, item: Tuple[str, Any]) -> None:
        try:
            kind, payload = item
            if kind == "traffic":
                lines = self._format_traffic(*payload)
            else:
                lines = self._format_shadow(*payload)
            if lines:
                self._append(lines)
        except Exception:  # noqa: BLE001 — capture is best-effort
            # observability of the data plane, never a hazard to it
            self.n_write_errors += 1
            logger.warning("traffic capture write failed", exc_info=True)

    def _format_traffic(self, version: str, t_wall: float,
                        rows: List[Tuple]) -> List[bytes]:
        out = []
        for rid, trace, payload, reply in rows:
            try:
                rep = json.loads(reply) if reply else {}
            except ValueError:
                rep = {"_raw": reply.decode("utf-8", "replace")}
            rec = {"kind": "traffic", "t": round(t_wall, 3),
                   "rid": rid, "trace": trace, "version": version,
                   "request": _py(payload), "reply": _py(rep)}
            out.append(json.dumps(rec).encode())
            self.n_rows += 1
        return out

    def _format_shadow(self, live_version: str, staged_version: str,
                       t_wall: float, df, live_out, shadow_out
                       ) -> List[bytes]:
        added = [c for c in live_out.columns if c not in df.columns]
        shadow_cols = [c for c in shadow_out.columns
                       if c not in df.columns]
        out = []
        for i in range(min(df.num_rows, self.shadow_rows_per_batch)):
            rec = {"kind": "shadow", "t": round(t_wall, 3),
                   "version": live_version,
                   "staged_version": staged_version,
                   "request": {c: _py(df[c][i]) for c in df.columns},
                   "live": {c: _py(live_out[c][i]) for c in added},
                   "shadow": {c: _py(shadow_out[c][i])
                              for c in shadow_cols}}
            out.append(json.dumps(rec).encode())
            self.n_shadow_rows += 1
        return out

    # -- segments ------------------------------------------------------------

    def _next_segment_index(self) -> int:
        latest = 0
        for name in os.listdir(self.directory):
            if name.startswith(SEGMENT_PREFIX) \
                    and name.endswith(SEGMENT_SUFFIX):
                try:
                    latest = max(latest, int(
                        name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]))
                except ValueError:
                    continue
        return latest + 1

    def _open_segment(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except Exception:  # noqa: BLE001
                pass
        self._seg_path = os.path.join(
            self.directory,
            f"{SEGMENT_PREFIX}{self._seg_idx:06d}{SEGMENT_SUFFIX}")
        self._fh = open(self._seg_path, "ab")
        self._seg_bytes = os.path.getsize(self._seg_path)
        self._seg_idx += 1

    def _append(self, lines: List[bytes]) -> None:
        if self._fh is None or self._seg_bytes >= self.max_segment_bytes:
            if self._fh is not None:
                self.n_segments_rotated += 1
            self._open_segment()
            self._prune()
        blob = b"".join(ln + b"\n" for ln in lines)
        self._fh.write(blob)
        self._fh.flush()
        self._seg_bytes += len(blob)

    def _segments(self) -> List[str]:
        return sorted(
            name for name in os.listdir(self.directory)
            if name.startswith(SEGMENT_PREFIX)
            and name.endswith(SEGMENT_SUFFIX))

    def _prune(self) -> None:
        segs = self._segments()
        for name in segs[:-self.max_segments]:
            try:
                os.remove(os.path.join(self.directory, name))
                self.n_segments_pruned += 1
            except OSError:
                continue

    # -- lifecycle / surfaces ------------------------------------------------

    def flush(self, timeout: float = 5.0) -> None:
        """Drain queued batches to disk (tests / shutdown)."""
        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            if self._thread is None or not self._thread.is_alive():
                # no writer running: drain inline
                try:
                    self._write_item(self._q.get_nowait())
                except Empty:
                    break
            else:
                time.sleep(0.01)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2)
        self.flush()
        if self._fh is not None:
            try:
                self._fh.close()
            except Exception:  # noqa: BLE001
                pass
            self._fh = None

    def bind(self, registry) -> None:
        """Expose capture counters in a server's registry."""
        for name, help_, attr in (
            ("serving_capture_rows_total",
             "Committed request/reply rows written to the traffic "
             "capture journal.", "n_rows"),
            ("serving_capture_shadow_rows_total",
             "Sampled shadow-comparison rows written to the capture "
             "journal (live vs staged outputs).", "n_shadow_rows"),
            ("serving_capture_dropped_total",
             "Sampled batches dropped because the capture writer was "
             "behind (capture never delays live traffic).",
             "n_dropped_batches"),
            ("serving_capture_segments_rotated_total",
             "Capture segments closed at the rotation threshold.",
             "n_segments_rotated"),
            ("serving_capture_segments_pruned_total",
             "Old capture segments deleted beyond max_segments.",
             "n_segments_pruned"),
            ("serving_capture_write_errors_total",
             "Capture writer failures (rows lost, live path "
             "unaffected).", "n_write_errors"),
        ):
            registry.counter(name, help_).set_function(
                lambda a=attr: getattr(self, a))

    def status(self) -> Dict[str, Any]:
        segs = self._segments()
        return {"directory": self.directory,
                "sample_every": self.sample_every,
                "shadow_sample_every": self.shadow_sample_every,
                "rows": self.n_rows,
                "shadow_rows": self.n_shadow_rows,
                "dropped_batches": self.n_dropped_batches,
                "segments": len(segs),
                "segments_rotated": self.n_segments_rotated,
                "segments_pruned": self.n_segments_pruned,
                "write_errors": self.n_write_errors}
