"""Event-loop serving frontend: the socket edge of the data plane.

Replaces the ``ThreadingHTTPServer``/``BaseHTTPRequestHandler`` ingress
(one thread per connection, line-at-a-time header parsing, three writes
per reply) with a :mod:`selectors`-based non-blocking frontend built for
the rates the staged pipeline (PR 2) can already sustain:

* **keep-alive connection reuse** — HTTP/1.1 persistent connections are
  the steady state, not an option: a connection parks in the loop
  between requests at the cost of one registered fd, never a thread;
* **incremental zero-copy framing** — requests are parsed straight out
  of a per-connection receive buffer: one ``\\r\\n\\r\\n`` scan finds the
  header block, one pre-compiled regex pass over it extracts the
  headers (no per-line split, no per-line decode — header values are
  decoded lazily, only when someone reads them), and the body is sliced
  out once via ``memoryview``;
* **vectored single-syscall replies** — the response head is assembled
  from cached blocks (per-status line, a once-a-second Date header,
  common Content-Type lines) and handed to ``socket.sendmsg([head,
  body])``: one syscall per reply, no head+body concatenation copy;
* **multi-acceptor ``SO_REUSEPORT`` loops** — ``acceptors=N`` with
  ``reuse_port=True`` binds N listening sockets to the one port and
  runs N independent event loops; the kernel load-balances accepted
  connections across them, so the socket edge scales past one loop's
  ceiling while every loop feeds the same staged
  collect/assemble -> dispatch -> encode executor.

The frontend is transport only. It speaks to its application through a
three-method protocol (duck-typed — :class:`ServingServer` and
:class:`ServingCoordinator` both implement it):

``app.handle_request(method, path, headers, body, reply) -> bool``
    Handle one request. ``reply(status, body, ctype=..., extra=...)``
    must be called EXACTLY ONCE — synchronously, or later from any
    thread (the serving pipeline's encoder threads call it at commit
    time). Return ``False`` for an unknown route (the frontend sends
    the 404). The frontend guarantees a late/duplicate ``reply`` (e.g.
    racing the request-timeout sweep) is dropped, never misdelivered
    to a newer request on the same connection.

Timeouts (all enforced by a per-loop sweep, not per-socket timers):

* ``idle_timeout`` — a connection parked *between* requests longer than
  this is closed, and a connection stuck *mid-request* (the slow-loris
  shape: headers or body dribbling in forever) is reaped on the same
  clock. ``<= 0`` disables reaping, matching the threaded frontend.
* ``request_timeout`` — a dispatched request whose ``reply`` has not
  arrived within this budget is answered 504 by the sweep (the
  stuck-batch contract the threaded frontend implements with
  ``Event.wait``); the eventual real reply is dropped by generation.

Fairness and shedding at the socket edge:

* ``max_pipelined_per_iter`` — at most this many buffered pipelined
  requests are served per connection per loop pass; the remainder is
  deferred to the next iteration (``serving_pipelining_deferred_total``
  counts deferrals), so a single connection flooding pipelined requests
  cannot monopolize a loop while other connections wait.
* ``max_conns_per_ip`` — a per-peer-address concurrent-connection cap
  enforced at accept, IN FRONT of the application's ``max_queue``
  shedding: over-cap accepts get an immediate 429 + close
  (``serving_per_ip_rejected_total``), and the observed per-IP
  high-water mark is exported as a gauge.

Protocol guardrails (each satisfies one of the framing edge cases the
frontend must not inherit from ``http.server``): header blocks beyond
``max_header_bytes`` are rejected 431; POST bodies need a valid
``Content-Length`` (missing -> 411, unparseable -> 400, beyond
``max_body_bytes`` -> 413); ``Connection: close`` (and HTTP/1.0 without
``keep-alive``) is honored; ``Transfer-Encoding: chunked`` is refused
501 (the serving wire contract is Content-Length-framed JSON).

See ``docs/serving.md`` ("The socket edge") for operator-facing knobs
and ``docs/observability.md`` for the connection gauges.
"""

from __future__ import annotations

import errno
import re
import selectors
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from contextlib import contextmanager

try:
    import ssl
except ImportError:  # pragma: no cover — stripped-down interpreters
    ssl = None  # type: ignore[assignment]

# except-clause tuples that stay valid (matching nothing) without ssl
_TLS_WANT_READ: tuple = (ssl.SSLWantReadError,) if ssl is not None else ()
_TLS_WANT_WRITE: tuple = (ssl.SSLWantWriteError,) if ssl is not None \
    else ()

from mmlspark_tpu.core.logs import get_logger

logger = get_logger("serving.frontend")

__all__ = ["EventLoopFrontend", "Headers", "batched_replies"]


# ---------------------------------------------------------------------------
# Batched reply flushing
# ---------------------------------------------------------------------------

#: per-thread reply batch: while a :func:`batched_replies` scope is
#: active on a committing thread, ``_Loop.post_reply`` parks replies
#: here (keyed by loop) instead of queue-append + wake per reply; the
#: scope exit flushes each loop's batch with ONE deque extend and ONE
#: wake. Thread-local, so concurrent encoder threads batch
#: independently with no shared state.
_REPLY_BATCH = threading.local()


@contextmanager
def batched_replies():
    """Coalesce cross-thread reply posts made inside the scope.

    The serving pipeline commits replies per micro-batch
    (``_commit_many``): without batching, N replies destined for N
    distinct connections on the same loop cost N wake checks and up to
    N wake syscalls; inside this scope they land in one deque extend
    and one wake per *loop*, and the loop delivers them all in one
    pass. Safe to nest (the outermost scope flushes) and to use on any
    thread; in-loop synchronous replies never hit the batch (they
    deliver inline, as before)."""
    if getattr(_REPLY_BATCH, "active", None) is not None:
        yield                      # nested: the outer scope flushes
        return
    batch: Dict[Any, list] = {}
    _REPLY_BATCH.active = batch
    try:
        yield
    finally:
        _REPLY_BATCH.active = None
        for loop, items in batch.items():
            loop.flush_replies(items)


# ---------------------------------------------------------------------------
# Request framing
# ---------------------------------------------------------------------------

#: one pass over the header block: RFC 7230 token names, optional
#: whitespace, value to end-of-line. Compiled once; runs directly on
#: the connection's ``bytearray`` receive buffer (re accepts any buffer
#: object), so the scan itself copies nothing — only the matched
#: name/value groups materialize, and values stay bytes until someone
#: reads them.
_HDR_RE = re.compile(rb"([!#$%&'*+\-.^_`|~0-9A-Za-z]+):[ \t]*([^\r\n]*)")

_CRLF2 = b"\r\n\r\n"


class Headers:
    """Case-insensitive, decode-lazy view over parsed header bytes.

    ``get`` mirrors the stdlib message API the rest of the stack codes
    against (``headers.get("X-Trace-Id")`` in
    :func:`~mmlspark_tpu.core.tracing.extract_span_context`,
    ``Deadline.from_headers``...), decoding a value (latin-1, the HTTP
    wire charset) only when it is actually read."""

    __slots__ = ("_raw",)

    def __init__(self, raw: Dict[bytes, bytes]):
        self._raw = raw

    def get(self, name: str, default: Optional[str] = None
            ) -> Optional[str]:
        v = self._raw.get(name.lower().encode("ascii"))
        return default if v is None else v.decode("latin-1")

    def get_bytes(self, lname: bytes, default: bytes = b"") -> bytes:
        return self._raw.get(lname, default)

    def __contains__(self, name: str) -> bool:
        return name.lower().encode("ascii") in self._raw

    def items(self):
        return [(k.decode("latin-1"), v.decode("latin-1"))
                for k, v in self._raw.items()]

    def __repr__(self) -> str:
        return f"Headers({self._raw!r})"


def parse_head(buf, head_end: int) -> Tuple[bytes, str, bytes, Headers]:
    """Parse ``buf[:head_end]`` (request line + header block, no final
    CRLFCRLF) into ``(method, path, version, headers)``.

    ``buf`` is the connection's receive buffer (bytes/bytearray —
    bytearray in production: ``find`` and the regex scan both run on it
    directly, so nothing is sliced or copied but the request line); the
    header scan is ONE pre-compiled regex pass bounded by pos/endpos —
    no line split, no per-line decode, no buffer slice. Raises
    ``ValueError`` on a malformed request line; malformed header lines
    (no colon) are skipped rather than fatal — lenient like the stdlib
    parser."""
    line_end = buf.find(b"\r\n", 0, head_end)
    if line_end < 0:
        line_end = head_end
    line = bytes(buf[:line_end])
    sp1 = line.find(b" ")
    sp2 = line.rfind(b" ")
    if sp1 <= 0 or sp2 <= sp1:
        raise ValueError(f"malformed request line: {line[:80]!r}")
    method = line[:sp1]
    path = line[sp1 + 1:sp2].decode("latin-1")
    version = line[sp2 + 1:]
    raw: Dict[bytes, bytes] = {}
    for m in _HDR_RE.finditer(buf, line_end + 2, head_end):
        raw[m.group(1).lower()] = m.group(2)
    return method, path, version, Headers(raw)


# ---------------------------------------------------------------------------
# Cached reply blocks
# ---------------------------------------------------------------------------

_PHRASES = {
    200: b"HTTP/1.1 200 OK\r\n",
    400: b"HTTP/1.1 400 Bad Request\r\n",
    404: b"HTTP/1.1 404 Not Found\r\n",
    408: b"HTTP/1.1 408 Request Timeout\r\n",
    411: b"HTTP/1.1 411 Length Required\r\n",
    413: b"HTTP/1.1 413 Payload Too Large\r\n",
    429: b"HTTP/1.1 429 Too Many Requests\r\n",
    431: b"HTTP/1.1 431 Request Header Fields Too Large\r\n",
    500: b"HTTP/1.1 500 Internal Server Error\r\n",
    501: b"HTTP/1.1 501 Not Implemented\r\n",
    503: b"HTTP/1.1 503 Service Unavailable\r\n",
    504: b"HTTP/1.1 504 Gateway Timeout\r\n",
}


def _status_line(status: int) -> bytes:
    line = _PHRASES.get(status)
    if line is None:
        line = b"HTTP/1.1 %d Status\r\n" % status
        _PHRASES[status] = line
    return line


# the Date header changes once a second; format it at most that often
# (shared across every loop and connection — wall clock is process-wide)
_DATE_CACHE: List[Any] = [0.0, b""]


def _date_line() -> bytes:
    now = time.time()
    if now - _DATE_CACHE[0] >= 1.0:
        from email.utils import formatdate
        # value BEFORE timestamp: a racing reader that sees the fresh
        # timestamp must never read the stale (or empty) bytes
        _DATE_CACHE[1] = ("Date: " + formatdate(now, usegmt=True)
                          + "\r\n").encode("ascii")
        _DATE_CACHE[0] = now
    return _DATE_CACHE[1]


_CTYPE_JSON = b"Content-Type: application/json\r\n"
_CONN_CLOSE = b"Connection: close\r\n"
_CL_PREFIX = b"Content-Length: "

#: Content-Length lines for small bodies, interned once: the common
#: replies (~10-200 byte JSON) skip the int->bytes format entirely
_CL_CACHE = [b"Content-Length: %d\r\n" % n for n in range(1024)]


def build_head(status: int, body_len: int,
               ctype: str = "application/json",
               extra: Tuple[Tuple[str, str], ...] = (),
               close: bool = False) -> bytes:
    """Assemble a response head from cached blocks. One ``join`` — the
    body is NOT concatenated here; ``sendmsg([head, body])`` carries
    both in one syscall without the copy."""
    parts = [_status_line(status), _date_line(),
             _CTYPE_JSON if ctype == "application/json"
             else b"Content-Type: " + ctype.encode("latin-1") + b"\r\n",
             _CL_CACHE[body_len] if body_len < 1024
             else _CL_PREFIX + str(body_len).encode("ascii") + b"\r\n"]
    for k, v in extra:
        parts.append(f"{k}: {v}\r\n".encode("latin-1"))
    if close:
        parts.append(_CONN_CLOSE)
    parts.append(b"\r\n")
    return b"".join(parts)


_SSE_CTYPE = b"Content-Type: text/event-stream\r\n"
_CHUNKED = b"Transfer-Encoding: chunked\r\nCache-Control: no-cache\r\n"


def build_stream_head(status: int = 200,
                      extra: Tuple[Tuple[str, str], ...] = (),
                      close: bool = False) -> bytes:
    """Response head for a chunked SSE stream: no Content-Length —
    ``Transfer-Encoding: chunked`` frames the incremental body, so the
    connection stays keep-alive after the terminal chunk."""
    parts = [_status_line(status), _date_line(), _SSE_CTYPE, _CHUNKED]
    for k, v in extra:
        parts.append(f"{k}: {v}\r\n".encode("latin-1"))
    if close:
        parts.append(_CONN_CLOSE)
    parts.append(b"\r\n")
    return b"".join(parts)


def _chunk(data: bytes) -> bytes:
    return b"%x\r\n" % len(data) + data + b"\r\n"


class _EventLoopStream:
    """A live incremental response on one event-loop connection.

    Producers (the decode scheduler's loop thread) call :meth:`emit`
    per event and :meth:`finish` once; both post to the owning loop,
    which frames each event as an HTTP chunk and rides the existing
    non-blocking write state machine (partial writes continue via
    ``conn.out`` + EVENT_WRITE). ``closed`` flips when the peer
    disconnects mid-stream or the bounded per-connection buffer
    overflows (slow consumer) — producers poll it and cancel their
    work; writes after ``closed`` are dropped."""

    __slots__ = ("_loop", "_conn", "_gen", "closed", "done", "t_first")

    def __init__(self, loop: "_Loop", conn: "_Conn", gen: int):
        self._loop = loop
        self._conn = conn
        self._gen = gen
        self.closed = False
        self.done = False
        # monotonic stamp of the FIRST chunk hitting the socket write
        # path — the client-observable TTFT edge (0.0 = none yet)
        self.t_first = 0.0

    def emit(self, data: bytes) -> None:
        if self.closed or self.done:
            return
        self._loop.post_stream(self._conn, self._gen, data, False)

    def finish(self, data: bytes = b"") -> None:
        if self.closed or self.done:
            return
        self.done = True
        self._loop.post_stream(self._conn, self._gen, data, True)


# ---------------------------------------------------------------------------
# Connection state machine
# ---------------------------------------------------------------------------

_HEAD, _BODY, _AWAIT, _CLOSING, _STREAM, _TLS_HS = 0, 1, 2, 3, 4, 5

#: sentinel tag marking a stream item on the shared reply deque
_STREAM_TAG = object()


class _Conn:
    __slots__ = ("sock", "fd", "buf", "scanned", "state", "gen", "out",
                 "t_last", "t_req_start", "t_await", "n_requests",
                 "keep_alive", "method", "path", "headers", "body_start",
                 "body_len", "want_write", "advancing", "peer_ip",
                 "stream", "tls")

    def __init__(self, sock: socket.socket, peer_ip: str = ""):
        self.sock = sock
        self.peer_ip = peer_ip
        # TLS connection: reads/writes go through the SSL record layer
        # (no sendmsg; SSLWantRead/WantWrite instead of EAGAIN), and
        # the connection starts life in the _TLS_HS handshake state
        self.tls = False
        self.fd = sock.fileno()
        self.buf = bytearray()
        self.scanned = 0            # CRLFCRLF search resume offset
        self.state = _HEAD
        # reply generation: bumped every time the in-flight request slot
        # is consumed (reply delivered OR timed out/aborted), so a stale
        # reply callback can never answer a LATER request on this socket
        self.gen = 0
        self.out = bytearray()      # unwritten reply bytes (rare path)
        self.t_last = 0.0           # last byte received (idle reaping)
        self.t_req_start = 0.0      # first byte of the current request
        self.t_await = 0.0          # when the current request dispatched
        self.n_requests = 0
        self.keep_alive = True
        self.method = b""
        self.path = ""
        self.headers: Optional[Headers] = None
        self.body_start = 0
        self.body_len = 0
        self.want_write = False
        self.advancing = False
        self.stream: Optional[_EventLoopStream] = None


class _Loop(threading.Thread):
    """One acceptor + event loop: a listening socket, a selector, the
    connections the kernel handed this loop, and a thread-safe reply
    queue fed by the pipeline's commit callbacks."""

    def __init__(self, frontend: "EventLoopFrontend", index: int,
                 listener: socket.socket):
        super().__init__(daemon=True,
                         name=f"{frontend.name}-frontend-{index}")
        self.frontend = frontend
        self.index = index
        self.listener = listener
        self.sel = selectors.DefaultSelector()
        self.conns: Dict[int, _Conn] = {}
        # pipelining-fairness continuations: connections whose buffered
        # requests were deferred mid-_advance (cap reached) resume here
        # on the NEXT loop iteration, after every other connection's
        # events were handled
        self._deferred: Dict[int, _Conn] = {}
        self._replies: deque = deque()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._wake_pending = False
        self._accepting = True
        self._stopping = False
        # busy-ratio window: time spent processing events vs wall time,
        # refreshed every ~2 s — the accept-loop saturation gauge
        self.busy_ratio = 0.0
        self._win_t0 = time.monotonic()
        self._win_busy = 0.0

    # -- cross-thread entry points ------------------------------------------

    def post_reply(self, conn: _Conn, gen: int, head: bytes,
                   body: bytes, close_after: bool) -> None:
        """Queue a reply for delivery by the loop thread; safe from any
        thread. In-loop callers deliver inline (no queue round-trip);
        inside a :func:`batched_replies` scope, cross-thread replies
        park in the thread-local batch and flush together."""
        if threading.get_ident() == self.ident:
            self._deliver(conn, gen, head, body, close_after)
            return
        batch = getattr(_REPLY_BATCH, "active", None)
        if batch is not None:
            batch.setdefault(self, []).append(
                (conn, gen, head, body, close_after))
            return
        self._replies.append((conn, gen, head, body, close_after))
        self.wake()

    def flush_replies(self, items: list) -> None:
        """Batched-commit flush: every reply in ``items`` joins the
        delivery deque in one extend, then ONE wake — the loop serves
        them all in a single pass (vs one wake check per reply)."""
        if not items:
            return
        self._replies.extend(items)
        fe = self.frontend
        fe.n_reply_flushes += 1
        fe.n_batched_replies += len(items)
        self.wake()

    def post_stream(self, conn: _Conn, gen: int, data: bytes,
                    end: bool) -> None:
        """Queue one stream event (or the terminal event) for
        delivery by the loop thread; safe from any thread."""
        if threading.get_ident() == self.ident:
            self._deliver_stream(conn, gen, data, end)
            return
        self._replies.append((_STREAM_TAG, conn, gen, data, end))
        self.wake()

    def open_stream(self, conn: _Conn, gen: int,
                    extra: Tuple[Tuple[str, str], ...] = ()
                    ) -> Optional[_EventLoopStream]:
        """Switch the in-flight request to incremental delivery: send
        the chunked-SSE head now, return the producer handle. LOOP
        THREAD ONLY (called synchronously from ``handle_request``);
        None when the request is no longer current."""
        if conn.fd not in self.conns or conn.gen != gen \
                or conn.state != _AWAIT:
            return None
        conn.state = _STREAM
        handle = _EventLoopStream(self, conn, gen)
        conn.stream = handle
        self.frontend.n_streams += 1
        self._write(conn, build_stream_head(
            200, extra, close=not conn.keep_alive), b"", False)
        return handle

    def _deliver_stream(self, conn: _Conn, gen: int, data: bytes,
                        end: bool) -> None:
        """Frame one SSE event as an HTTP chunk IF the stream is still
        current; the terminal event also writes the zero chunk and
        returns the connection to keep-alive (or closes it)."""
        if conn.fd not in self.conns or conn.gen != gen \
                or conn.state != _STREAM:
            return
        fe = self.frontend
        if len(conn.out) > fe.max_stream_buffer_bytes:
            # slow-consumer backpressure: the bounded per-conn buffer
            # is full — drop the connection rather than balloon memory
            # (the producer sees handle.closed and cancels its work)
            fe.n_stream_overflows += 1
            self._close(conn)
            return
        payload = _chunk(data) if data else b""
        if not end:
            fe.n_stream_events += 1
            # the stall clock: a stream is alive as long as events
            # flow — the sweep reaps streams whose LAST event is older
            # than request_timeout (the threaded frontend's
            # q.get(timeout) analogue)
            conn.t_await = time.monotonic()
            stream = conn.stream
            if stream is not None and stream.t_first == 0.0:
                # socket-edge TTFT: the decode scheduler reads this
                # at finish in preference to its own loop-side stamp
                stream.t_first = conn.t_await
            self._write(conn, payload, b"", False)
            return
        payload += b"0\r\n\r\n"                 # terminal chunk
        close_after = not conn.keep_alive
        conn.stream = None
        conn.gen += 1
        conn.state = _CLOSING if close_after else _HEAD
        conn.t_req_start = conn.t_last = time.monotonic()
        fe.n_stream_events += 1
        self._write(conn, payload, b"", close_after)
        if conn.fd in self.conns and conn.state == _HEAD \
                and not conn.out:
            self._advance(conn)       # serve pipelined follow-ups

    def wake(self) -> None:
        # one pending byte is enough to wake the selector; the flag
        # keeps a burst of commits from paying one syscall each (reads
        # and writes of a bool are atomic under the GIL; a lost race
        # costs one harmless extra byte)
        if not self._wake_pending:
            self._wake_pending = True
            try:
                self._wake_w.send(b"\x01")
            except OSError:
                pass

    def pause_accept(self) -> None:
        self._accepting = False
        self.wake()

    def request_stop(self) -> None:
        self._stopping = True
        self.wake()

    # -- the loop ------------------------------------------------------------

    def run(self) -> None:
        fe = self.frontend
        self.sel.register(self.listener, selectors.EVENT_READ, "accept")
        self.sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        last_sweep = time.monotonic()
        # sweep often enough that short idle timeouts (tests use 0.3 s)
        # reap within a fraction of their budget
        tick = 0.05 if 0 < fe.idle_timeout <= 1.0 else 0.25
        try:
            while True:
                t_sel = time.monotonic()
                # never park in select while deferred pipelined work is
                # waiting — it was deferred for fairness, not for later
                events = self.sel.select(
                    timeout=0 if self._deferred else tick)
                t0 = time.monotonic()
                if self._stopping:
                    break
                self._wake_pending = False
                for key, mask in events:
                    what = key.data
                    if what == "accept":
                        self._accept_burst()
                    elif what == "wake":
                        self._drain_wake()
                    else:
                        conn = what
                        if mask & selectors.EVENT_WRITE:
                            self._on_writable(conn)
                        if mask & selectors.EVENT_READ and \
                                conn.fd in self.conns:
                            self._on_readable(conn)
                self._drain_replies()
                if self._deferred:
                    # resume capped pipelined connections: one fresh
                    # _advance budget each, AFTER this iteration's
                    # events — a flooding connection progresses, but
                    # never monopolizes the loop
                    resumed = list(self._deferred.values())
                    self._deferred.clear()
                    for conn in resumed:
                        if conn.fd in self.conns:
                            self._advance(conn)
                if not self._accepting and self.listener is not None:
                    self._close_listener()
                now = time.monotonic()
                if now - last_sweep >= tick:
                    self._sweep(now)
                    last_sweep = now
                # busy-ratio bookkeeping (saturation telemetry): the
                # fraction of wall time NOT spent blocked in select()
                self._win_busy += now - t0
                if now - self._win_t0 >= 2.0:
                    span = max(now - self._win_t0, 1e-9)
                    self.busy_ratio = min(self._win_busy / span, 1.0)
                    self._win_t0, self._win_busy = now, 0.0
                _ = t_sel
        except Exception:  # noqa: BLE001 — a dead loop strands its fds
            logger.error("frontend loop %d crashed", self.index,
                         exc_info=True)
        finally:
            self._shutdown()

    # -- accept --------------------------------------------------------------

    def _accept_burst(self) -> None:
        fe = self.frontend
        if self.listener is None:
            return
        for _ in range(256):          # bounded: never starve live conns
            try:
                sock, _addr = self.listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if not self._accepting:
                sock.close()
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass                  # AF_UNIX etc.
            peer_ip = _addr[0] if isinstance(_addr, tuple) and _addr \
                else ""
            if not fe._ip_acquire(peer_ip):
                # per-IP shedding layer: one peer flooding connections
                # is refused at accept — an immediate 429 + close —
                # BEFORE it can occupy queue slots other clients need.
                # Best-effort single send: the socket was just
                # accepted, so the tiny reply fits the send buffer.
                # (On a TLS port there is no handshake to speak the
                # reply over — the close alone is the signal.)
                fe.n_per_ip_rejected += 1
                if fe.ssl_context is None:
                    body = (b'{"error": "too many connections from '
                            b'this address"}')
                    try:
                        sock.send(build_head(
                            429, len(body),
                            extra=(("Retry-After", "1"),),
                            close=True) + body)
                    except OSError:
                        pass
                sock.close()
                continue
            if fe.ssl_context is not None:
                # TLS termination: wrap now, handshake incrementally on
                # the loop (the _TLS_HS state) — a slow (or silent, or
                # plaintext-speaking) peer never blocks this thread
                try:
                    sock = fe.ssl_context.wrap_socket(
                        sock, server_side=True,
                        do_handshake_on_connect=False)
                except (OSError, ValueError):
                    fe._ip_release(peer_ip)
                    sock.close()
                    continue
            conn = _Conn(sock, peer_ip)
            if fe.ssl_context is not None:
                conn.tls = True
                conn.state = _TLS_HS
            conn.t_last = conn.t_req_start = time.monotonic()
            self.conns[conn.fd] = conn
            fe.n_connections += 1
            self.sel.register(sock, selectors.EVENT_READ, conn)

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    def _close_listener(self) -> None:
        if self.listener is None:
            return
        try:
            self.sel.unregister(self.listener)
        except (KeyError, ValueError):
            pass
        try:
            self.listener.close()
        except OSError:
            pass
        self.listener = None

    # -- TLS handshake -------------------------------------------------------

    def _tls_handshake(self, conn: _Conn) -> None:
        """Drive one step of the non-blocking TLS handshake — a
        first-class connection state, not a blocking call: WantRead
        leaves the read registration in place, WantWrite re-registers
        for writability, success moves to ``_HEAD``, and anything else
        (a plaintext byte on the TLS port, a bad record, a mid-
        handshake disconnect) closes cleanly and counts a failure."""
        fe = self.frontend
        try:
            conn.sock.do_handshake()
        except ssl.SSLWantReadError:
            self._want_write(conn, False)
            return
        except ssl.SSLWantWriteError:
            self._want_write(conn, True)
            return
        except (OSError, ValueError):
            # ssl.SSLError subclasses OSError: plaintext on a TLS
            # port, protocol mismatch, EOF mid-handshake — all end
            # here, closed without a stack trace or a stuck fd
            fe.n_tls_handshake_failures += 1
            self._close(conn)
            return
        fe.n_tls_handshakes += 1
        conn.state = _HEAD
        self._want_write(conn, False)
        conn.t_last = conn.t_req_start = time.monotonic()
        if conn.sock.pending():
            # the read that finished the handshake may have pulled the
            # first request's app-data record off the wire with it: the
            # decrypted bytes sit in the SSL layer, the raw fd is empty,
            # and the selector would never fire — serve them now
            self._on_readable(conn)

    # -- read + parse --------------------------------------------------------

    def _on_readable(self, conn: _Conn) -> None:
        if conn.state == _TLS_HS:
            self._tls_handshake(conn)
            return
        try:
            data = conn.sock.recv(65536)
            if conn.tls and data:
                # the SSL layer may hold MORE decrypted bytes than one
                # recv returned, with nothing left on the raw socket —
                # the selector would never fire again for them
                while conn.sock.pending():
                    more = conn.sock.recv(65536)
                    if not more:
                        break
                    data += more
        except (BlockingIOError, InterruptedError):
            return
        except _TLS_WANT_READ:
            return
        except _TLS_WANT_WRITE:
            # renegotiation wants the socket writable first
            self._want_write(conn, True)
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            self._close(conn)         # peer closed (maybe mid-request)
            return
        now = time.monotonic()
        conn.t_last = now
        if conn.state == _HEAD and not conn.buf:
            conn.t_req_start = now
        conn.buf += data
        if len(conn.buf) > self.frontend.max_header_bytes + \
                self.frontend.max_body_bytes:
            # a client flooding bytes while a request is in flight (or
            # ignoring every reject) must not grow the buffer unbounded
            self._close(conn)
            return
        self._advance(conn)

    def _advance(self, conn: _Conn) -> None:
        """Drive the state machine as far as the buffered bytes allow.
        One while-iteration per complete request, so a pipelining
        client is served in order without waiting for read events: a
        synchronous reply flips the state back to ``_HEAD`` mid-loop
        and the next buffered request parses immediately. The
        ``advancing`` flag keeps that re-entry iterative — ``_deliver``
        never recurses into an ``_advance`` that is already on the
        stack (a deep pipeline burst must not grow the C stack)."""
        if conn.advancing:
            return
        conn.advancing = True
        try:
            self._advance_inner(conn)
        finally:
            conn.advancing = False

    def _advance_inner(self, conn: _Conn) -> None:
        fe = self.frontend
        served = 0
        cap = fe.max_pipelined_per_iter
        while conn.state in (_HEAD, _BODY) and not conn.out:
            if cap > 0 and served >= cap and conn.buf:
                # HTTP/1.1 pipelining fairness: one connection
                # flooding pipelined requests in a single buffer must
                # not monopolize this loop iteration — park the rest
                # of its buffer and resume next iteration, after every
                # OTHER connection's events were handled
                fe.n_pipelining_deferred += 1
                self._deferred[conn.fd] = conn
                return
            buf = conn.buf
            if conn.state == _HEAD:
                # tolerate stray CRLFs between requests (RFC 7230 3.5)
                while buf[:2] == b"\r\n":
                    del buf[:2]
                if not buf:
                    return
                # resume the terminator scan where the last one left
                # off (minus 3: the terminator may straddle the chunks)
                head_end = buf.find(_CRLF2, max(conn.scanned - 3, 0))
                if head_end < 0:
                    conn.scanned = len(buf)
                    if len(buf) > fe.max_header_bytes:
                        fe.n_parse_errors += 1
                        self._reject(conn, 431,
                                     b'{"error": "header block too '
                                     b'large"}')
                    return
                if head_end > fe.max_header_bytes:
                    # the whole oversized block landed in one recv:
                    # finding the terminator does not make it admissible
                    fe.n_parse_errors += 1
                    self._reject(conn, 431,
                                 b'{"error": "header block too '
                                 b'large"}')
                    return
                conn.scanned = 0
                try:
                    method, path, version, headers = parse_head(
                        buf, head_end)
                except ValueError:
                    fe.n_parse_errors += 1
                    self._reject(conn, 400,
                                 b'{"error": "malformed request"}')
                    return
                conn.method, conn.path, conn.headers = \
                    method, path, headers
                # keep-alive: HTTP/1.1 default-on, 1.0 default-off,
                # Connection header overrides either way
                tok = headers.get_bytes(b"connection").lower()
                if version == b"HTTP/1.0":
                    conn.keep_alive = tok == b"keep-alive"
                else:
                    conn.keep_alive = tok != b"close"
                if headers.get_bytes(b"transfer-encoding"):
                    fe.n_parse_errors += 1
                    self._reject(conn, 501,
                                 b'{"error": "chunked transfer encoding '
                                 b'not supported"}')
                    return
                raw_cl = headers.get_bytes(b"content-length", None)
                if raw_cl is None:
                    if method == b"POST" or method == b"PUT":
                        # a body-bearing method MUST declare its length:
                        # the serving wire contract is length-framed
                        fe.n_parse_errors += 1
                        self._reject(conn, 411,
                                     b'{"error": "Content-Length '
                                     b'required"}')
                        return
                    clen = 0
                else:
                    try:
                        clen = int(raw_cl)
                        if clen < 0:
                            raise ValueError
                    except ValueError:
                        fe.n_parse_errors += 1
                        self._reject(conn, 400,
                                     b'{"error": "invalid '
                                     b'Content-Length"}')
                        return
                if clen > fe.max_body_bytes:
                    fe.n_parse_errors += 1
                    self._reject(conn, 413,
                                 b'{"error": "body too large"}')
                    return
                conn.body_start = head_end + 4
                conn.body_len = clen
                conn.state = _BODY
            # _BODY: wait for the full declared length
            total = conn.body_start + conn.body_len
            if len(conn.buf) < total:
                return
            body = bytes(memoryview(conn.buf)[conn.body_start:total])
            del conn.buf[:total]
            conn.scanned = 0
            served += 1
            self._dispatch(conn, body)

    def _dispatch(self, conn: _Conn, body: bytes) -> None:
        fe = self.frontend
        conn.n_requests += 1
        fe.n_requests += 1
        if conn.n_requests > 1:
            fe.n_keepalive_reuses += 1
        conn.state = _AWAIT
        conn.t_await = time.monotonic()
        gen = conn.gen
        ka = conn.keep_alive
        loop = self

        def reply(status: int, rbody: bytes = b"",
                  ctype: str = "application/json",
                  extra: Tuple[Tuple[str, str], ...] = ()) -> None:
            head = build_head(status, len(rbody), ctype, extra,
                              close=not ka)
            loop.post_reply(conn, gen, head, rbody, not ka)

        def begin_stream(extra: Tuple[Tuple[str, str], ...] = ()):
            # upgrade this request to incremental chunked-SSE delivery
            # (token streaming). Synchronous, loop thread only — the
            # application calls it DURING handle_request, before any
            # reply; the returned handle then accepts emit()/finish()
            # from any thread. Mutually exclusive with reply().
            return loop.open_stream(conn, gen, extra)

        reply.begin_stream = begin_stream
        method = conn.method.decode("latin-1")
        try:
            handled = fe.app.handle_request(method, conn.path,
                                            conn.headers, body, reply)
        except Exception as e:  # noqa: BLE001 — app bug, not a conn bug
            logger.warning("handle_request failed for %s %s",
                           method, conn.path, exc_info=True)
            err = ('{"error": %s}'
                   % _json_str(str(e) or "internal error")).encode()
            self._deliver(conn, gen,
                          build_head(500, len(err), close=not ka),
                          err, not ka)
            return
        if not handled:
            nf = b'{"error": "not found"}'
            self._deliver(conn, gen,
                          build_head(404, len(nf), close=not ka),
                          nf, not ka)

    # -- reject / reply / write ---------------------------------------------

    def _reject(self, conn: _Conn, status: int, body: bytes) -> None:
        """Protocol-error reply: always ``Connection: close`` (the
        framing is broken; resynchronizing the stream is hopeless)."""
        conn.state = _CLOSING
        conn.gen += 1
        conn.buf.clear()
        head = build_head(status, len(body), close=True)
        self._write(conn, head, body, close_after=True)

    def _deliver(self, conn: _Conn, gen: int, head: bytes, body: bytes,
                 close_after: bool) -> None:
        """Deliver a reply IF its request is still current (generation
        match): a reply racing the timeout sweep or a closed socket is
        dropped here, never written to the wrong request."""
        if conn.fd not in self.conns or conn.gen != gen \
                or conn.state != _AWAIT:
            return
        conn.gen += 1
        conn.state = _CLOSING if close_after else _HEAD
        # the slow-loris reap clock restarts here: any bytes of the
        # NEXT request that arrived while this one was in flight must
        # be aged from this reply, not from the previous request's
        # first byte
        conn.t_req_start = time.monotonic()
        self._write(conn, head, body, close_after)
        if conn.fd in self.conns and conn.state == _HEAD \
                and not conn.out:
            conn.t_last = time.monotonic()
            self._advance(conn)       # serve pipelined follow-ups

    def _drain_replies(self) -> None:
        while True:
            try:
                item = self._replies.popleft()
            except IndexError:
                return
            if item[0] is _STREAM_TAG:
                _, conn, gen, data, end = item
                self._deliver_stream(conn, gen, data, end)
            else:
                self._deliver(*item)

    def _write(self, conn: _Conn, head: bytes, body: bytes,
               close_after: bool) -> None:
        if conn.out:
            conn.out += head
            conn.out += body
        else:
            try:
                if conn.tls:
                    # SSL sockets have no sendmsg (each write becomes
                    # one TLS record anyway): one concatenated send
                    rest = head + body if body else head
                    n = conn.sock.send(rest)
                else:
                    # the vectored single-syscall reply: status+headers
                    # and body leave in one sendmsg, no concat copy
                    n = conn.sock.sendmsg(
                        (head, body) if body else (head,))
            except (BlockingIOError, InterruptedError):
                n = 0
            except _TLS_WANT_READ + _TLS_WANT_WRITE:
                n = 0
            except OSError:
                self._close(conn)
                return
            total = len(head) + len(body)
            if n >= total:
                if close_after:
                    self._close(conn)
                return
            rest = head + body
            conn.out += rest[n:]      # rare: kernel buffer full
        self._want_write(conn, True)

    def _on_writable(self, conn: _Conn) -> None:
        if conn.state == _TLS_HS:
            self._tls_handshake(conn)
            return
        if conn.out:
            try:
                n = conn.sock.send(conn.out)
            except (BlockingIOError, InterruptedError):
                return
            except _TLS_WANT_READ + _TLS_WANT_WRITE:
                return
            except OSError:
                self._close(conn)
                return
            del conn.out[:n]
        if not conn.out:
            self._want_write(conn, False)
            if conn.state == _CLOSING:
                self._close(conn)
            elif conn.state == _HEAD:
                self._advance(conn)

    def _want_write(self, conn: _Conn, want: bool) -> None:
        if conn.want_write == want or conn.fd not in self.conns:
            return
        conn.want_write = want
        ev = selectors.EVENT_READ | (selectors.EVENT_WRITE if want else 0)
        try:
            self.sel.modify(conn.sock, ev, conn)
        except (KeyError, ValueError, OSError):
            pass

    def _close(self, conn: _Conn) -> None:
        if self.conns.pop(conn.fd, None) is None:
            return
        self._deferred.pop(conn.fd, None)
        self.frontend._ip_release(conn.peer_ip)
        if conn.stream is not None:
            # mid-stream disconnect: flag the producer (the decode
            # scheduler polls this and cancels the request — no slot
            # or page may outlive its audience)
            conn.stream.closed = True
            conn.stream = None
        conn.gen += 1                 # outstanding replies become stale
        conn.state = _CLOSING
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    # -- sweeps --------------------------------------------------------------

    def _sweep(self, now: float) -> None:
        fe = self.frontend
        idle = fe.idle_timeout
        rt = fe.request_timeout
        doomed: List[_Conn] = []
        timed_out: List[_Conn] = []
        stalled: List[_Conn] = []
        for conn in self.conns.values():
            if conn.state == _AWAIT:
                if rt and rt > 0 and now - conn.t_await > rt:
                    timed_out.append(conn)
                continue
            if conn.state == _STREAM:
                # a wedged producer (hung device, dead scheduler)
                # must not park streaming clients forever: no event
                # within the stuck-batch budget drops the connection
                # (the 200 head is already out — there is no 504 to
                # send; closing flags the producer via the handle)
                if rt and rt > 0 and now - conn.t_await > rt:
                    stalled.append(conn)
                continue
            if conn.state == _TLS_HS:
                # a peer parked mid-handshake (connected then silent,
                # or trickling handshake bytes) is the TLS slow-loris:
                # reaped on the handshake's age, like a mid-request
                # stall
                if idle and idle > 0 and \
                        now - conn.t_req_start > idle:
                    doomed.append(conn)
                continue
            if idle and idle > 0 and conn.state in (_HEAD, _BODY):
                if conn.buf or conn.state == _BODY:
                    # mid-request stall: the slow-loris shape — bytes
                    # dribbling in keep t_last fresh, so the reap clock
                    # is the REQUEST's age, not the socket's idleness
                    if now - conn.t_req_start > idle:
                        doomed.append(conn)
                elif now - conn.t_last > idle:
                    doomed.append(conn)
        for conn in doomed:
            fe.n_idle_reaped += 1
            self._close(conn)
        for conn in stalled:
            fe.n_request_timeouts += 1
            self._close(conn)
        for conn in timed_out:
            # same contract as the threaded frontend's Event.wait
            # expiry: 504 now, drop the late real reply by generation
            gen = conn.gen
            body = fe.request_timeout_body
            self._deliver(conn, gen,
                          build_head(504, len(body),
                                     close=not conn.keep_alive),
                          body, not conn.keep_alive)
            fe.n_request_timeouts += 1

    # -- shutdown ------------------------------------------------------------

    def _shutdown(self) -> None:
        self._close_listener()
        # deliver any replies already posted (the pipeline quiesced
        # before stop; what is queued now is all there will ever be),
        # then give pending writes a short bounded flush
        self._drain_replies()
        deadline = time.monotonic() + 0.5
        while time.monotonic() < deadline and any(
                c.out for c in self.conns.values()):
            events = self.sel.select(timeout=0.05)
            for key, mask in events:
                if isinstance(key.data, _Conn) and \
                        mask & selectors.EVENT_WRITE:
                    self._on_writable(key.data)
        for conn in list(self.conns.values()):
            self._close(conn)
        try:
            self.sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass


def _json_str(s: str) -> str:
    import json
    return json.dumps(s)


# ---------------------------------------------------------------------------
# The frontend
# ---------------------------------------------------------------------------

class EventLoopFrontend:
    """N accept/event loops sharing one port, speaking the
    ``handle_request`` protocol to an application (see module doc)."""

    def __init__(self, app, host: str = "127.0.0.1", port: int = 0, *,
                 acceptors: int = 1, reuse_port: bool = False,
                 idle_timeout: float = 0.0,
                 request_timeout: Optional[float] = None,
                 request_timeout_body: bytes =
                 b'{"error": "inference timed out"}',
                 max_header_bytes: int = 16384,
                 max_body_bytes: int = 64 << 20,
                 backlog: int = 1024,
                 max_conns_per_ip: int = 0,
                 max_pipelined_per_iter: int = 16,
                 max_stream_buffer_bytes: int = 256 << 10,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 ssl_context=None,
                 registry=None, name: str = "serving"):
        self.app = app
        self.name = name
        # -- TLS termination (docs/serving.md "TLS at the edge"):
        # pass a ready ssl.SSLContext, or a cert/key pair to build the
        # server-default one. The handshake is a first-class state of
        # the connection machine (non-blocking, WantRead/WantWrite
        # re-registration), so the encrypted edge keeps every event-
        # loop property — keep-alive, pipelining, streaming, sweeps —
        # without a fronting proxy.
        if ssl_context is not None and (tls_cert or tls_key):
            raise ValueError("pass ssl_context OR tls_cert/tls_key, "
                             "not both")
        if tls_cert or tls_key:
            if ssl is None:
                raise ValueError("this interpreter lacks the ssl "
                                 "module; TLS termination unavailable")
            if not (tls_cert and tls_key):
                raise ValueError("TLS needs BOTH tls_cert and tls_key")
            ssl_context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_context.load_cert_chain(tls_cert, tls_key)
        self.ssl_context = ssl_context
        self.n_tls_handshakes = 0
        self.n_tls_handshake_failures = 0
        self.idle_timeout = float(idle_timeout or 0.0)
        self.request_timeout = request_timeout
        self.request_timeout_body = request_timeout_body
        self.max_header_bytes = int(max_header_bytes)
        self.max_body_bytes = int(max_body_bytes)
        self.acceptors = max(int(acceptors), 1)
        self.backlog = max(int(backlog), 1)
        self.reuse_port = bool(reuse_port)
        # -- per-IP connection cap: a shedding layer IN FRONT of the
        # application's max_queue — beyond this many concurrent
        # connections from one peer address, further accepts get an
        # immediate 429 + close. 0 disables. Tracked frontend-wide
        # (one peer's connections spread across every acceptor loop).
        self.max_conns_per_ip = int(max_conns_per_ip)
        self._ip_lock = threading.Lock()
        self._conns_per_ip: Dict[str, int] = {}
        self.per_ip_high_water = 0
        self.n_per_ip_rejected = 0
        self.n_per_ip_underflow = 0
        # -- HTTP/1.1 pipelining fairness: at most this many buffered
        # requests served per connection per _advance pass; the rest
        # are deferred to the next loop iteration so one flooding
        # pipelined connection cannot monopolize a loop. <= 0 disables.
        self.max_pipelined_per_iter = int(max_pipelined_per_iter)
        self.n_pipelining_deferred = 0
        # -- token streaming: a streamed response may only buffer this
        # many unwritten bytes per connection (slow consumer) before
        # the frontend drops the connection and flags the producer
        self.max_stream_buffer_bytes = int(max_stream_buffer_bytes)
        self.n_streams = 0
        self.n_stream_events = 0
        self.n_stream_overflows = 0
        if self.acceptors > 1 and not self.reuse_port:
            # N loops cannot share ONE listening socket without the
            # thundering-herd accept races SO_REUSEPORT exists to fix
            raise ValueError("acceptors > 1 requires reuse_port=True")
        # frontend counters: plain ints bumped from loop threads (int
        # += is tear-free under the GIL; exactness beyond that is not
        # worth a lock on the accept path), exposed via set_function
        # views exactly like the server's own counters
        self.n_connections = 0
        self.n_requests = 0
        self.n_keepalive_reuses = 0
        self.n_idle_reaped = 0
        self.n_parse_errors = 0
        self.n_request_timeouts = 0
        # batched reply flushing (the commit path's batched_replies
        # scope): flushes = one-wake loop passes, batched = replies
        # they carried (batched/flushes = coalescing factor)
        self.n_reply_flushes = 0
        self.n_batched_replies = 0
        self._listeners: List[socket.socket] = []
        first = self._bind(host, port)
        self.host, self.port = first.getsockname()[:2]
        self._listeners.append(first)
        for _ in range(self.acceptors - 1):
            self._listeners.append(self._bind(self.host, self.port))
        self._loops = [_Loop(self, i, lst)
                       for i, lst in enumerate(self._listeners)]
        if registry is not None:
            self._register_metrics(registry)

    # -- per-IP accounting (accept path; lock-guarded, accepts are
    # orders of magnitude rarer than requests) ------------------------------

    def _ip_acquire(self, ip: str) -> bool:
        """Admit a new connection from ``ip``; False = over the cap."""
        if self.max_conns_per_ip <= 0 or not ip:
            return True
        with self._ip_lock:
            n = self._conns_per_ip.get(ip, 0)
            if n >= self.max_conns_per_ip:
                return False
            self._conns_per_ip[ip] = n + 1
            if n + 1 > self.per_ip_high_water:
                self.per_ip_high_water = n + 1
            return True

    def _ip_release(self, ip: str) -> None:
        if self.max_conns_per_ip <= 0 or not ip:
            return
        with self._ip_lock:
            n = self._conns_per_ip.get(ip, 0) - 1
            if n < 0:
                # a release with no matching acquire: clamped, counted
                # — the leak-check test asserts this stays 0 (every
                # teardown path funnels through _Loop._close exactly
                # once; its conns-dict pop guards the double call)
                self.n_per_ip_underflow += 1
            elif n == 0:
                self._conns_per_ip.pop(ip, None)
            else:
                self._conns_per_ip[ip] = n

    def _bind(self, host: str, port: int) -> socket.socket:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if self.reuse_port:
                if not hasattr(socket, "SO_REUSEPORT"):
                    raise OSError(
                        errno.ENOPROTOOPT,
                        "SO_REUSEPORT unavailable on this platform")
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            s.bind((host, port))
            s.listen(self.backlog)
            s.setblocking(False)
        except BaseException:
            s.close()
            raise
        return s

    def _register_metrics(self, registry) -> None:
        registry.gauge(
            "serving_open_connections",
            "Sockets currently registered with the frontend loops "
            "(keep-alive connections park here between requests)."
        ).set_function(lambda: sum(len(lp.conns) for lp in self._loops))
        registry.gauge(
            "serving_accept_loop_busy_ratio",
            "Fraction of wall time the busiest accept loop spent "
            "processing events (1.0 = the socket edge is saturated; "
            "add SO_REUSEPORT acceptors)."
        ).set_function(
            lambda: max((lp.busy_ratio for lp in self._loops),
                        default=0.0))
        for mname, help_, attr in (
            ("serving_connections_total",
             "Connections accepted by the event-loop frontend.",
             "n_connections"),
            ("serving_frontend_requests_total",
             "Requests framed by the event-loop frontend (all routes).",
             "n_requests"),
            ("serving_keepalive_reuses_total",
             "Requests served on an already-used connection (reuse "
             "rate = reuses / frontend requests).", "n_keepalive_reuses"),
            ("serving_idle_reaped_total",
             "Connections closed by the idle/slow-loris sweep.",
             "n_idle_reaped"),
            ("serving_parse_errors_total",
             "Requests rejected at the framing layer (400/411/413/"
             "431/501).", "n_parse_errors"),
            ("serving_request_timeouts_total",
             "In-flight requests 504ed by the request-timeout sweep.",
             "n_request_timeouts"),
            ("serving_pipelining_deferred_total",
             "Times a connection's buffered pipelined requests were "
             "deferred to the next loop iteration by the fairness cap "
             "(max_pipelined_per_iter).", "n_pipelining_deferred"),
            ("serving_per_ip_rejected_total",
             "Connections refused at accept by the per-IP cap "
             "(429 + close before any queue slot was spent).",
             "n_per_ip_rejected"),
            ("serving_reply_flush_batches_total",
             "Batched reply flushes (one deque extend + one wake per "
             "loop per commit batch).", "n_reply_flushes"),
            ("serving_batched_replies_total",
             "Replies delivered through batched flushes (ratio to "
             "flush batches = coalescing factor).",
             "n_batched_replies"),
            ("serving_streams_total",
             "Requests upgraded to incremental chunked-SSE delivery "
             "(token streaming).", "n_streams"),
            ("serving_stream_events_total",
             "SSE events written to streamed responses (terminal "
             "events included).", "n_stream_events"),
            ("serving_stream_overflows_total",
             "Streamed connections dropped because the bounded "
             "per-connection write buffer overflowed (slow consumer).",
             "n_stream_overflows"),
            ("serving_tls_handshakes_total",
             "TLS handshakes completed by the event-loop edge "
             "(connections that reached the HTTP state).",
             "n_tls_handshakes"),
            ("serving_tls_handshake_failures_total",
             "TLS handshakes that failed (plaintext bytes on the TLS "
             "port, protocol mismatch, mid-handshake disconnect) — "
             "each closed cleanly.", "n_tls_handshake_failures"),
        ):
            registry.counter(mname, help_).set_function(
                lambda a=attr: getattr(self, a))
        registry.gauge(
            "serving_per_ip_conns_high_water",
            "Highest concurrent-connection count any single peer "
            "address has reached (0 when the per-IP cap is off)."
        ).set_function(lambda: self.per_ip_high_water)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "EventLoopFrontend":
        # idempotent: ServingServer.start() may run twice (helper +
        # context-manager __enter__ is a common test shape) and the
        # loops are long-lived Thread objects, not per-call ones
        for lp in self._loops:
            if not lp._started.is_set():
                lp.start()
        return self

    def pause_accept(self) -> None:
        """Stop accepting new connections; established connections keep
        being served. Part of graceful drain: readiness flips first,
        then the listeners go away, then in-flight work finishes."""
        for lp in self._loops:
            lp.pause_accept()

    def stop(self) -> None:
        """Stop the loops. Call only after the application has quiesced
        (every ``reply`` that will ever fire has fired): each loop
        delivers already-posted replies, briefly flushes pending
        writes, then closes everything."""
        for lp in self._loops:
            lp.request_stop()
        for lp in self._loops:
            if lp.is_alive():
                lp.join(timeout=5)
        for lst in self._listeners:
            try:
                lst.close()
            except OSError:
                pass

    # -- observability -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        reqs = self.n_requests
        return {
            "kind": "eventloop",
            "acceptors": self.acceptors,
            "reuse_port": self.reuse_port,
            "tls": self.ssl_context is not None,
            "tls_handshakes_total": self.n_tls_handshakes,
            "tls_handshake_failures_total":
                self.n_tls_handshake_failures,
            "open_connections": sum(len(lp.conns) for lp in self._loops),
            "connections_total": self.n_connections,
            "requests_total": reqs,
            "keepalive_reuses_total": self.n_keepalive_reuses,
            "keepalive_reuse_rate": round(
                self.n_keepalive_reuses / reqs, 4) if reqs else 0.0,
            "idle_reaped_total": self.n_idle_reaped,
            "parse_errors_total": self.n_parse_errors,
            "request_timeouts_total": self.n_request_timeouts,
            "pipelining_deferred_total": self.n_pipelining_deferred,
            "per_ip_rejected_total": self.n_per_ip_rejected,
            "per_ip_conns_high_water": self.per_ip_high_water,
            # live per-IP ledger: tracked addresses and release-
            # without-acquire underflows — the leak-check test's
            # public surface (0 tracked and 0 underflows at idle)
            "per_ip_tracked": len(self._conns_per_ip),
            "per_ip_underflow_total": self.n_per_ip_underflow,
            "reply_flush_batches_total": self.n_reply_flushes,
            "batched_replies_total": self.n_batched_replies,
            "streams_total": self.n_streams,
            "stream_events_total": self.n_stream_events,
            "stream_overflows_total": self.n_stream_overflows,
            "busy_ratio": round(max(
                (lp.busy_ratio for lp in self._loops), default=0.0), 4),
        }
