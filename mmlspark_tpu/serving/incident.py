"""Anomaly-triggered incident capture: the second half of the
postmortem plane.

PRs 18-19 gave the worker *detectors* — SLO burn-rate alerting
(serving/slo.py) and TSDB anomaly detection (core/tsdb.py) — whose
transitions already ride one notifier channel. This module subscribes
an :class:`IncidentManager` to that channel: on every ``pending ->
firing`` transition it snapshots a correlated evidence bundle to
``incidents/<id>/`` while the evidence still exists — the slow traces
before the flight recorder rotates them out, the CPU profile window
*around* the firing instant (the always-on sampler in
core/profiler.py means the history is already in memory), the violated
series, the log ring, and the worker's stats surfaces.

Capture correctness rules:

* **Never on the hot path.** :meth:`IncidentManager.notify` is called
  under the SLO engine's / anomaly detector's evaluation locks; it only
  enqueues (bounded queue, drops + counts when full) — all file I/O,
  range queries and trace serialization happen on one dedicated
  ``incident-capture`` daemon thread.
* **No races with the finishing alert.** Capture works exclusively
  from the *transition event payload* (an immutable snapshot taken at
  fire time) plus point-in-time snapshots of the trace store / TSDB /
  log ring taken at capture start — it never reads live alert-state
  machines, so an alert that resolves mid-capture cannot corrupt the
  bundle.
* **Detectably complete.** ``manifest.json`` — the trigger plus a
  SHA-256 digest of every artifact — is written LAST (tmp + rename,
  the PR-7 checkpoint idiom): a bundle interrupted by a crash has no
  manifest and surfaces as ``complete: false``.
* **Bounded.** One bundle per alert per ``cooldown_s`` (suppressed
  captures are counted, not queued), and at most ``max_incidents``
  bundles on disk (oldest evicted after each capture).

Read side: ``GET /incidents`` (list), ``GET /incidents/<id>``
(manifest + file inventory), ``GET /incidents/<id>/<artifact>`` (raw
file) on every worker — both frontends, same route table — and
coordinator ``GET /fleet/incidents`` fan-out with worker attribution
(dead workers degrade to an errors entry). ``tools/trace_dump.py
--incidents [--fetch <id>]`` is the terminal client.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from mmlspark_tpu.core.logs import get_logger
from mmlspark_tpu.core.resilience import Clock, SYSTEM_CLOCK

logger = get_logger("serving.incident")

#: artifact filenames a bundle may contain (also the route whitelist
#: for ``GET /incidents/<id>/<artifact>`` — nothing outside this set is
#: ever served, so the path segment cannot traverse).
BUNDLE_FILES = ("alert.json", "series.json", "traces.json",
                "logs.json", "stats.json", "profile.collapsed",
                "profile.trace.json", "profile.json", "manifest.json")


def _slug(name: str, max_len: int = 48) -> str:
    out = "".join(c if (c.isalnum() or c in "-_") else "-"
                  for c in str(name))
    return (out or "alert")[:max_len]


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(65536), b""):
            h.update(chunk)
    return h.hexdigest()


class FanoutNotifier:
    """Deliver each alert transition to several sinks (the webhook
    :class:`~mmlspark_tpu.serving.slo.AlertNotifier` and the
    :class:`IncidentManager`). One sink raising never starves another;
    ``status()`` merges the children so ``GET /slo`` keeps working."""

    def __init__(self, *sinks: Any):
        self.sinks = [s for s in sinks if s is not None]

    def notify(self, event: Dict[str, Any]) -> None:
        for sink in self.sinks:
            try:
                sink.notify(event)
            except Exception:
                logger.exception("alert sink %r failed",
                                 type(sink).__name__)

    def status(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"sinks": len(self.sinks)}
        for sink in self.sinks:
            st = getattr(sink, "status", None)
            if callable(st):
                try:
                    out[type(sink).__name__] = st()
                except Exception:
                    pass
        return out


class IncidentManager:
    """Capture one evidence bundle per firing alert, bounded and
    rate-limited. Dependencies are injected (store / tracer / profiler
    / log ring / stats callback) so tests exercise the capture path
    without a server."""

    def __init__(self, base_dir: str, *,
                 tsdb: Any = None,
                 tracer: Any = None,
                 profiler: Any = None,
                 log_ring: Any = None,
                 stats_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 related_exprs: Sequence[str] = (),
                 cooldown_s: float = 300.0,
                 max_incidents: int = 16,
                 profile_pre_s: float = 60.0,
                 profile_post_s: float = 30.0,
                 lookback_s: float = 600.0,
                 series_step_s: float = 10.0,
                 max_traces: int = 8,
                 queue_cap: int = 64,
                 clock: Clock = SYSTEM_CLOCK):
        self.base_dir = str(base_dir)
        self.tsdb = tsdb
        self.tracer = tracer
        self.profiler = profiler
        self.log_ring = log_ring
        self.stats_fn = stats_fn
        self.related_exprs = list(related_exprs)
        self.cooldown_s = float(cooldown_s)
        self.max_incidents = int(max_incidents)
        self.profile_pre_s = float(profile_pre_s)
        self.profile_post_s = float(profile_post_s)
        self.lookback_s = float(lookback_s)
        self.series_step_s = float(series_step_s)
        self.max_traces = int(max_traces)
        self.clock = clock
        self._queue: "queue.Queue" = queue.Queue(maxsize=int(queue_cap))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._busy = False
        self._lock = threading.Lock()
        self._last_capture: Dict[str, float] = {}  # policy -> mono ts
        self._seq = 0
        self._recent: List[Dict[str, Any]] = []    # last transitions
        self.n_captured = 0
        self.n_suppressed = 0
        self.n_dropped = 0
        self.n_evicted = 0
        self.n_failed = 0
        self.last_id: Optional[str] = None
        os.makedirs(self.base_dir, exist_ok=True)

    # -- the notifier-channel contract --------------------------------

    def notify(self, event: Dict[str, Any]) -> None:
        """Alert-transition sink. Called under the emitting engine's
        evaluation lock — MUST NOT block: firing transitions are
        enqueued for the capture thread, resolved transitions only
        update the recent-transitions log."""
        with self._lock:
            self._recent.append({k: event.get(k) for k in
                                 ("type", "policy", "slo_kind",
                                  "at_unix")})
            del self._recent[:-32]
        if event.get("type") != "firing":
            return
        try:
            self._queue.put_nowait(dict(event))
        except queue.Full:
            self.n_dropped += 1

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="incident-capture")
        self._thread.start()

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        try:
            self._queue.put_nowait(None)       # wake the worker
        except queue.Full:
            pass
        t.join(timeout=10.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                event = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            if event is None:
                continue
            self._busy = True
            try:
                self.capture(event)
            except Exception:
                self.n_failed += 1
                logger.exception("incident capture failed")
            finally:
                self._busy = False

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block (REAL time) until the queue is drained and no capture
        is in flight — test/drill synchronization, not a prod API."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.empty() and not self._busy:
                return True
            time.sleep(0.005)
        return False

    # -- capture ------------------------------------------------------

    def capture(self, event: Dict[str, Any]) -> Optional[str]:
        """Capture one bundle for a firing transition; returns the
        incident id, or None when suppressed by the cooldown. Runs on
        the capture thread (or synchronously from tests)."""
        policy = str(event.get("policy", "unknown"))
        now = self.clock.now()
        last = self._last_capture.get(policy)
        if last is not None and (now - last) < self.cooldown_s:
            self.n_suppressed += 1
            return None
        # stamp BEFORE the (slow) capture so a burst of transitions
        # inside one cooldown window cannot double-capture
        self._last_capture[policy] = now

        at_mono = float(event.get("at_mono", now))
        at_unix = float(event.get("at_unix", time.time()))
        self._seq += 1
        inc_id = (f"inc-{int(at_unix * 1000):013d}-{self._seq:03d}-"
                  f"{_slug(policy)}")
        inc_dir = os.path.join(self.base_dir, inc_id)
        os.makedirs(inc_dir, exist_ok=True)

        def _write_json(name: str, payload: Any) -> None:
            with open(os.path.join(inc_dir, name), "w") as f:
                json.dump(payload, f, indent=1, default=str)

        # 1) immediate evidence — snapshot while it still exists
        _write_json("alert.json", event)
        _write_json("series.json", self._capture_series(event, at_mono))
        _write_json("traces.json", self._capture_traces())
        _write_json("logs.json", self._capture_logs())
        _write_json("stats.json", self._capture_stats())
        # 2) the profile window [firing - pre, firing + post]: wait for
        # the post-window to elapse so the bundle shows the regression
        # *in progress*, then dump
        self._wait_until(at_mono + self.profile_post_s)
        self._capture_profile(inc_dir, at_mono)
        # 3) manifest LAST — digests over everything above; a bundle
        # without one is detectably incomplete
        files: Dict[str, Dict[str, Any]] = {}
        for name in sorted(os.listdir(inc_dir)):
            path = os.path.join(inc_dir, name)
            if name == "manifest.json" or not os.path.isfile(path):
                continue
            files[name] = {"sha256": _sha256(path),
                           "bytes": os.path.getsize(path)}
        manifest = {
            "id": inc_id,
            "trigger": {k: event.get(k) for k in
                        ("type", "policy", "slo_kind", "objective",
                         "expr", "value", "z", "direction", "at_unix",
                         "at_mono") if k in event},
            "at_unix": at_unix,
            "at_mono": at_mono,
            "profile_window": {"start": at_mono - self.profile_pre_s,
                               "end": at_mono + self.profile_post_s},
            "files": files,
            "complete": True,
        }
        tmp = os.path.join(inc_dir, ".manifest.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, default=str)
        os.replace(tmp, os.path.join(inc_dir, "manifest.json"))
        self.n_captured += 1
        self.last_id = inc_id
        logger.warning("incident %s captured (policy=%s, %d files)",
                       inc_id, policy, len(files))
        self._evict()
        return inc_id

    def _wait_until(self, t: float) -> None:
        """Wait (stoppably) until the injected clock reaches ``t`` —
        polls so a ManualClock advanced by a test thread releases it."""
        while not self._stop.is_set() and self.clock.now() < t:
            self._stop.wait(0.005)

    def _capture_series(self, event: Dict[str, Any],
                        at_mono: float) -> Dict[str, Any]:
        if self.tsdb is None:
            return {"series": {}, "note": "no tsdb configured"}
        exprs = list(self.related_exprs)
        own = event.get("expr")
        if own and own not in exprs:
            exprs.append(own)
        out: Dict[str, Any] = {}
        # clamp: a small monotonic timestamp (ManualClock starting at
        # 0) must not go negative — query_range reads negative start
        # as end-relative
        start = max(0.0, at_mono - self.lookback_s)
        for expr in exprs:
            try:
                out[expr] = self.tsdb.query_range(
                    expr, start=start, end=None,
                    step=self.series_step_s)
            except Exception as exc:
                out[expr] = {"error": str(exc)}
        return {"lookback_s": self.lookback_s, "series": out}

    def _capture_traces(self) -> Dict[str, Any]:
        if self.tracer is None:
            return {"traces": []}
        from mmlspark_tpu.core.tracing import to_perfetto
        summaries = self.tracer.traces(slow_only=False)
        # errors first, then slowest — the traces an operator opens
        summaries.sort(key=lambda s: (s.get("status") == "ok",
                                      -float(s.get("duration_ms", 0))))
        picked = summaries[:self.max_traces]
        out = []
        for s in picked:
            entry: Dict[str, Any] = {"summary": s}
            raw = self.tracer.get_trace(s.get("trace_id"))
            if raw is not None:
                try:
                    entry["perfetto"] = to_perfetto(raw)
                except Exception as exc:
                    entry["perfetto_error"] = str(exc)
            out.append(entry)
        return {"retained": len(summaries), "traces": out}

    def _capture_logs(self) -> Dict[str, Any]:
        if self.log_ring is None:
            return {"records": []}
        return {"status": self.log_ring.status(),
                "records": self.log_ring.records()}

    def _capture_stats(self) -> Dict[str, Any]:
        if self.stats_fn is None:
            return {}
        try:
            return self.stats_fn()
        except Exception as exc:
            return {"error": str(exc)}

    def _capture_profile(self, inc_dir: str, at_mono: float) -> None:
        if self.profiler is None:
            return
        t0 = at_mono - self.profile_pre_s
        t1 = at_mono + self.profile_post_s
        counts = self.profiler.collapsed_between(t0, t1)
        lines = [f"{stack} {n}" for stack, n in
                 sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))]
        with open(os.path.join(inc_dir, "profile.collapsed"), "w") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
        with open(os.path.join(inc_dir, "profile.trace.json"),
                  "w") as f:
            json.dump(self.profiler.chrome_trace_between(t0, t1), f)
        with open(os.path.join(inc_dir, "profile.json"), "w") as f:
            json.dump(self.profiler.profile_between(t0, t1), f,
                      indent=1)

    def _evict(self) -> None:
        """Drop the oldest bundles beyond ``max_incidents`` (ids are
        unix-millisecond-prefixed, so name order is capture order)."""
        try:
            dirs = sorted(d for d in os.listdir(self.base_dir)
                          if os.path.isdir(
                              os.path.join(self.base_dir, d)))
        except OSError:
            return
        while len(dirs) > self.max_incidents:
            victim = dirs.pop(0)
            shutil.rmtree(os.path.join(self.base_dir, victim),
                          ignore_errors=True)
            self.n_evicted += 1

    # -- read side ----------------------------------------------------

    def list(self) -> List[Dict[str, Any]]:
        """Bundle inventory, newest first. A bundle without a manifest
        (capture in flight, or interrupted) reports
        ``complete: false``."""
        out: List[Dict[str, Any]] = []
        try:
            dirs = sorted((d for d in os.listdir(self.base_dir)
                           if os.path.isdir(
                               os.path.join(self.base_dir, d))),
                          reverse=True)
        except OSError:
            return out
        for d in dirs:
            manifest = self._read_manifest(d)
            if manifest is None:
                out.append({"id": d, "complete": False})
                continue
            files = manifest.get("files", {})
            out.append({
                "id": d,
                "policy": manifest.get("trigger", {}).get("policy"),
                "slo_kind": manifest.get("trigger", {}).get("slo_kind"),
                "at_unix": manifest.get("at_unix"),
                "complete": bool(manifest.get("complete")),
                "n_files": len(files),
                "bytes": sum(int(v.get("bytes", 0))
                             for v in files.values()),
            })
        return out

    def get(self, inc_id: str) -> Optional[Dict[str, Any]]:
        """Manifest + on-disk file inventory for one bundle, or None
        for an unknown / path-hostile id."""
        inc_dir = self._safe_dir(inc_id)
        if inc_dir is None:
            return None
        manifest = self._read_manifest(inc_id)
        present = sorted(f for f in os.listdir(inc_dir)
                         if os.path.isfile(os.path.join(inc_dir, f))
                         and not f.startswith("."))
        return {"id": inc_id,
                "complete": bool(manifest and manifest.get("complete")),
                "manifest": manifest, "present": present}

    def artifact(self, inc_id: str, name: str
                 ) -> Optional[Dict[str, Any]]:
        """One raw bundle file (whitelisted names only); ``None`` when
        the bundle or artifact doesn't exist."""
        if name not in BUNDLE_FILES:
            return None
        inc_dir = self._safe_dir(inc_id)
        if inc_dir is None:
            return None
        path = os.path.join(inc_dir, name)
        if not os.path.isfile(path):
            return None
        with open(path, "rb") as f:
            body = f.read()
        ctype = ("application/json" if name.endswith(".json")
                 else "text/plain; charset=utf-8")
        return {"body": body, "content_type": ctype}

    def _safe_dir(self, inc_id: str) -> Optional[str]:
        if (not inc_id or "/" in inc_id or "\\" in inc_id
                or inc_id.startswith(".")):
            return None
        inc_dir = os.path.join(self.base_dir, inc_id)
        return inc_dir if os.path.isdir(inc_dir) else None

    def _read_manifest(self, inc_id: str) -> Optional[Dict[str, Any]]:
        path = os.path.join(self.base_dir, inc_id, "manifest.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def status(self) -> Dict[str, Any]:
        with self._lock:
            recent = list(self._recent[-8:])
        return {
            "dir": self.base_dir,
            "running": self._thread is not None,
            "captured": self.n_captured,
            "suppressed_cooldown": self.n_suppressed,
            "dropped_queue_full": self.n_dropped,
            "evicted": self.n_evicted,
            "failed": self.n_failed,
            "cooldown_s": self.cooldown_s,
            "max_incidents": self.max_incidents,
            "last_id": self.last_id,
            "recent_transitions": recent,
        }
