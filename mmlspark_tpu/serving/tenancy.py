"""Tenant isolation & overload control for the serving plane.

Every request that reaches a :class:`~mmlspark_tpu.serving.server.
ServingServer` carries (or fails to carry) an API key; this module
turns that key into a *tenant* with a priority class, rate and
concurrency quotas, a fair-share weight, and a prefix-cache page
budget — so one tenant's flood degrades only that tenant's
throughput, never the fleet's.

The subsystem is deliberately host-side-only bookkeeping: admission,
shedding, and fair-share ordering all happen before a request joins a
batch, so tenancy never changes dispatch shapes and stays off the
compiled path (the ``tenant_isolation_v1`` bench gate asserts zero
post-warmup recompiles with tenancy enabled).

Pieces
------
``extract_api_key``
    ``X-Api-Key`` header, else ``Authorization: Bearer <token>`` —
    identical on both frontends (the threaded ``http.server`` handler
    and the event-loop edge both expose case-insensitive ``.get``).
``Tenant`` / ``TenantRegistry``
    Static key → tenant mapping, loadable from JSON (inline dict, file
    path, or the ``MMLSPARK_TENANTS`` env var) with an
    ``unknown_key_policy`` of ``"reject"`` (401 on missing/unknown
    keys) or ``"anonymous"`` (map them to the anonymous tenant).
``TokenBucket``
    Injectable-clock token bucket; ``retry_after()`` computes the
    HONEST wait until the next token from refill math, which is what
    quota 429s carry instead of the fixed ``shed_retry_after``.
``FairCycle``
    Deficit-weighted round-robin chooser used for both decode slot
    claims and collector batch assembly: each present tenant accrues
    its weight per round, the largest deficit wins and pays the round
    total, so any tenant with positive weight is served within a
    bounded number of rounds (the bounded-starvation proof test).
``ReleaseRateEwma``
    EWMA over decode slot-release gaps → honest ``Retry-After`` for
    decode 429s; returns ``None`` while cold or stale so callers fall
    back to the constant.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from mmlspark_tpu.core.resilience import SYSTEM_CLOCK, Clock
from mmlspark_tpu.serving.policy import PRIORITY_CLASSES, PriorityShedPolicy

ENV_VAR = "MMLSPARK_TENANTS"
ANONYMOUS_ID = "anonymous"


def extract_api_key(headers) -> Optional[str]:
    """Pull the API key out of a request's headers.

    ``X-Api-Key`` wins; otherwise an ``Authorization: Bearer <token>``
    credential is accepted. Works against anything with a
    case-insensitive ``.get`` (``email.message.Message`` on the
    threaded frontend, :class:`~mmlspark_tpu.serving.frontend.Headers`
    on the event-loop one). Returns ``None`` when no credential is
    present."""
    if headers is None:
        return None
    key = headers.get("X-Api-Key")
    if key:
        key = key.strip()
        if key:
            return key
    auth = headers.get("Authorization")
    if auth:
        parts = auth.split(None, 1)
        if len(parts) == 2 and parts[0].lower() == "bearer":
            token = parts[1].strip()
            if token:
                return token
    return None


class TokenBucket:
    """Classic token bucket with an injectable clock.

    ``rate_per_s <= 0`` (or ``None``) means unlimited — every acquire
    succeeds. ``retry_after`` answers "how long until ``n`` tokens
    exist?" from the refill math, so a 429 can carry an honest wait
    instead of a guess."""

    def __init__(self, rate_per_s: Optional[float],
                 burst: Optional[float] = None,
                 clock: Clock = SYSTEM_CLOCK):
        self.rate = float(rate_per_s) if rate_per_s else 0.0
        # default burst: one second's worth of tokens, never below 1
        self.burst = float(burst) if burst is not None \
            else max(self.rate, 1.0)
        self.clock = clock
        self._tokens = self.burst
        self._last = clock.now()
        self._lock = threading.Lock()

    @property
    def unlimited(self) -> bool:
        return self.rate <= 0.0

    def _refill_locked(self, now: float) -> None:
        dt = now - self._last
        self._last = now
        if dt > 0:
            self._tokens = min(self.burst, self._tokens + dt * self.rate)

    def try_acquire(self, n: float = 1.0) -> bool:
        if self.unlimited:
            return True
        with self._lock:
            self._refill_locked(self.clock.now())
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available (0.0 if they
        already are)."""
        if self.unlimited:
            return 0.0
        with self._lock:
            self._refill_locked(self.clock.now())
            short = n - self._tokens
            if short <= 0:
                return 0.0
            return short / self.rate

    @property
    def tokens(self) -> float:
        """Current level (refilled to now) — test/stats surface."""
        if self.unlimited:
            return float("inf")
        with self._lock:
            self._refill_locked(self.clock.now())
            return self._tokens


class Tenant:
    """One tenant's static contract: identity, priority class, quotas,
    fair-share weight. ``None`` quotas mean unlimited."""

    __slots__ = ("id", "priority", "api_keys", "rate_per_s", "burst",
                 "max_inflight", "max_cache_pages", "weight")

    def __init__(self, id: str, priority: str = "interactive",
                 api_keys: Sequence[str] = (),
                 rate_per_s: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_inflight: Optional[int] = None,
                 max_cache_pages: Optional[int] = None,
                 weight: float = 1.0):
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority {priority!r} for tenant {id!r}; "
                f"expected one of {PRIORITY_CLASSES}")
        self.id = str(id)
        self.priority = priority
        self.api_keys = tuple(api_keys)
        self.rate_per_s = float(rate_per_s) if rate_per_s else None
        self.burst = float(burst) if burst is not None else None
        self.max_inflight = int(max_inflight) \
            if max_inflight is not None else None
        self.max_cache_pages = int(max_cache_pages) \
            if max_cache_pages is not None else None
        self.weight = max(float(weight), 0.0)

    def to_dict(self) -> Dict[str, object]:
        return {"id": self.id, "priority": self.priority,
                "rate_per_s": self.rate_per_s, "burst": self.burst,
                "max_inflight": self.max_inflight,
                "max_cache_pages": self.max_cache_pages,
                "weight": self.weight}


class TenantState:
    """Mutable runtime side of one tenant: the token bucket, the
    in-flight concurrency count (checked-and-bumped under one lock so
    N racing threads can never exceed the cap), and plain counters
    the metric views read lock-free."""

    __slots__ = ("tenant", "bucket", "lock", "inflight",
                 "inflight_high_water", "n_requests", "n_shed_rate",
                 "n_shed_concurrency", "n_shed_overload", "n_replayed",
                 "n_tokens", "n_goodput_tokens", "n_release_underflow")

    def __init__(self, tenant: Tenant, clock: Clock):
        self.tenant = tenant
        self.bucket = TokenBucket(tenant.rate_per_s, tenant.burst,
                                  clock=clock) \
            if tenant.rate_per_s else None
        self.lock = threading.Lock()
        self.inflight = 0
        self.inflight_high_water = 0
        self.n_requests = 0
        self.n_shed_rate = 0
        self.n_shed_concurrency = 0
        self.n_shed_overload = 0
        self.n_replayed = 0
        self.n_tokens = 0
        self.n_goodput_tokens = 0
        self.n_release_underflow = 0

    def stats(self) -> Dict[str, object]:
        t = self.tenant
        return {"id": t.id, "priority": t.priority,
                "weight": t.weight,
                "inflight": self.inflight,
                "inflight_high_water": self.inflight_high_water,
                "n_requests": self.n_requests,
                "n_replayed": self.n_replayed,
                "n_shed_rate": self.n_shed_rate,
                "n_shed_concurrency": self.n_shed_concurrency,
                "n_shed_overload": self.n_shed_overload,
                "n_tokens": self.n_tokens,
                "n_goodput_tokens": self.n_goodput_tokens,
                "n_release_underflow": self.n_release_underflow,
                "bucket_tokens": (round(self.bucket.tokens, 3)
                                  if self.bucket is not None else None),
                "max_inflight": t.max_inflight,
                "rate_per_s": t.rate_per_s}


class TenantRegistry:
    """Static API-key → tenant mapping plus the per-tenant runtime
    admission state.

    ``unknown_key_policy``:
      * ``"anonymous"`` (default) — requests with no key or an unknown
        key run as the anonymous tenant (its quotas still apply);
      * ``"reject"`` — they are refused at the edge with 401.

    ``high_water`` is the queue-pressure fraction where priority-aware
    shedding starts (see :class:`~mmlspark_tpu.serving.policy.
    PriorityShedPolicy`); ``fair_share`` turns deficit-weighted
    round-robin ordering of collector batches and decode slot claims
    on/off (the A/B axis of the ``tenant_isolation_v1`` bench)."""

    def __init__(self, tenants: Iterable[Tenant] = (),
                 unknown_key_policy: str = "anonymous",
                 high_water: float = 0.5,
                 fair_share: bool = True,
                 anonymous: Optional[Tenant] = None,
                 clock: Clock = SYSTEM_CLOCK,
                 label_cap: int = 32):
        if unknown_key_policy not in ("reject", "anonymous"):
            raise ValueError("unknown_key_policy must be 'reject' or "
                             f"'anonymous', got {unknown_key_policy!r}")
        self.unknown_key_policy = unknown_key_policy
        self.fair_share = bool(fair_share)
        self.shed_policy = PriorityShedPolicy(high_water=high_water)
        self.clock = clock
        self.label_cap = int(label_cap)
        self.tenants: Dict[str, Tenant] = {}
        self._keys: Dict[str, str] = {}
        self._states: Dict[str, TenantState] = {}
        self._lock = threading.Lock()
        for t in tenants:
            self._add(t)
        if ANONYMOUS_ID not in self.tenants:
            self._add(anonymous if anonymous is not None
                      else Tenant(ANONYMOUS_ID, priority="batch"))
        elif anonymous is not None:
            raise ValueError("both an 'anonymous' tenant entry and an "
                             "explicit anonymous= were given")
        self.anonymous = self.tenants[ANONYMOUS_ID]
        # bounded label cardinality for metrics: declaration order is
        # the top-K; later tenants fold into "other"
        from mmlspark_tpu.core.telemetry import BoundedLabelSet
        self._labels = BoundedLabelSet(cap=self.label_cap)
        for tid in self.tenants:
            self._labels.key(tid)

    def _add(self, t: Tenant) -> None:
        if t.id in self.tenants:
            raise ValueError(f"duplicate tenant id {t.id!r}")
        self.tenants[t.id] = t
        self._states[t.id] = TenantState(t, self.clock)
        for k in t.api_keys:
            if k in self._keys:
                raise ValueError(f"api key assigned to both "
                                 f"{self._keys[k]!r} and {t.id!r}")
            self._keys[k] = t.id

    # -- construction --------------------------------------------------------

    @classmethod
    def from_dict(cls, cfg: Dict[str, object],
                  clock: Clock = SYSTEM_CLOCK) -> "TenantRegistry":
        tenants = [Tenant(**row) for row in cfg.get("tenants", ())]
        kw = {k: cfg[k] for k in ("unknown_key_policy", "high_water",
                                  "fair_share", "label_cap") if k in cfg}
        return cls(tenants, clock=clock, **kw)

    @classmethod
    def from_json(cls, path: str,
                  clock: Clock = SYSTEM_CLOCK) -> "TenantRegistry":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_dict(json.load(f), clock=clock)

    @classmethod
    def from_env(cls, clock: Clock = SYSTEM_CLOCK
                 ) -> Optional["TenantRegistry"]:
        """Build from ``MMLSPARK_TENANTS`` — inline JSON (starts with
        ``{``) or a path to a JSON file; ``None`` when unset."""
        raw = os.environ.get(ENV_VAR, "").strip()
        if not raw:
            return None
        if raw.startswith("{"):
            return cls.from_dict(json.loads(raw), clock=clock)
        return cls.from_json(raw, clock=clock)

    @classmethod
    def from_value(cls, value, clock: Clock = SYSTEM_CLOCK
                   ) -> Optional["TenantRegistry"]:
        """Coerce a constructor argument: an existing registry, a
        config dict, a JSON file path, or ``None``."""
        if value is None:
            return None
        if isinstance(value, TenantRegistry):
            return value
        if isinstance(value, dict):
            return cls.from_dict(value, clock=clock)
        if isinstance(value, str):
            return cls.from_json(value, clock=clock)
        raise TypeError(f"tenancy= accepts TenantRegistry, dict, "
                        f"JSON path, or None — got {type(value)!r}")

    # -- identity ------------------------------------------------------------

    def resolve(self, api_key: Optional[str]) -> Optional[Tenant]:
        """Key → tenant; ``None`` means REJECT (policy is 'reject' and
        the key is missing or unknown)."""
        if api_key is not None:
            tid = self._keys.get(api_key)
            if tid is not None:
                return self.tenants[tid]
        if self.unknown_key_policy == "reject":
            return None
        return self.anonymous

    def state(self, tenant_id: str) -> TenantState:
        return self._states[tenant_id]

    def label_of(self, tenant_id: str) -> str:
        """Bounded-cardinality metric label for a tenant id (top-K by
        declaration order, then ``other``)."""
        label, _ = self._labels.key(tenant_id)
        return label

    def states_for_label(self, label: str) -> List[TenantState]:
        """Every state whose metric label is ``label`` — 1 for top-K
        tenants, the whole overflow tail for ``other``."""
        return [st for tid, st in self._states.items()
                if self.label_of(tid) == label]

    def labels(self) -> List[str]:
        """The distinct metric labels in declaration order."""
        out: List[str] = []
        for tid in self.tenants:
            lbl = self.label_of(tid)
            if lbl not in out:
                out.append(lbl)
        return out

    # -- admission -----------------------------------------------------------

    def admit(self, tenant: Tenant
              ) -> Optional[Tuple[str, Optional[float]]]:
        """Charge one request against ``tenant``'s quotas.

        Returns ``None`` on success (the in-flight slot is HELD — the
        caller must :meth:`release` exactly once when the request
        resolves), else ``(reason, retry_after)`` where reason is
        ``"rate"`` or ``"concurrency"`` and ``retry_after`` is the
        honest bucket wait (``None`` when the bucket can't say —
        concurrency sheds clear when some in-flight request finishes,
        which the caller estimates from its own release rate)."""
        st = self._states[tenant.id]
        if st.bucket is not None and not st.bucket.try_acquire():
            with st.lock:
                st.n_shed_rate += 1
            return ("rate", st.bucket.retry_after())
        with st.lock:
            if tenant.max_inflight is not None \
                    and st.inflight >= tenant.max_inflight:
                st.n_shed_concurrency += 1
                return ("concurrency", None)
            st.inflight += 1
            if st.inflight > st.inflight_high_water:
                st.inflight_high_water = st.inflight
            st.n_requests += 1
        return None

    def release(self, tenant_id: str) -> None:
        """Return an in-flight slot. Underflow (a release with no
        matching admit) is clamped and counted — the leak-check test
        asserts the counter stays 0."""
        st = self._states.get(tenant_id)
        if st is None:
            return
        with st.lock:
            if st.inflight > 0:
                st.inflight -= 1
            else:
                st.n_release_underflow += 1

    def should_shed(self, tenant: Tenant, depth: int,
                    capacity: int) -> bool:
        """Priority-aware overload verdict for queue pressure
        ``depth``/``capacity`` (only meaningful when tenancy is on;
        with ``fair_share`` off this degrades to the plain full-queue
        check for every class)."""
        if not self.fair_share:
            return capacity > 0 and depth >= capacity
        return self.shed_policy.should_shed(depth, capacity,
                                            tenant.priority)

    def note_shed_overload(self, tenant_id: str) -> None:
        st = self._states.get(tenant_id)
        if st is not None:
            with st.lock:
                st.n_shed_overload += 1

    def note_replay(self, tenant_id: str) -> None:
        st = self._states.get(tenant_id)
        if st is not None:
            with st.lock:
                st.n_replayed += 1

    def note_tokens(self, tenant_id: str, n: int) -> None:
        st = self._states.get(tenant_id)
        if st is not None:
            with st.lock:
                st.n_tokens += int(n)

    def note_goodput_tokens(self, tenant_id: str, n: int) -> None:
        """Tokens delivered by a CLEAN decode finish (eos/length) —
        the per-tenant goodput numerator; ``note_tokens`` above stays
        the all-reasons denominator."""
        st = self._states.get(tenant_id)
        if st is not None:
            with st.lock:
                st.n_goodput_tokens += int(n)

    def weight_of(self, tenant_id: str) -> float:
        t = self.tenants.get(tenant_id)
        return t.weight if t is not None else 1.0

    # -- introspection -------------------------------------------------------

    def total_inflight(self) -> int:
        return sum(st.inflight for st in self._states.values())

    def stats(self) -> Dict[str, object]:
        return {"unknown_key_policy": self.unknown_key_policy,
                "fair_share": self.fair_share,
                "high_water": self.shed_policy.high_water,
                "label_cap": self.label_cap,
                # nonzero = the metric cap is hiding tenants in the
                # "other" row (raise label_cap or prune tenants)
                "label_overflow": self._labels.n_overflowed,
                "tenants": [st.stats()
                            for st in self._states.values()]}


class FairCycle:
    """Deficit-weighted round-robin chooser over whatever tenants are
    *present* right now.

    Each :meth:`choose` call accrues every present tenant's weight
    into its deficit, picks the largest deficit (stable tie-break on
    presentation order), and charges the winner the round total. A
    tenant whose queue empties is forgotten (standard DRR: no credit
    hoarding while absent), and zero-weight tenants accrue a small
    epsilon so they still progress — which is the bounded-starvation
    guarantee the proof test exercises: with total weight ``W`` and a
    tenant of weight ``w``, that tenant is served at least once every
    ``ceil(W / w) + 1`` rounds it is present."""

    EPSILON = 1e-3

    def __init__(self):
        self._deficit: Dict[str, float] = {}

    def choose(self, present: Dict[str, float]) -> str:
        """Pick the next tenant to serve among ``present``
        (tenant id → weight). ``present`` must be non-empty."""
        if not present:
            raise ValueError("FairCycle.choose needs >= 1 tenant")
        self._deficit = {k: v for k, v in self._deficit.items()
                         if k in present}
        best = None
        best_d = 0.0
        total = 0.0
        for tid, w in present.items():
            w = w if w > 0 else self.EPSILON
            total += w
            d = self._deficit.get(tid, 0.0) + w
            self._deficit[tid] = d
            if best is None or d > best_d:
                best, best_d = tid, d
        self._deficit[best] -= total
        return best

    def reset(self) -> None:
        self._deficit.clear()


class ReleaseRateEwma:
    """EWMA over the gaps between decode slot-release events.

    Feeds the honest ``Retry-After`` on decode 429s: with ``q``
    requests ahead in the waiting queue and one slot freeing every
    ``gap`` seconds on average, a client should come back in about
    ``q * gap`` seconds. :meth:`retry_after` returns ``None`` while
    cold (fewer than ``min_samples`` releases) or stale (no release
    for ``max_idle_s``) so callers fall back to the configured
    constant."""

    def __init__(self, alpha: float = 0.2, min_samples: int = 4,
                 max_idle_s: float = 30.0,
                 clock: Clock = SYSTEM_CLOCK):
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self.max_idle_s = float(max_idle_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._gap: Optional[float] = None
        self._last: Optional[float] = None
        self.n_samples = 0

    def note(self) -> None:
        """One slot released now."""
        now = self.clock.now()
        with self._lock:
            last, self._last = self._last, now
            if last is None:
                return
            gap = now - last
            if gap > self.max_idle_s:
                # an idle lull, not a service gap — restart the EWMA
                self._gap = None
                self.n_samples = 0
                return
            self._gap = gap if self._gap is None \
                else (1 - self.alpha) * self._gap + self.alpha * gap
            self.n_samples += 1

    def gap_s(self) -> Optional[float]:
        with self._lock:
            if self._gap is None or self.n_samples < self.min_samples:
                return None
            if self._last is not None \
                    and self.clock.now() - self._last > self.max_idle_s:
                return None
            return self._gap

    def retry_after(self, n_ahead: int) -> Optional[float]:
        """Honest wait for a client behind ``n_ahead`` queued
        requests; ``None`` when cold/stale (use the constant)."""
        gap = self.gap_s()
        if gap is None:
            return None
        return max(gap * max(int(n_ahead), 1), 1e-3)
