"""PartitionConsolidator: funnel concurrent callers through few workers.

Capability parity with `io/http/src/main/scala/PartitionConsolidator.scala:103,17`
— the reference funnels rows from many Spark partitions into one worker
per executor so rate-limited services see bounded concurrency. The
columnar equivalent: a Transformer wrapper that caps how many transform
calls run at once process-wide (callers queue on a semaphore), so N
threads scoring against a rate-limited HTTP service behave like the
consolidated single channel.
"""

from __future__ import annotations

import threading
from typing import Dict

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param, in_range
from mmlspark_tpu.core.stage import Transformer

# process-level channels keyed by consolidation group
# (parity: SharedSingleton keyed by uid, SharedVariable.scala:18,37)
_channels: Dict[str, threading.Semaphore] = {}
_channels_lock = threading.Lock()


def _channel(key: str, slots: int) -> threading.Semaphore:
    with _channels_lock:
        if key not in _channels:
            _channels[key] = threading.Semaphore(slots)
        return _channels[key]


class PartitionConsolidator(Transformer):
    """Cap process-wide concurrency of an inner transformer."""

    stage = Param(None, "the transformer to consolidate", complex=True)
    group = Param("default", "consolidation group key", ptype=str)
    max_concurrency = Param(1, "simultaneous transform calls",
                            in_range(lo=1))

    def transform(self, df: DataFrame) -> DataFrame:
        sem = _channel(self.group, self.max_concurrency)
        with sem:
            return self.stage.transform(df)

    def _save_extra(self, path, arrays):
        import os
        self.stage.save(os.path.join(path, "inner"))

    def _load_extra(self, path, arrays):
        import os
        from mmlspark_tpu.core.stage import PipelineStage
        self.stage = PipelineStage.load(os.path.join(path, "inner"))
