"""Continuous batching for autoregressive decode.

The frame-serving plane (server.py) dispatches whole shape-bucketed
batches: right for stateless models, wrong for autoregressive decode,
where requests have private growing state (a KV cache) and finish at
different times — batching whole requests would hold every member
until the slowest one's last token. This module batches at the *slot*
level instead:

* a :class:`TransformerDecoder` owns ONE preallocated slot-indexed
  KV-cache pool (``models/transformer.init_kv_cache``) plus the jitted
  prefill/step functions built over it — fixed shapes, donated cache,
  so a warm decode loop performs **zero device allocations and zero
  retraces** however requests churn;
* a :class:`DecodeScheduler` runs the step loop: between any two
  decode steps, waiting requests claim free slots (one bucketed
  prefill each), finished requests (EOS / token budget / cache-lane
  end / deadline / cancel) release theirs, and the single-token step
  always runs over the full fixed ``[n_slots]`` batch. The loop never
  stops or retraces while traffic flows — joiners splice in between
  steps, leavers just return an index.

Requests ride the server's existing admission machinery
(:class:`~mmlspark_tpu.serving.server.ServingServer` routes its
``decode_path`` here): replay/join/shed/deadline semantics, the reply
journal, root spans, and the trace id all behave exactly as on the
frame plane. Tokens are emitted incrementally into the request's
in-flight state (visible via ``GET /decode/stats``); the reply carries
the full sequence once the request leaves its slot.

The decode plane's memory is **paged** by default (docs/serving.md
"Paged KV cache"): the KV pool is a shared set of fixed-size pages
plus per-slot page tables, so cache HBM is spent on rows sequences
actually occupy — a :class:`PagePool` claims/frees pages between
steps with the same no-leak ledger as slots, admission sheds 429 on
page exhaustion, and a pool that runs dry mid-decode preempts (partial
tokens, ``pages_exhausted``) instead of OOMing. The page pool is
**content-addressable across requests** (docs/serving.md "Prefix
cache"): a :class:`PrefixCache` radix index keyed by
``page_size``-token prompt chunks maps a new prompt to its longest
cached prefix, whose pages attach to the new slot's table by
REFERENCE (``PagePool`` refcounts — a shared page frees only when its
last reader leaves), a finishing request's prompt-complete pages are
published into the index instead of freed (LRU-bounded; eviction
reclaims unreferenced pages under claim pressure), and the prefill
computes only the uncached suffix — exact, token-for-token the cold
path. With a draft model
configured, the scheduler runs **speculative rounds** (fused k-token
draft propose + one width-k target verify; exact greedy prefix
acceptance, rejection sampling for sampled opt-ins, acceptance-gated
by :class:`~mmlspark_tpu.serving.policy.SpeculationPolicy`). Requests
that ask for ``stream=1`` get their tokens **incrementally** as
chunked SSE events through either frontend's stream handle
(``pending.stream``); disconnects flip the handle's ``closed`` flag
and resolve through the same ``_finish`` as every other exit.

Observability: slot occupancy, decode steps, per-token counters,
prefill/step latency histograms, page-pool occupancy, speculative
acceptance, and queue-wait all land in the server's registry
(``docs/observability.md`` "Decode metrics"); every request's trace
shows ``queue_wait``/``prefill``/``decode`` children under its root.
Chaos: a ``fault_plan`` drives the ``decode_prefill`` and
``decode_step`` sites — an injected step/verify fault 500s the
affected requests but **never strands a slot or a page**
(tests/test_serving_decode.py).
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.logs import get_logger
from mmlspark_tpu.core.resilience import SYSTEM_CLOCK, Clock
from mmlspark_tpu.parallel.sharding import bucket_ladder, bucket_target
from mmlspark_tpu.serving.tenancy import (
    ANONYMOUS_ID, FairCycle, ReleaseRateEwma,
)

logger = get_logger("serving.decode")


class DecodeOverloaded(RuntimeError):
    """The waiting queue is full: new decode work must shed (429)."""


class TransformerDecoder:
    """The model side of continuous batching: one KV pool + the jitted
    prefill/step machinery over it, with host-side bookkeeping.

    Not thread-safe by design — exactly one :class:`DecodeScheduler`
    loop thread drives it (the cache is DONATED through every call;
    two concurrent calls would race one buffer). ``eos_id`` is the
    stop token (None = never stops early; requests end on their token
    budget). ``warmup()`` compiles the step and every prompt bucket;
    after it, :meth:`n_compiles` staying flat is the zero-retrace
    evidence the bench gates on.

    **Paged mode** (``paged=True``, the default): the pool is a
    block-table layout — ``n_pages`` shared pages of ``page_size``
    rows plus per-slot page tables — so cache HBM is spent on rows
    sequences actually occupy instead of ``max_len`` per slot (page 0
    is the scratch page; see ``models/transformer.py``). ``n_pages``
    defaults to the dense equivalent (every slot can hold a full
    lane); set it lower to serve more slots at the same HBM — the
    scheduler's :class:`PagePool` admission keeps the pool honest.
    ``paged=False`` keeps the dense ``[n_slots, max_len]``-lane pool
    as the A/B baseline. Callers without a scheduler (direct API,
    ``testing/decode_load``) may omit page tables: an identity table
    (slot ``s`` -> pages ``[1 + s*pps, 1 + (s+1)*pps)``) stands in,
    which needs the full-size default pool.

    **Speculative decoding** (``draft_params``/``draft_cfg``): a small
    draft model (same vocab — e.g.
    :func:`~mmlspark_tpu.models.transformer.layer_truncated_draft`)
    proposes ``spec_k`` greedy tokens per slot in ONE fused device
    program, and a width-``spec_k`` verify step of the target scores
    them all at once; the scheduler accepts the longest agreeing
    prefix. The draft keeps a dense slot-lane cache (its layers are
    the cheap fraction — paging the target is where the HBM lives).
    Requires paged mode and no mesh (the draft is replicated)."""

    def __init__(self, params, cfg, n_slots: int = 8,
                 max_len: int = 256, eos_id: Optional[int] = None,
                 donate: bool = True, mesh=None,
                 paged: bool = True, page_size: int = 16,
                 n_pages: Optional[int] = None,
                 draft_params=None, draft_cfg=None, spec_k: int = 4,
                 attn_impl: str = "auto",
                 verify_ce_impl: Optional[str] = None,
                 prefix_cache: bool = True,
                 quantized_ffn: bool = False):
        from mmlspark_tpu.models import transformer as T
        self.cfg = cfg
        self.n_slots = int(n_slots)
        self.max_len = int(max_len)
        self.eos_id = eos_id
        self.mesh = mesh
        self.paged = bool(paged)
        self.quantized_ffn = bool(quantized_ffn)
        if self.quantized_ffn:
            # int8-compute FFN (ISSUE 17 tentpole a): per-channel
            # scales derived ONCE here — construction is rollout stage
            # time, so the quantized tree warms/compiles pre-flip and
            # serving never requantizes. Attention/rope/softmax/the
            # residual stream stay f32 (quantize_decode_ffn docs);
            # row-wise parity vs the f32 tree is the rollout verify's
            # job, not an assumption.
            params = T.quantize_decode_ffn(params, cfg)
        cache_sharding = None
        if mesh is not None:
            # tensor-parallel decode: ONE model + ONE KV pool span the
            # mesh — heads/MLP-hidden shard over the model axis
            # (decode_param_specs), each device's cache holds exactly
            # its heads' lanes (decode_cache_spec — the head dim is
            # axis 3 of the dense AND the paged layout, so one spec
            # serves both). The jitted machinery below compiles the
            # SAME programs as sharded computations; shapes, donation,
            # and compile-once are unchanged.
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            is_spec = lambda x: isinstance(x, PartitionSpec)  # noqa: E731
            p_sh = jax.tree.map(
                lambda s: NamedSharding(mesh, s),
                T.decode_param_specs(cfg, mesh,
                                     quantized_ffn=self.quantized_ffn),
                is_leaf=is_spec)
            params = jax.device_put(params, p_sh)
            cache_sharding = NamedSharding(mesh,
                                           T.decode_cache_spec(mesh))
        self.params = params
        if self.paged:
            page_size = int(page_size)
            if page_size < 1 or page_size & (page_size - 1):
                # prompt buckets are powers of two: a pow2 page divides
                # every bucket >= itself (whole-chunk scatters) and
                # bounds the rest to the partial-page path — any other
                # size leaves buckets the prefill cannot chunk
                raise ValueError(
                    f"page_size={page_size} must be a power of two")
            if self.max_len % page_size:
                raise ValueError(
                    f"page_size={page_size} must divide "
                    f"max_len={self.max_len}")
            self.page_size = int(page_size)
            self.pages_per_slot = self.max_len // self.page_size
            # default pool = the dense equivalent + the scratch page:
            # identical HBM and admission behavior until the operator
            # shrinks it (or raises n_slots at the same pool)
            self.n_pages = (int(n_pages) if n_pages is not None
                            else 1 + self.n_slots * self.pages_per_slot)
            if self.n_pages < 2:
                raise ValueError("paged cache needs n_pages >= 2 "
                                 "(page 0 is the scratch page)")
            # the decode-step gather engine (ROADMAP item 5 / PR 11
            # follow-up): "auto" runs the fused Pallas block-table
            # kernel on TPU (the page table aims each page DMA via
            # scalar prefetch — no per-layer lane materialization in
            # HBM) and the dense gather everywhere else; "dense" /
            # "pallas" / "pallas_interpret" force an engine
            # (interpret = the CPU parity-test mode). Under a TP mesh
            # the kernel dispatches sharding-aware: heads are
            # independent, so each model-axis shard runs the kernel
            # on its own head slice of the pool (a shard_map inside
            # the step — per-shard head-slice grids, page tables
            # replicated; token-for-token parity vs the dense gather
            # is test-pinned for the mesh path too).
            if attn_impl not in ("auto", "dense", "pallas",
                                 "pallas_interpret"):
                raise ValueError(f"unknown attn_impl {attn_impl!r}")
            if attn_impl == "auto":
                from mmlspark_tpu.parallel.pallas_attention import (
                    paged_attention_available)
                attn_impl = ("pallas" if paged_attention_available()
                             else "dense")
            self.attn_impl = attn_impl
            self.cache = T.init_paged_kv_cache(cfg, self.n_pages,
                                               self.page_size)
            # the SAME resolved engine drives the prefill builders
            # (ISSUE 17): "pallas" runs the streaming flash kernels —
            # no [S, S] score matrix in the cold prefills, no [S, V]
            # lane materialization in the offset/prefix prefill —
            # "dense" keeps the softmax paths, interpret is CPU parity
            self._prefill = T.build_paged_prefill(
                cfg, self.page_size, self.pages_per_slot,
                donate=donate, cache_sharding=cache_sharding,
                attn_impl=attn_impl)
            self._step = T.build_paged_decode_step(
                cfg, self.n_slots, self.page_size, self.pages_per_slot,
                donate=donate, cache_sharding=cache_sharding,
                attn_impl=attn_impl)
            # the cross-request prefix cache's compute half: a
            # partial/offset prefill that computes KV only for the
            # uncached suffix [hit_len, S) while attending over the
            # shared prefix pages (the scheduler's PrefixCache is the
            # index half; prefix_cache=False skips building/warming it
            # — the A/B baseline)
            self._prefix_prefill = (
                T.build_paged_prefix_prefill(
                    cfg, self.page_size, self.pages_per_slot,
                    donate=donate, cache_sharding=cache_sharding,
                    attn_impl=attn_impl)
                if prefix_cache else None)
            if 1 + self.n_slots * self.pages_per_slot <= self.n_pages:
                self._identity_tables = (
                    1 + np.arange(self.n_slots * self.pages_per_slot,
                                  dtype=np.int32)
                ).reshape(self.n_slots, self.pages_per_slot)
            else:
                self._identity_tables = None   # pool is undersized on
                # purpose: tables must come from the scheduler's pool
        else:
            if attn_impl not in ("auto", "dense"):
                # the kernel fuses the PAGED gather; the dense lane
                # pool has none — refuse loudly rather than silently
                # serving dense numbers under a 'pallas' flag
                raise ValueError(
                    f"attn_impl={attn_impl!r} needs the paged cache "
                    "(paged=True); the dense lane pool has no gather "
                    "to fuse")
            self.page_size = self.pages_per_slot = 0
            self.n_pages = 0
            self.attn_impl = "dense"
            self._identity_tables = None
            self._prefix_prefill = None
            self.cache = T.init_kv_cache(cfg, self.n_slots,
                                         self.max_len)
            self._prefill = T.build_prefill(
                cfg, donate=donate, cache_sharding=cache_sharding)
            self._step = T.build_decode_step(
                cfg, self.n_slots, self.max_len, donate=donate,
                cache_sharding=cache_sharding)
        if cache_sharding is not None:
            import jax
            self.cache = jax.device_put(self.cache, cache_sharding)
        # -- speculative decoding (optional)
        self.spec_k = int(spec_k)
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        self.draft_cache = None
        self._draft_prefill = self._draft_step = None
        self._propose = self._verify = None
        self.verify_ce_impl: Optional[str] = None
        if draft_params is not None:
            if draft_cfg is None:
                raise ValueError("draft_params needs draft_cfg")
            if draft_cfg.vocab != cfg.vocab:
                raise ValueError("draft and target must share a vocab")
            if not self.paged:
                raise ValueError(
                    "speculative decoding rides the paged cache "
                    "(paged=True)")
            if mesh is not None:
                raise ValueError(
                    "speculative decoding with a mesh is not wired "
                    "yet: the draft is replicated")
            if not 2 <= self.spec_k < self.max_len:
                raise ValueError(f"spec_k={spec_k} must be in "
                                 f"[2, max_len)")
            self.draft_cache = T.init_kv_cache(draft_cfg, self.n_slots,
                                               self.max_len)
            self._draft_prefill = T.build_prefill(draft_cfg,
                                                  donate=donate)
            self._draft_step = T.build_decode_step(
                draft_cfg, self.n_slots, self.max_len, donate=donate)
            self._propose = T.build_draft_propose(
                draft_cfg, self.n_slots, self.max_len, self.spec_k,
                donate=donate)
            # the verify/score pass also emits per-proposal target
            # log-probs (the acceptance-quality signal): scored by the
            # streaming fused-CE kernel when eligible (TPU,
            # lane-aligned d_model, tile-filling token count — a
            # [N, k-1] fetch instead of deriving from the [N, k, V]
            # logits), the XLA logsumexp path otherwise.
            self.verify_ce_impl = (
                verify_ce_impl if verify_ce_impl is not None
                else T.verify_ce_engine(cfg, self.n_slots, self.spec_k,
                                        sharded=mesh is not None))
            self._verify = T.build_paged_verify_step(
                cfg, self.n_slots, self.spec_k, self.page_size,
                self.pages_per_slot, donate=donate,
                cache_sharding=cache_sharding,
                with_scores=True, ce_impl=self.verify_ce_impl)

    @property
    def has_draft(self) -> bool:
        return self._verify is not None

    @property
    def has_prefix_prefill(self) -> bool:
        return self._prefix_prefill is not None

    def placement(self) -> Dict[str, Any]:
        """Where this decoder's params + KV pool live (the
        ``/decode/stats`` placement surface)."""
        if self.mesh is None:
            return {"mode": "single_device", "n_devices": 1}
        from mmlspark_tpu.parallel import dist
        out = {"mode": "tensor_parallel",
               "label": dist.placement_label(self.mesh)}
        out.update(dist.placement_report(
            {"params": self.params, "cache": self.cache}, self.mesh))
        return out

    # -- shapes --------------------------------------------------------------

    def prompt_buckets(self) -> List[int]:
        """The prefill shape ladder: pow2 buckets clamped at
        ``max_len`` (same policy as the frame plane's batch buckets —
        one ladder idiom framework-wide, derived in O(log max_len)
        instead of the old O(max_len) bucket_target scan)."""
        return bucket_ladder(self.max_len)

    def pad_prompt(self, prompt: np.ndarray) -> np.ndarray:
        bucket = bucket_target(len(prompt), self.max_len)
        out = np.zeros(bucket, np.int32)
        out[:len(prompt)] = prompt
        return out

    # -- compute -------------------------------------------------------------

    def _table_for(self, slot: int, page_table) -> np.ndarray:
        if page_table is not None:
            return np.asarray(page_table, np.int32)
        if self._identity_tables is None:
            raise ValueError(
                "this paged pool is smaller than n_slots full lanes: "
                "page tables must come from the scheduler's PagePool")
        return self._identity_tables[slot]

    def prefill_logits(self, slot: int, prompt: np.ndarray,
                       page_table=None, draft: bool = True
                       ) -> "tuple[int, Any]":
        """Fill ``slot``'s cache lane (dense) or its claimed pages
        (paged — ``page_table``; identity fallback when omitted) from
        ``prompt``; returns the first generated greedy token AND the
        last-position logits (a device array — only a sampling caller
        pays the host fetch). With a draft configured, the draft's
        slot lane is prefilled too (both models must agree on the
        prompt before proposals mean anything) — unless
        ``draft=False``, for requests that can never speculate (the
        scheduler skips the wasted draft pass)."""
        import jax.numpy as jnp
        padded = self.pad_prompt(prompt)
        if self.paged:
            self.cache, nxt, logits = self._prefill(
                self.params, self.cache, jnp.asarray(padded),
                jnp.asarray(self._table_for(slot, page_table)),
                np.int32(len(prompt)))
        else:
            self.cache, nxt, logits = self._prefill(
                self.params, self.cache, jnp.asarray(padded),
                np.int32(slot), np.int32(len(prompt)))
        if self.has_draft and draft:
            self.draft_cache, _, _ = self._draft_prefill(
                self.draft_params, self.draft_cache,
                jnp.asarray(padded), np.int32(slot),
                np.int32(len(prompt)))
        return int(nxt), logits

    def prefill(self, slot: int, prompt: np.ndarray,
                page_table=None) -> int:
        """Greedy :meth:`prefill_logits` (compat surface)."""
        return self.prefill_logits(slot, prompt, page_table)[0]

    def prefill_prefix_logits(self, slot: int, prompt: np.ndarray,
                              hit_len: int, page_table,
                              draft: bool = True
                              ) -> "tuple[int, Any]":
        """Partial/offset prefill: the prompt's first ``hit_len``
        tokens (page-aligned, ``< len(prompt)``) already live in the
        shared prefix pages at the head of ``page_table`` — compute
        K/V only for the suffix (padded to its own bucket) while
        attending over the whole virtual lane. Token-for-token
        equivalent to :meth:`prefill_logits` (the shared pages ARE a
        previous cold prefill's rows). The draft cache (speculation)
        has no page plane, so the draft still prefills the FULL prompt
        into its dense slot lane — already-warmed prompt buckets, and
        the draft's cost is the cheap fraction by construction."""
        import jax.numpy as jnp
        if hit_len <= 0:
            return self.prefill_logits(slot, prompt, page_table,
                                       draft=draft)
        if hit_len % self.page_size or hit_len >= len(prompt):
            raise ValueError(
                f"hit_len={hit_len} must be page-aligned and < "
                f"prompt length {len(prompt)}")
        padded = self.pad_prompt(prompt[hit_len:])
        self.cache, nxt, logits = self._prefix_prefill(
            self.params, self.cache, jnp.asarray(padded),
            jnp.asarray(self._table_for(slot, page_table)),
            np.int32(len(prompt)), np.int32(hit_len))
        if self.has_draft and draft:
            self.draft_cache, _, _ = self._draft_prefill(
                self.draft_params, self.draft_cache,
                jnp.asarray(self.pad_prompt(prompt)), np.int32(slot),
                np.int32(len(prompt)))
        return int(nxt), logits

    def step_logits(self, tokens: np.ndarray, pos: np.ndarray,
                    page_tables=None) -> "tuple[np.ndarray, Any]":
        """One token for every slot: ``tokens``/``pos`` are the full
        fixed ``[n_slots]`` arrays (free slots ride along at token 0 /
        pos 0, paged free slots with an all-scratch table row).
        Returns greedy next tokens plus the full per-slot logits
        (device array; fetched only when a sampler needs it)."""
        import jax.numpy as jnp
        if self.paged:
            if page_tables is None:
                if self._identity_tables is None:
                    raise ValueError("undersized paged pool needs "
                                     "scheduler page tables")
                page_tables = self._identity_tables
            self.cache, nxt, logits = self._step(
                self.params, self.cache, jnp.asarray(tokens),
                jnp.asarray(pos),
                jnp.asarray(np.asarray(page_tables, np.int32)))
        else:
            self.cache, nxt, logits = self._step(
                self.params, self.cache, jnp.asarray(tokens),
                jnp.asarray(pos))
        return np.asarray(nxt), logits

    def step(self, tokens: np.ndarray, pos: np.ndarray,
             page_tables=None) -> np.ndarray:
        """Greedy :meth:`step_logits` (compat surface)."""
        return self.step_logits(tokens, pos, page_tables)[0]

    # -- speculative compute -------------------------------------------------

    def propose(self, tokens: np.ndarray, pos: np.ndarray
                ) -> np.ndarray:
        """``spec_k`` chained greedy draft steps in ONE device program
        -> proposals ``[n_slots, spec_k]`` (the draft cache advances
        in place)."""
        import jax.numpy as jnp
        self.draft_cache, props = self._propose(
            self.draft_params, self.draft_cache, jnp.asarray(tokens),
            jnp.asarray(pos))
        return np.asarray(props)

    def draft_step_logits(self, tokens: np.ndarray, pos: np.ndarray
                          ) -> "tuple[np.ndarray, Any]":
        """One draft step with logits — the slow proposal path a
        sampled speculative slot needs (per-step draft distributions
        on host for rejection sampling)."""
        import jax.numpy as jnp
        self.draft_cache, nxt, logits = self._draft_step(
            self.draft_params, self.draft_cache, jnp.asarray(tokens),
            jnp.asarray(pos))
        return np.asarray(nxt), logits

    def verify_logits(self, tokens: np.ndarray, pos: np.ndarray,
                      page_tables
                      ) -> "tuple[np.ndarray, Any, np.ndarray]":
        """The target's width-``spec_k`` scoring pass: ``tokens`` is
        ``[n_slots, spec_k]`` (column 0 = each slot's current input
        token, columns 1.. = draft proposals). Returns the greedy
        argmax per position, the full logits (device array — fetched
        only when a sampled slot needs rejection sampling), and the
        per-proposal target log-probs ``[n_slots, spec_k - 1]``
        (fused-CE or XLA per ``verify_ce_impl``)."""
        import jax.numpy as jnp
        self.cache, toks, logits, scores = self._verify(
            self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(pos),
            jnp.asarray(np.asarray(page_tables, np.int32)))
        return np.asarray(toks), logits, np.asarray(scores)

    def n_compiles(self) -> int:
        """Compiled-executable count across every jitted entry point
        (prefill buckets, the step, and the draft/propose/verify
        machinery when speculation is on): flat after warmup = zero
        retraces."""
        n = int(self._prefill._cache_size() + self._step._cache_size())
        for fn in (self._draft_prefill, self._draft_step,
                   self._propose, self._verify,
                   self._prefix_prefill):
            if fn is not None:
                n += int(fn._cache_size())
        return n

    def warmup(self) -> int:
        """Compile the decode step, every prefill bucket, and (when
        speculation is on) the draft/propose/verify machinery before
        traffic (the cache content it writes lands on scratch pages /
        free lanes, which the next real prefill overwrites). Returns
        the compile count — the post-warmup baseline."""
        zeros_t = np.zeros(self.n_slots, np.int32)
        zero_tables = (np.zeros((self.n_slots, self.pages_per_slot),
                                np.int32) if self.paged else None)
        self.step(zeros_t, zeros_t.copy(), zero_tables)
        for bucket in self.prompt_buckets():
            self.prefill(0, np.zeros(min(bucket, self.max_len - 1),
                                     np.int32),
                         zero_tables[0] if self.paged else None)
        if self._prefix_prefill is not None:
            # the offset prefill compiles per SUFFIX bucket — the same
            # pow2 ladder (hit depth is a traced scalar, not a shape)
            import jax.numpy as jnp
            for bucket in self.prompt_buckets():
                self.cache, _, _ = self._prefix_prefill(
                    self.params, self.cache,
                    jnp.asarray(np.zeros(bucket, np.int32)),
                    jnp.asarray(zero_tables[0]),
                    np.int32(1), np.int32(0))
        if self.has_draft:
            self.propose(zeros_t, zeros_t.copy())
            self.draft_step_logits(zeros_t, zeros_t.copy())
            self.verify_logits(
                np.zeros((self.n_slots, self.spec_k), np.int32),
                zeros_t.copy(), zero_tables)
        return self.n_compiles()

class Sampler:
    """Per-request seeded token sampling over the step's full logits.

    Greedy decode stays the device-side argmax (no logits transfer);
    a request that asks for ``temperature > 0`` gets temperature /
    top-k / nucleus (top-p) sampling on host from its slot's logits
    row, driven by its own ``numpy`` PRNG — so one ``seed`` makes a
    sampled decode bit-for-bit reproducible whatever other requests
    share the batch (slot independence extends to randomness)."""

    __slots__ = ("temperature", "top_k", "top_p", "seed", "_rng")

    def __init__(self, temperature: float, top_k: int = 0,
                 top_p: float = 1.0, seed: Optional[int] = None):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = seed
        self._rng = np.random.default_rng(seed)

    def probs(self, logits: np.ndarray) -> np.ndarray:
        """The transformed distribution (temperature, then top-k, then
        nucleus restriction, renormalized) — the ``p``/``q`` both
        sides of speculative rejection sampling score against."""
        l = logits.astype(np.float64) / max(self.temperature, 1e-6)
        if 0 < self.top_k < l.size:
            kth = np.partition(l, -self.top_k)[-self.top_k]
            l = np.where(l < kth, -np.inf, l)
        l = l - l.max()
        p = np.exp(l)
        p /= p.sum()
        if self.top_p < 1.0:
            order = np.argsort(-p, kind="stable")
            cum = np.cumsum(p[order])
            # smallest prefix whose mass reaches top_p (>= 1 token)
            keep = int(np.searchsorted(cum, self.top_p)) + 1
            mask = np.zeros(p.size, bool)
            mask[order[:keep]] = True
            p = np.where(mask, p, 0.0)
            p /= p.sum()
        return p

    def sample(self, logits: np.ndarray) -> int:
        return int(self._rng.choice(logits.size,
                                    p=self.probs(logits)))

    def draw(self, p: np.ndarray) -> int:
        """Draw from an explicit distribution with this request's own
        PRNG (speculative residual draws stay per-request seeded)."""
        return int(self._rng.choice(p.size, p=p))

    def uniform(self) -> float:
        """One accept/reject draw from the request's PRNG."""
        return float(self._rng.random())

    def describe(self) -> Dict[str, Any]:
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed}


class SlotPool:
    """Free-slot index pool. Claim/release are O(1) under one lock —
    release checks the claimed SET, not the free list (the old ``slot
    in self._free`` scan was O(n_free) per release inside the step
    loop, the same ledger mistake :class:`PagePool` already fixed);
    the scheduler loop is the only claimer, but cancel paths and tests
    read ``n_free`` concurrently."""

    def __init__(self, n_slots: int):
        self.n_slots = int(n_slots)
        self._free = list(range(self.n_slots - 1, -1, -1))
        self._claimed: set = set()
        self._lock = threading.Lock()

    def claim(self) -> Optional[int]:
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._claimed.add(slot)
            return slot

    def release(self, slot: int) -> None:
        with self._lock:
            if slot not in self._claimed:
                raise RuntimeError(f"slot {slot} double-released")
            self._claimed.discard(slot)
            self._free.append(slot)

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)


class PagePool:
    """Refcounted free-page index pool over the paged KV cache. Page 0
    is the scratch page (unclaimed table entries route writes there)
    and is never handed out, so a pool of ``n_pages`` holds
    ``n_pages - 1`` claimable pages.

    Every claimed page carries a **refcount**: ``claim`` hands out
    fresh pages at refcount 1, ``ref`` adds a reader to
    already-claimed pages (how a request attaches a cached prefix —
    and how the :class:`PrefixCache` itself pins the pages it
    publishes), and ``release`` drops a reference — a page returns to
    the free list only when its LAST holder releases it. ``claim`` is
    all-or-nothing — a request either gets every page it asked for or
    none (no partial grabs to leak on the error path). The high-water
    mark and the idle invariant (``n_free`` plus index-held pages ==
    ``n_pages - 1``, every surviving refcount exactly the index's own)
    are the page-leak ledger the chaos tests assert — refcounts, not
    raw ownership."""

    def __init__(self, n_pages: int):
        self.n_pages = int(n_pages)
        self._free = list(range(self.n_pages - 1, 0, -1))
        # page -> refcount for claimed pages: O(1) double-release
        # detection AND the sharing ledger in one structure
        self._ref: Dict[int, int] = {}
        self._lock = threading.Lock()
        self.high_water = 0

    def claim(self, n: int = 1) -> Optional[List[int]]:
        with self._lock:
            if n > len(self._free):
                return None
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._ref[p] = 1
            if len(self._ref) > self.high_water:
                self.high_water = len(self._ref)
            return pages

    def ref(self, pages: List[int]) -> None:
        """Add one reader to each already-claimed page (attaching a
        shared prefix). Raises on a page nobody holds — refcounts on
        free pages would resurrect reclaimed state."""
        with self._lock:
            for p in pages:
                if p not in self._ref:
                    raise RuntimeError(
                        f"page {p} ref'd while unclaimed")
            for p in pages:
                self._ref[p] += 1

    def release(self, pages: List[int]) -> None:
        with self._lock:
            for p in pages:
                if p not in self._ref:
                    raise RuntimeError(f"page {p} double-released")
                self._ref[p] -= 1
                if self._ref[p] == 0:
                    del self._ref[p]
                    self._free.append(p)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._ref.get(page, 0)

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_claimed(self) -> int:
        with self._lock:
            return len(self._ref)


class _RadixNode:
    """One cached page: keyed in its parent by the ``page_size``-token
    chunk whose K/V rows the page holds. ``parent``/``key`` back-links
    make leaf eviction O(log n) per victim (pop a leaf, its parent
    becomes the next candidate) instead of a full re-walk each."""

    __slots__ = ("children", "page", "last_used", "parent", "key",
                 "tenant")

    def __init__(self, page: int, now: float, parent=None, key=None,
                 tenant: str = ""):
        self.children: Dict[tuple, "_RadixNode"] = {}
        self.page = page
        self.last_used = now
        self.parent = parent
        self.key = key
        # the tenant whose finished request published this page ("" =
        # unattributed): quota charging and over-quota-first eviction
        # key off it; SHARING stays tenant-blind (lookup never checks)
        self.tenant = tenant


class PrefixCache:
    """Content-addressed index over the paged KV pool: a radix tree
    keyed at page granularity (``page_size``-token chunks of prompt
    token ids) mapping a new prompt to its longest cached prefix
    (docs/serving.md "Prefix cache").

    The tree holds ONE reference on every published page (via
    :meth:`PagePool.ref` semantics — publication transfers the
    finishing request's reference instead of freeing the page), so a
    cached page with refcount 1 is **unreferenced** — evictable — and
    refcount > 1 means live readers are attached. ``lookup`` walks
    whole chunks, refs the matched pages for the caller (the caller
    releases them at finish like any claimed page), and stamps the
    path's LRU clocks; ``publish`` inserts a finished request's
    fully-written PROMPT pages (never a page its owner might still
    write: generated-token pages and the partial tail page stay
    private and are freed). ``evict_for`` reclaims LRU unreferenced
    leaves under pressure; ``max_pages`` bounds the resident set.

    Thread safety: one lock over the tree. Pool refcount mutations for
    matched/published pages happen under it, so a concurrent
    ``release`` can never free a page between the radix match and the
    ``ref`` that pins it."""

    def __init__(self, pool: PagePool, page_size: int,
                 max_pages: Optional[int] = None,
                 clock: Clock = SYSTEM_CLOCK):
        self.pool = pool
        self.page_size = int(page_size)
        # default bound: the whole claimable pool — eviction under
        # claim pressure keeps live requests ahead of cache residency
        self.max_pages = (int(max_pages) if max_pages is not None
                          else pool.n_pages - 1)
        self.clock = clock
        self._root = _RadixNode(page=0, now=0.0)
        self._lock = threading.Lock()
        self.n_cached = 0
        self.n_lookups = 0
        self.n_hits = 0
        self.n_hit_tokens = 0
        self.n_published = 0
        self.n_evicted = 0
        # per-tenant residency: publication charges the owning tenant;
        # quotas bound a tenant's resident pages (eviction inside the
        # over-quota tenant first — one flood cannot monopolize the
        # shared index). Tenants without a quota are unbounded.
        self._quotas: Dict[str, int] = {}
        self._tenant_pages: Dict[str, int] = {}

    def set_quota(self, tenant_id: str,
                  max_pages: Optional[int]) -> None:
        """Bound ``tenant_id``'s resident cached pages (``None``
        removes the bound). Enforced at publish time: an over-quota
        tenant evicts ITS OWN LRU pages to make room, never another
        tenant's."""
        with self._lock:
            if max_pages is None:
                self._quotas.pop(tenant_id, None)
            else:
                self._quotas[tenant_id] = int(max_pages)

    def _charge_locked(self, tenant: str, n: int) -> None:
        c = self._tenant_pages.get(tenant, 0) + n
        if c > 0:
            self._tenant_pages[tenant] = c
        else:
            self._tenant_pages.pop(tenant, None)

    def _chunks(self, tokens, n: int):
        ps = self.page_size
        return [tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
                for i in range(n)]

    def lookup(self, prompt) -> "tuple[int, List[int]]":
        """Longest cached prefix of ``prompt`` -> ``(hit_len,
        pages)``, with the pages ref'd for the caller. ``hit_len`` is
        page-aligned and capped at ``len(prompt) - 1`` — the last
        prompt position is always computed by the (partial) prefill,
        which needs its logits for the first generated token.

        Does NOT count itself: a head-of-line request short of suffix
        pages re-queues and looks up again next pass, so the exported
        (monotonic) counters are bumped once per ADMITTED request via
        :meth:`count` instead of once per attempt."""
        max_chunks = (len(prompt) - 1) // self.page_size
        with self._lock:
            node, pages = self._root, []
            now = self.clock.now()
            for chunk in self._chunks(prompt, max_chunks):
                child = node.children.get(chunk)
                if child is None:
                    break
                child.last_used = now
                pages.append(child.page)
                node = child
            if not pages:
                return 0, []
            self.pool.ref(pages)
            return len(pages) * self.page_size, pages

    def count(self, hit_len: int) -> None:
        """Record one admitted request's lookup outcome in the hit
        ledger (monotonic — these back Prometheus counters)."""
        with self._lock:
            self.n_lookups += 1
            if hit_len > 0:
                self.n_hits += 1
                self.n_hit_tokens += hit_len

    def miss_count(self) -> int:
        """``misses = lookups - hits`` from ONE locked snapshot — the
        two fields update together under the lock, so an unlocked
        two-field read could tear mid-update and hand Prometheus a
        transiently decreasing counter (read as a reset)."""
        with self._lock:
            return self.n_lookups - self.n_hits

    def publish(self, prompt, pages: List[int],
                tenant: Optional[str] = None) -> "set":
        """Insert a finished request's prompt-complete pages
        (``pages[i]`` holds prompt rows ``[i*ps, (i+1)*ps)``) into the
        tree. Only pages newly ABSORBED by the index (their reference
        transferred from the request to the cache) are returned — the
        caller releases everything else: chunks already present keep
        the incumbent page (identical content — K/V is a pure function
        of the token prefix) and the duplicate stays the caller's to
        free. Absorption respects ``max_pages``: LRU unreferenced
        pages are evicted to make room, and when nothing is evictable
        the remaining chunks simply stay unpublished. ``tenant``
        attributes the fresh pages to their owner: a tenant at its
        :meth:`set_quota` bound evicts its OWN LRU pages first, and
        when none are evictable its surplus chunks stay unpublished
        (the caller frees them) — other tenants' residency is never
        taxed for one tenant's churn."""
        n_chunks = min(len(prompt) // self.page_size, len(pages))
        if n_chunks == 0:
            return set()
        owner = tenant or ""
        quota = self._quotas.get(owner) if owner else None
        absorbed: set = set()
        with self._lock:
            # size the eviction ONCE: count the chunks actually
            # missing (cheap path walk), then a single heap-seeded
            # _evict_locked covers them all — the per-chunk fallback
            # below only fires when eviction came up short, so a warm
            # cache at its bound pays one tree walk per publish, not
            # one per fresh chunk
            chunks = self._chunks(prompt, n_chunks)
            node, missing = self._root, 0
            for chunk in chunks:
                if node is not None:
                    node = node.children.get(chunk)
                if node is None:
                    missing += 1
            shortfall = self.n_cached + missing - self.max_pages
            if missing and shortfall > 0:
                self._evict_pressure_locked(shortfall)
            node = self._root
            now = self.clock.now()
            path: set = set()            # every node on this publish's
            # chain — fresh or matched. A mid-publish eviction that
            # removed one (a fresh page is a refcount-1 leaf until the
            # next chunk lands; a MATCHED incumbent can be refcount-1
            # too when this publisher duplicated rather than attached
            # it) would orphan the subtree being extended — its pages
            # unreachable forever, the ledger permanently dirty.
            for i, chunk in enumerate(chunks):
                child = node.children.get(chunk)
                if child is None:
                    if quota is not None and \
                            self._tenant_pages.get(owner, 0) >= quota \
                            and not self._evict_locked(
                                1, exclude=path, tenant=owner):
                        break    # at quota, nothing of OURS evictable
                    if self.n_cached >= self.max_pages and \
                            not self._evict_locked(1, exclude=path):
                        break            # full and pinned: stop here
                    child = _RadixNode(pages[i], now, parent=node,
                                       key=chunk, tenant=owner)
                    node.children[chunk] = child
                    self.n_cached += 1
                    self.n_published += 1
                    self._charge_locked(owner, 1)
                    absorbed.add(pages[i])
                else:
                    child.last_used = now
                path.add(id(child))
                node = child
        return absorbed

    def _nodes_locked(self):
        """Every node in the tree (root excluded). Caller holds the
        lock."""
        stack = [self._root]
        while stack:
            nd = stack.pop()
            for child in nd.children.values():
                yield child
                stack.append(child)

    def _evict_locked(self, n: int, exclude=frozenset(),
                      tenant: Optional[str] = None) -> int:
        """Evict up to ``n`` LRU leaves whose page has no reader
        beyond the index itself (refcount 1). Leaves only: an
        interior node's descendants are reachable exclusively through
        it — but evicting a leaf can TURN its parent into one, so
        candidates ride a heap seeded by one walk and parents join as
        their last child goes (O(n_cached + evicted·log) instead of a
        full re-walk per victim). ``exclude`` holds the node ids an
        in-flight publish is building under (never evict the chain
        being extended). ``tenant`` restricts victims to one tenant's
        pages (the over-quota-first path)."""
        import heapq
        heap = [(nd.last_used, i, nd)
                for i, nd in enumerate(self._nodes_locked())
                if not nd.children
                and (tenant is None or nd.tenant == tenant)]
        heapq.heapify(heap)
        seq = len(heap)
        evicted = 0
        while evicted < n and heap:
            _, _, nd = heapq.heappop(heap)
            if nd.children or nd.parent is None \
                    or nd.parent.children.get(nd.key) is not nd:
                continue                 # stale entry: re-parented or
                # already evicted this round
            if id(nd) in exclude or \
                    self.pool.refcount(nd.page) != 1:
                continue                 # pinned or publish-in-flight
            nd.parent.children.pop(nd.key)
            self.pool.release([nd.page])
            self.n_cached -= 1
            self.n_evicted += 1
            self._charge_locked(nd.tenant, -1)
            evicted += 1
            parent = nd.parent
            if not parent.children and parent is not self._root \
                    and (tenant is None or parent.tenant == tenant):
                heapq.heappush(heap, (parent.last_used, seq, parent))
                seq += 1
        return evicted

    def _evict_pressure_locked(self, n: int,
                               exclude=frozenset()) -> int:
        """Claim-pressure eviction: reclaim from OVER-QUOTA tenants
        first (most-over first), then fall back to global LRU — so a
        tenant camping past its budget pays for pool pressure before
        anyone inside theirs does."""
        evicted = 0
        if self._quotas:
            over = sorted(
                ((self._tenant_pages.get(t, 0) - q, t)
                 for t, q in self._quotas.items()
                 if self._tenant_pages.get(t, 0) > q),
                reverse=True)
            for surplus, t in over:
                if evicted >= n:
                    break
                evicted += self._evict_locked(
                    min(n - evicted, surplus), exclude=exclude,
                    tenant=t)
        if evicted < n:
            evicted += self._evict_locked(n - evicted,
                                          exclude=exclude)
        return evicted

    def evict_for(self, n_needed: int) -> int:
        """Reclaim LRU unreferenced cached pages until the pool can
        hand out ``n_needed`` pages (or nothing evictable remains).
        Returns the number evicted."""
        with self._lock:
            short = n_needed - self.pool.n_free
            return self._evict_pressure_locked(short) if short > 0 \
                else 0

    @property
    def n_evictable(self) -> int:
        """Cached pages no live request holds — reclaimable headroom.
        O(n_cached) tree walk with a pool-lock hop per page: a stats /
        test surface, NOT for per-request paths (admission uses the
        O(1) ``n_cached`` upper bound instead)."""
        with self._lock:
            return sum(1 for nd in self._nodes_locked()
                       if self.pool.refcount(nd.page) == 1)

    def ledger_clean(self) -> bool:
        """The IDLE/drain refcount invariant: every cached page is
        held by exactly the index (refcount 1) and free + cached
        accounts for the whole claimable pool — no request left a
        reference behind. Meaningful only with no requests live (a
        healthy reader mid-decode holds refcount 2); scrape it at
        drain, alert on it at idle."""
        with self._lock:
            pages = [nd.page for nd in self._nodes_locked()]
            if len(pages) != self.n_cached:
                return False
        if any(self.pool.refcount(p) != 1 for p in pages):
            return False
        return (self.pool.n_free + len(pages)
                == self.pool.n_pages - 1)

    def clear(self) -> int:
        """Release every cached page back to the pool (drain /
        shutdown). Pages with live readers lose only the index's
        reference. Returns the number of entries dropped."""
        with self._lock:
            pages = [nd.page for nd in self._nodes_locked()]
            self._root.children.clear()
            dropped, self.n_cached = self.n_cached, 0
            self._tenant_pages.clear()
            if pages:
                self.pool.release(pages)
            return dropped

    def stats(self) -> Dict[str, Any]:
        return {"page_size": self.page_size,
                "max_pages": self.max_pages,
                "cached_pages": self.n_cached,
                "evictable_pages": self.n_evictable,
                "lookups": self.n_lookups,
                "hits": self.n_hits,
                "hit_rate": (round(self.n_hits / self.n_lookups, 4)
                             if self.n_lookups else None),
                "hit_tokens": self.n_hit_tokens,
                "published_pages": self.n_published,
                "evicted_pages": self.n_evicted,
                "tenant_pages": dict(self._tenant_pages),
                "tenant_quotas": dict(self._quotas),
                "ledger_clean": self.ledger_clean()}


#: tokens-per-request histogram ladder (powers of two): its own edges,
#: NOT the latency buckets — the registry rejects bucket mismatches
#: per metric name, so the ladder is explicit here
TOKENS_PER_REQUEST_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0,
                              128.0, 256.0, 512.0, 1024.0)

#: per-request cap on timeline spans (first_token + spec_round events)
#: so a 100k-token decode cannot flood the flight recorder ring
_MAX_TIMELINE_SPANS = 128


class _DecodeRequest:
    """Per-request decode state, riding alongside the server's
    ``_PendingRequest`` (``pending`` — reply/status/event/callbacks/
    deadline/trace/span/stream all live there)."""

    __slots__ = ("pending", "prompt", "max_new", "produced", "slot",
                 "cancelled", "t_submit", "t_prefill", "t_decode",
                 "t_first", "t_last", "n_timeline",
                 "sampler", "spec", "pages", "hit_len")

    def __init__(self, pending, prompt: np.ndarray, max_new: int,
                 sampler: Optional[Sampler] = None,
                 spec: Optional[bool] = None):
        self.pending = pending
        self.prompt = prompt
        self.max_new = int(max_new)
        self.sampler = sampler
        # speculative opt-in/out from the payload; None = default
        # (greedy slots speculate when a draft exists, sampled slots
        # only on explicit opt-in — rejection sampling changes PRNG
        # consumption, so a seeded client must ask for it)
        self.spec = spec
        self.produced: List[int] = []       # incremental emission
        self.slot: Optional[int] = None
        self.pages: List[int] = []          # held KV pages (paged):
        # the first hit_len // page_size are SHARED prefix pages
        # (ref'd, read-only), the rest privately claimed
        self.hit_len = 0                    # cached-prefix depth
        self.cancelled = False
        self.t_submit: float = 0.0
        self.t_prefill: float = 0.0
        self.t_decode: float = 0.0
        # token-level timeline stamps (scheduler clock): first emitted
        # token and the latest emit — TTFT/TPOT fall out at _finish
        self.t_first: float = 0.0
        self.t_last: float = 0.0
        self.n_timeline = 0                 # timeline spans recorded

    @property
    def stream(self):
        return getattr(self.pending, "stream", None)


class DecodeScheduler:
    """The continuous-batching step loop.

    ``submit()`` (any thread) parses and enqueues; the loop thread
    admits waiting requests into free slots between steps, runs the
    fixed-shape decode step while any slot is live, and resolves
    requests through the server's commit path (journal + spans +
    waiter release) — or a standalone default when unbound (direct
    scheduler tests).

    Slot lifecycle (docs/serving.md "Continuous batching"):

    ``waiting -> prefill(slot claimed) -> stepping -> released`` on
    the first of: EOS, ``max_new_tokens`` produced, cache lane full
    (``max_len``), deadline expired, cancel, or an injected/real step
    fault. Every exit path releases the slot — the slot-leak chaos
    test churns all of them and asserts ``n_free == n_slots`` after.
    """

    def __init__(self, decoder: TransformerDecoder,
                 max_waiting: int = 256,
                 max_new_tokens_default: int = 64,
                 clock: Clock = SYSTEM_CLOCK,
                 fault_plan=None,
                 registry=None, tracer=None,
                 idle_wait_s: float = 0.02,
                 spec_policy="auto",
                 prefix_cache="auto",
                 prefix_cache_pages: Optional[int] = None):
        from mmlspark_tpu.serving.policy import SpeculationPolicy
        self.decoder = decoder
        # acceptance-gated speculation (serving/policy.py): "auto"
        # installs the default policy when a draft exists, None runs
        # speculation unconditionally, or pass a configured
        # SpeculationPolicy
        if spec_policy == "auto":
            spec_policy = (SpeculationPolicy() if decoder.has_draft
                           else None)
        self.spec_policy = spec_policy
        self.max_waiting = int(max_waiting)
        self.max_new_tokens_default = int(max_new_tokens_default)
        self.clock = clock
        self.fault_plan = fault_plan
        self.tracer = tracer
        self.idle_wait_s = float(idle_wait_s)
        self.pool = SlotPool(decoder.n_slots)
        # the page plane (paged decoders): the shared page pool plus
        # the live [n_slots, pages_per_slot] tables the jitted step/
        # verify read — unclaimed entries stay 0 (the scratch page)
        self.pages: Optional[PagePool] = None
        self._tables: Optional[np.ndarray] = None
        self.prefix: Optional[PrefixCache] = None
        if decoder.paged:
            self.pages = PagePool(decoder.n_pages)
            self._tables = np.zeros(
                (decoder.n_slots, decoder.pages_per_slot), np.int32)
            # the cross-request prefix cache: "auto" turns it on
            # exactly when the decoder built the offset-prefill
            # machinery (prefix_cache=False there is the A/B baseline)
            if prefix_cache == "auto":
                prefix_cache = decoder.has_prefix_prefill
            if prefix_cache:
                if not decoder.has_prefix_prefill:
                    raise ValueError(
                        "prefix_cache=True needs a decoder built "
                        "with prefix_cache=True (the offset-prefill "
                        "machinery)")
                self.prefix = PrefixCache(
                    self.pages, decoder.page_size,
                    max_pages=prefix_cache_pages, clock=clock)
        elif prefix_cache is True:
            raise ValueError("the prefix cache rides the paged pool "
                             "(paged=True)")
        self._waiting: deque = deque()
        self._by_rid: Dict[str, _DecodeRequest] = {}
        self._active: Dict[int, _DecodeRequest] = {}
        self._tokens = np.zeros(decoder.n_slots, np.int32)
        self._pos = np.zeros(decoder.n_slots, np.int32)
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # resolved by bind(); standalone default releases the pending
        # directly (event + callbacks), no journal
        self._commit: Callable[[Any], None] = self._standalone_commit
        self.n_requests = 0
        self.n_steps = 0
        self.n_tokens = 0
        self.n_prefills = 0
        # the prefill-throughput ledger the prefix-cache A/B gates on:
        # prompt tokens SERVED (cached prefix included) over prefill
        # wall-clock — a hit shrinks the wall, not the numerator
        self.n_prompt_tokens = 0
        self.prefill_s = 0.0
        self.n_step_faults = 0
        self.slots_high_water = 0
        self.n_page_preempts = 0
        # speculative ledger: acceptance_rate = accepted / proposed
        self.n_spec_rounds = 0
        self.n_spec_proposed = 0
        #: EWMA of the verify score-head's per-proposal target log-
        #: probs (fused-CE/XLA — acceptance quality, not just rate)
        self.spec_proposal_logp = None
        self.n_spec_accepted = 0
        self.releases: Dict[str, int] = {}   # finish_reason -> count
        # goodput: tokens delivered by CLEAN finishes (eos/length) —
        # the numerator; n_tokens stays the all-reasons denominator
        self.n_goodput_tokens = 0
        # tenancy hooks (wired by bind() against the server's
        # registry): slot-release EWMA feeds honest decode-429
        # Retry-After; the fair cycle orders slot claims per tenant
        self._server = None
        self.release_ewma = ReleaseRateEwma(clock=clock)
        self._fair = FairCycle()
        self._m_prefill = None
        self._m_step = None
        self._m_spec_round = None
        self._m_queue_wait = None
        self._m_ttft = None
        self._m_tpot = None
        self._m_tokens_req = None
        self._m_device = None
        if registry is not None:
            self._register_metrics(registry)

    # -- wiring --------------------------------------------------------------

    def bind(self, server) -> None:
        """Attach to a :class:`ServingServer`: its registry, tracer,
        clock, and commit path (journaled exactly-once replies) become
        this scheduler's."""
        self.clock = server.clock
        self.tracer = server.tracer
        self._commit = server._commit
        self._server = server
        self.release_ewma = ReleaseRateEwma(clock=server.clock)
        # per-tenant prefix-cache page budgets come from the registry
        if self.prefix is not None \
                and getattr(server, "tenancy", None) is not None:
            for t in server.tenancy.tenants.values():
                if t.max_cache_pages is not None:
                    self.prefix.set_quota(t.id, t.max_cache_pages)
        self._register_metrics(server.registry)

    def _register_metrics(self, m) -> None:
        m.gauge("serving_decode_slots_in_use",
                "KV-cache slots currently decoding."
                ).set_function(lambda: len(self._active))
        m.gauge("serving_decode_slots_free",
                "Free KV-cache slots.").set_function(
            lambda: self.pool.n_free)
        m.gauge("serving_decode_waiting",
                "Decode requests admitted but not yet in a slot."
                ).set_function(lambda: len(self._waiting))
        for name, help_, fn in (
            ("serving_decode_requests_total",
             "Decode requests that entered the scheduler.",
             lambda: self.n_requests),
            ("serving_decode_steps_total",
             "Single-token decode steps executed (each covers every "
             "live slot).", lambda: self.n_steps),
            ("serving_decode_tokens_total",
             "Tokens emitted to live requests.",
             lambda: self.n_tokens),
            ("serving_decode_prefills_total",
             "Prompt prefills (slot claims).",
             lambda: self.n_prefills),
            ("serving_decode_step_faults_total",
             "Decode steps that raised (injected or real); affected "
             "requests 500, slots are released.",
             lambda: self.n_step_faults),
            ("serving_decode_page_preempts_total",
             "Requests finished early because the page pool could not "
             "grow their lane mid-decode (finish_reason "
             "pages_exhausted).", lambda: self.n_page_preempts),
            ("serving_decode_spec_rounds_total",
             "Speculative rounds executed (one draft propose + one "
             "target verify each).", lambda: self.n_spec_rounds),
            ("serving_decode_spec_proposed_total",
             "Draft tokens proposed to the verifier.",
             lambda: self.n_spec_proposed),
            ("serving_decode_spec_accepted_total",
             "Draft tokens the target accepted (acceptance rate = "
             "accepted / proposed).", lambda: self.n_spec_accepted),
        ):
            m.counter(name, help_).set_function(fn)
        if self.pages is not None:
            m.gauge("serving_decode_pages_free",
                    "Free KV-cache pages in the shared pool."
                    ).set_function(lambda: self.pages.n_free)
            m.gauge("serving_decode_pages_in_use",
                    "KV-cache pages currently held by live slots "
                    "(prefix-cache residents are NOT in use — see "
                    "serving_decode_pages_cached).").set_function(
                lambda: (self.pages.n_pages - 1) - self.pages.n_free
                - (self.prefix.n_cached
                   if self.prefix is not None else 0))
            m.gauge("serving_decode_page_high_water",
                    "Most pages ever simultaneously claimed."
                    ).set_function(lambda: self.pages.high_water)
        if self.prefix is not None:
            m.gauge("serving_decode_pages_cached",
                    "KV-cache pages resident in the prefix-cache "
                    "radix index (held by the index; refcount 1 = "
                    "evictable).").set_function(
                lambda: self.prefix.n_cached)
            lk = m.counter(
                "serving_decode_prefix_lookups_total",
                "Prefix-cache radix lookups at admission, by result.",
                labels=("result",))
            lk.labels("hit").set_function(lambda: self.prefix.n_hits)
            lk.labels("miss").set_function(
                lambda: self.prefix.miss_count())
            m.counter("serving_decode_prefix_hit_tokens_total",
                      "Prompt tokens served from cached prefix pages "
                      "instead of recomputed at prefill."
                      ).set_function(lambda: self.prefix.n_hit_tokens)
            m.counter("serving_decode_prefix_evicted_pages_total",
                      "Cached pages reclaimed by LRU eviction under "
                      "pool pressure.").set_function(
                lambda: self.prefix.n_evicted)
        self._m_prefill = m.histogram(
            "serving_prefill_latency_ms",
            "Prompt prefill wall-clock per prompt bucket.",
            labels=("bucket",))
        self._m_step = m.histogram(
            "serving_decode_step_latency_ms",
            "Single-token decode step wall-clock (all slots at once).")
        self._m_spec_round = m.histogram(
            "serving_decode_spec_round_latency_ms",
            "Speculative round wall-clock (draft propose + target "
            "verify + host acceptance, all slots at once).")
        # billing-grade device-time attribution: the same family the
        # server's dispatch stage charges (get-or-create — one counter
        # per registry). Steps/spec rounds run ALL active slots at
        # once, so their wall time is pro-rated equally across the
        # tenants riding those slots; prefill is per-request and
        # charges whole.
        self._m_device = m.counter(
            "serving_tenant_device_ms_total",
            "Device wall-clock milliseconds attributed to each tenant: "
            "batch dispatch pro-rated by rows, decode steps pro-rated "
            "by active slots, prefill charged to its request.",
            labels=("tenant",))
        self._m_queue_wait = m.histogram(
            "serving_decode_queue_wait_ms",
            "Submit -> slot-claim wait per decode request.")
        # token-level decode timelines (ISSUE 18): observed once per
        # request at _finish — EVERY release reason, not just clean EOS
        self._m_ttft = m.histogram(
            "serving_decode_ttft_ms",
            "Time-to-first-token: admit -> first emitted token "
            "(socket-edge stamp for streamed replies).",
            labels=("route", "tenant"))
        self._m_tpot = m.histogram(
            "serving_decode_tpot_ms",
            "Time-per-output-token: mean inter-token gap after the "
            "first.", labels=("route", "tenant"))
        self._m_tokens_req = m.histogram(
            "serving_decode_tokens_per_request",
            "Tokens delivered per request, by finish reason.",
            labels=("reason",), buckets=TOKENS_PER_REQUEST_BUCKETS)
        m.counter("serving_decode_goodput_tokens_total",
                  "Tokens delivered by clean finishes (eos/length) — "
                  "the goodput numerator; serving_decode_tokens_total "
                  "is the all-reasons denominator."
                  ).set_function(lambda: self.n_goodput_tokens)
        if self.pages is not None:
            m.gauge("serving_decode_kv_pool_bytes",
                    "Live bytes held by the paged KV pool."
                    ).set_function(self._cache_bytes)
        if self.prefix is not None:
            m.gauge("serving_decode_prefix_cache_bytes",
                    "Bytes held by prefix-cache resident pages."
                    ).set_function(
                lambda: self._cache_bytes()
                * self.prefix.n_cached // max(self.pages.n_pages, 1))

    def _cache_bytes(self) -> int:
        """Exposition-time view: bytes of the decoder's KV tree."""
        try:
            from mmlspark_tpu.parallel.dist import tree_bytes
            return int(tree_bytes(self.decoder.cache))
        except Exception:  # noqa: BLE001 — a view must never raise
            return 0

    def _timeline_labels(self, req: _DecodeRequest
                         ) -> "tuple[str, str]":
        """``(route, tenant)`` labels for the timeline histograms.
        Route is the server's decode path; the tenant label rides the
        tenancy registry's BoundedLabelSet so an unbounded tenant
        population collapses into 'other' instead of minting children
        without bound."""
        route = "decode"
        tenant = ANONYMOUS_ID
        srv = self._server
        if srv is not None:
            route = getattr(srv, "decode_path", None) or route
            ten = getattr(srv, "tenancy", None)
            tid = getattr(req.pending, "tenant", None)
            if ten is not None and tid:
                tenant = ten.label_of(tid)
        return route, tenant

    def _charge_device_ms(self, total_ms: float,
                          reqs: "Iterable[_DecodeRequest]") -> None:
        """Pro-rate one step/round/prefill's device wall-clock equally
        across the tenants whose requests rode it (each active slot
        advances one token per step — equal shares are the honest
        split). One counter inc per distinct tenant per step; tenant
        labels ride the tenancy registry's BoundedLabelSet via
        :meth:`_timeline_labels`."""
        if self._m_device is None or total_ms <= 0:
            return
        counts: "dict[str, int]" = {}
        n = 0
        for req in reqs:
            _, tenant = self._timeline_labels(req)
            counts[tenant] = counts.get(tenant, 0) + 1
            n += 1
        if not n:
            return
        share = total_ms / n
        for tenant, k in counts.items():
            self._m_device.labels(tenant).inc(share * k)

    # -- admission (any thread) ----------------------------------------------

    def overloaded(self) -> bool:
        return len(self._waiting) >= self.max_waiting

    def queue_pressure(self) -> "tuple[int, int]":
        """``(depth, capacity)`` of the waiting queue — the pressure
        signal priority-aware shedding evaluates."""
        return len(self._waiting), self.max_waiting

    def retry_after_hint(self) -> Optional[float]:
        """Honest decode-429 ``Retry-After`` from the slot-release
        EWMA scaled by the queue ahead; ``None`` while the EWMA is
        cold or stale (caller falls back to the constant)."""
        return self.release_ewma.retry_after(len(self._waiting))

    def parse(self, payload: Any
              ) -> "tuple[np.ndarray, int, Optional[Sampler], Optional[bool]]":
        """Payload -> (prompt tokens, max_new, sampler, speculative).
        Raises ValueError on anything the decode plane cannot serve
        (the caller 400s)."""
        if not isinstance(payload, dict):
            raise ValueError("decode payload must be a JSON object")
        prompt = payload.get("prompt")
        if not isinstance(prompt, list) or not prompt or \
                not all(isinstance(t, int) and not isinstance(t, bool)
                        and 0 <= t for t in prompt):
            # bool is an int subclass: [true, false] must 400, not
            # silently decode as tokens [1, 0]
            raise ValueError(
                'decode payload needs "prompt": [token ids] '
                '(non-empty list of non-negative ints)')
        if any(t >= self.decoder.cfg.vocab for t in prompt):
            raise ValueError(
                f"prompt token out of range (vocab "
                f"{self.decoder.cfg.vocab})")
        if len(prompt) >= self.decoder.max_len:
            raise ValueError(
                f"prompt length {len(prompt)} >= max_len "
                f"{self.decoder.max_len} (no room to generate)")
        max_new = payload.get("max_new_tokens",
                              self.max_new_tokens_default)
        if not isinstance(max_new, int) or isinstance(max_new, bool) \
                or max_new < 1:
            raise ValueError('"max_new_tokens" must be a positive int')
        # the cache lane bounds the sequence: clamp the budget to it
        max_new = min(max_new, self.decoder.max_len - len(prompt))
        spec = payload.get("speculative")
        if spec is not None and not isinstance(spec, bool):
            raise ValueError('"speculative" must be a boolean')
        stream = payload.get("stream")
        if stream is not None and not isinstance(stream, bool):
            raise ValueError('"stream" must be a boolean')
        return np.asarray(prompt, np.int32), max_new, \
            self._parse_sampling(payload), spec

    @staticmethod
    def _parse_sampling(payload: dict) -> Optional[Sampler]:
        """Request-selectable sampling: ``temperature`` (> 0 turns
        sampling on; 0/absent = greedy, the default), ``top_k``,
        ``top_p``, ``seed``. Bad values 400 like any other payload
        error."""
        temp = payload.get("temperature", 0)
        if isinstance(temp, bool) or not isinstance(temp, (int, float)) \
                or not np.isfinite(temp) or temp < 0:
            raise ValueError(
                '"temperature" must be a finite number >= 0 '
                '(0 = greedy)')
        top_k = payload.get("top_k", 0)
        if isinstance(top_k, bool) or not isinstance(top_k, int) \
                or top_k < 0:
            raise ValueError('"top_k" must be an int >= 0 (0 = off)')
        top_p = payload.get("top_p", 1.0)
        if isinstance(top_p, bool) or not isinstance(top_p, (int, float)) \
                or not 0.0 < float(top_p) <= 1.0:
            raise ValueError('"top_p" must be in (0, 1]')
        seed = payload.get("seed")
        if seed is not None and (isinstance(seed, bool)
                                 or not isinstance(seed, int)):
            raise ValueError('"seed" must be an int')
        if float(temp) == 0.0:
            if "temperature" not in payload and \
                    (int(top_k) > 0 or float(top_p) < 1.0):
                # EFFECTIVE knobs with temperature ABSENT: serve them
                # at temperature 1 rather than silently decoding
                # greedy. An EXPLICIT "temperature": 0 always wins —
                # 0 is documented as greedy, and overriding it to
                # unseeded T=1 sampling would hand the client exactly
                # the nondeterminism it asked to avoid. No-op values
                # (top_k: 0, top_p: 1.0 — both documented "off") stay
                # greedy either way.
                return Sampler(1.0, int(top_k), float(top_p), seed)
            return None
        return Sampler(float(temp), int(top_k), float(top_p), seed)

    def _pages_for(self, rows: int) -> int:
        """Pages covering virtual rows ``[0, rows)``."""
        ps = self.decoder.page_size
        return max((int(rows) + ps - 1) // ps, 1)

    def _claim_pages(self, n: int) -> Optional[List[int]]:
        """Claim ``n`` fresh pages, evicting LRU unreferenced cached
        pages first when the free list alone cannot cover it."""
        got = self.pages.claim(n)
        if got is None and self.prefix is not None:
            self.prefix.evict_for(n)
            got = self.pages.claim(n)
        return got

    def _release_pages(self, req: _DecodeRequest,
                       publish: bool) -> None:
        """Drop the request's page references. On a clean finish the
        prompt-complete pages are PUBLISHED into the prefix index
        (their reference transfers to the cache — a future prompt
        sharing the prefix attaches them instead of recomputing);
        everything else — shared-prefix refs, the partial prompt tail,
        generated-token pages — is released. Publication is refused
        for ``error`` finishes: a faulted step's cache state is
        suspect, and poisoning the index would wrong every future
        match."""
        pages, req.pages = req.pages, []
        absorbed = set()
        if self.prefix is not None and publish:
            absorbed = self.prefix.publish(
                req.prompt, pages,
                tenant=getattr(req.pending, "tenant", None))
        rest = [p for p in pages if p not in absorbed]
        if rest:
            self.pages.release(rest)

    def _spec_capable(self, req: _DecodeRequest) -> bool:
        """Whether this request may EVER enter a speculative cohort:
        explicit payload opt-in/out wins; greedy defaults on, sampled
        defaults off (rejection sampling changes seeded-PRNG
        consumption). Fixed for the request's lifetime — it decides
        the draft prefill at admission and the draft-cache catch-up
        obligation on non-speculative rounds."""
        if not self.decoder.has_draft:
            return False
        return (req.spec if req.spec is not None
                else req.sampler is None)

    def submit(self, pending, parsed=None) -> None:
        """Enqueue one admitted request (already past the server's
        replay/join/shed/doa checks). Raises ValueError on a bad
        payload (caller replies 400), DecodeOverloaded when the
        waiting queue is full OR the page pool cannot hold the prompt
        (caller replies 429 + Retry-After — page exhaustion is
        backpressure, never a mid-decode OOM). ``parsed`` lets a
        caller that already validated the payload (the streaming
        pre-check) pass its :meth:`parse` tuple instead of paying a
        second pass."""
        prompt, max_new, sampler, spec = (
            parsed if parsed is not None else self.parse(
                pending.payload))
        req = _DecodeRequest(pending, prompt, max_new, sampler, spec)
        req.t_submit = self.clock.now()
        if self.pages is not None:
            # admission-time page check: the prompt (plus the first
            # generated row) must fit the pool outright. Advisory —
            # running slots may grow before this request reaches a
            # slot, and _admit_waiting re-checks — but it turns a
            # full pool into an honest 429 instead of a queued
            # request that can never start.
            need = self._pages_for(len(prompt) + 1)
            # cache-full admission sheds BEFORE touching shared state:
            # cached pages count as reclaimable headroom (eviction
            # frees them at claim time), but no lookup, ref, or
            # eviction happens for a request that only sheds.
            # n_cached is the O(1) UPPER bound (pinned cached pages
            # are not really evictable) — an optimistic admit just
            # waits head-of-line like any page-tight request, which
            # this check is already advisory about.
            avail = self.pages.n_free + (
                self.prefix.n_cached if self.prefix is not None
                else 0)
            if avail < need:
                raise DecodeOverloaded(
                    f"decode page pool exhausted ({need} pages "
                    f"needed, {avail} free or evictable)")
        with self._lock:
            if len(self._waiting) >= self.max_waiting:
                raise DecodeOverloaded("decode waiting queue full")
            self._waiting.append(req)
            self._by_rid[pending.rid] = req
            self.n_requests += 1
        self._work.set()

    def cancel(self, rid: str) -> bool:
        """Flag a waiting or in-slot request cancelled; it resolves
        (partial tokens, ``finish_reason: "cancelled"``) and frees its
        slot at the next loop pass. Returns False for unknown rids."""
        with self._lock:
            req = self._by_rid.get(rid)
            if req is None:
                return False
            req.cancelled = True
        self._work.set()
        return True

    # -- resolution ----------------------------------------------------------

    @staticmethod
    def _standalone_commit(p) -> None:
        p.event.set()
        for cb in p.callbacks:
            try:
                cb(p)
            except Exception:  # noqa: BLE001 — mirror server._release
                logger.warning("reply callback failed", exc_info=True)

    def _now(self) -> float:
        return (self.tracer.clock.now() if self.tracer is not None
                else self.clock.now())

    def _add_span(self, req: _DecodeRequest, name: str, t0: float,
                  t1: float, status: str = "ok", **attrs) -> None:
        if self.tracer is not None and req.pending.span is not None:
            self.tracer.add(name, t0, t1, parent=req.pending.span,
                            status=status, **attrs)

    def _finish(self, req: _DecodeRequest, reason: str,
                status: int = 200,
                error: Optional[str] = None) -> None:
        """Resolve a request and free whatever it held — slot AND
        pages; EVERY exit path funnels here, so neither can leak."""
        if req.slot is not None:
            with self._lock:
                # under the lock so stats() can snapshot _active
                # against the loop thread's churn
                self._active.pop(req.slot, None)
            self._tokens[req.slot] = 0
            self._pos[req.slot] = 0
            if self._tables is not None:
                self._tables[req.slot, :] = 0
            self.pool.release(req.slot)
            self.release_ewma.note()
            t1 = self._now()
            self._add_span(req, "decode", req.t_decode, t1,
                           status="ok" if status == 200 else "error",
                           slot=req.slot, n_tokens=len(req.produced),
                           finish_reason=reason)
            req.slot = None
        if req.pages:
            self._release_pages(req, publish=reason != "error")
        with self._lock:
            self._by_rid.pop(req.pending.rid, None)
            self.releases[reason] = self.releases.get(reason, 0) + 1
        p = req.pending
        # token-level timeline: EVERY release reason lands in the
        # histograms — cancel/deadline/preempt/fault partial counts
        # included, so goodput can never undercount failure modes
        n = len(req.produced)
        clean = reason in ("eos", "length")
        if clean:
            self.n_goodput_tokens += n
        if self._m_tokens_req is not None:
            self._m_tokens_req.labels(reason).observe(float(n))
        if n > 0 and req.t_first > 0.0 and self._m_ttft is not None:
            route, tenant = self._timeline_labels(req)
            t_first = req.t_first
            # streamed replies prefer the SOCKET-EDGE stamp (first
            # chunk actually written to the client) — comparable to
            # t_submit only on the real monotonic clock
            s_edge = getattr(req.stream, "t_first", 0.0) or 0.0
            if s_edge > 0.0 and self.clock is SYSTEM_CLOCK:
                t_first = s_edge
            self._m_ttft.labels(route, tenant).observe(
                max(t_first - req.t_submit, 0.0) * 1000.0)
            if n >= 2 and req.t_last >= req.t_first:
                self._m_tpot.labels(route, tenant).observe(
                    (req.t_last - req.t_first) / (n - 1) * 1000.0)
        # emitted tokens billed to the owning tenant exactly once, at
        # resolution (partial emissions from preempts/faults included)
        tid = getattr(p, "tenant", None)
        if tid and req.produced and self._server is not None \
                and getattr(self._server, "tenancy", None) is not None:
            self._server.tenancy.note_tokens(tid, n)
            if clean:
                self._server.tenancy.note_goodput_tokens(tid, n)
        if status == 200:
            p.status = 200
            body = {"tokens": req.produced,
                    "n_tokens": len(req.produced),
                    "prompt_len": int(len(req.prompt)),
                    "finish_reason": reason}
            p.reply = json.dumps(body).encode()
        else:
            p.status = status
            body = {"error": error or reason,
                    "tokens": req.produced,
                    "n_tokens": len(req.produced),
                    "finish_reason": reason}
            p.reply = json.dumps(body).encode()
        stream = req.stream
        if stream is not None and not stream.closed:
            # the terminal SSE event mirrors the JSON reply (plus the
            # done marker) and ends the chunked body; the connection
            # returns to keep-alive. The journal still gets the plain
            # reply — a replayed rid is served non-streamed.
            stream.finish(b"data: " + json.dumps(
                dict(body, done=True)).encode() + b"\n\n")
        self._commit(p)

    # -- the loop ------------------------------------------------------------

    def start(self) -> "DecodeScheduler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="decode-scheduler")
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # the loop is stuck inside a prefill/step (hung device,
                # first-compile of a big model): finishing its in-slot
                # requests from HERE would race its own retirement path
                # — double slot releases, double commits. Leave them to
                # the daemon thread; stranded clients 504 at
                # request_timeout (the server stop() idiom).
                logger.warning(
                    "decode loop did not stop in %.1fs; leaving "
                    "in-flight slots to it", timeout)
                return
        # the loop is dead: resolve stragglers so no client hangs
        with self._lock:
            waiting = list(self._waiting)
            self._waiting.clear()
        for req in waiting:
            self._finish(req, "error", status=503,
                         error="decode scheduler stopping")
        for req in list(self._active.values()):
            self._finish(req, "error", status=503,
                         error="decode scheduler stopping")

    def _loop(self) -> None:
        while not self._stop.is_set():
            # dead waiters resolve EVERY pass, slots full or not: with
            # every slot pinned by long decodes, a cancelled/expired
            # waiter must still get its prompt reply (and stop counting
            # toward overloaded()) instead of rotting until the
            # frontend's request_timeout
            self._reap_waiting()
            self._admit_waiting()
            if not self._active:
                # fully idle (nothing waiting either) -> block until
                # submit()/cancel()/stop() wakes us, no 50 Hz poll;
                # with waiters held back by deadline-less slots the
                # short timeout keeps their deadlines honest
                self._work.wait(self.idle_wait_s
                                if self._waiting else None)
                self._work.clear()
                continue
            self._run_step()

    def _reap_waiting(self) -> None:
        with self._lock:
            if not self._waiting:
                return
            keep, dead = deque(), []
            for req in self._waiting:
                p = req.pending
                s = req.stream
                if req.cancelled or (p.deadline is not None
                                     and p.deadline.expired) \
                        or (s is not None and s.closed):
                    dead.append(req)
                else:
                    keep.append(req)
            self._waiting = keep
        for req in dead:
            if req.cancelled:
                self._finish(req, "cancelled")
            elif req.stream is not None and req.stream.closed:
                # the streaming client hung up before a slot was
                # claimed: never journaled (status != 200) so a retry
                # re-executes
                self._finish(req, "disconnected", status=500,
                             error="client disconnected")
            else:
                self._finish(req, "deadline", status=504,
                             error="deadline exceeded before decode")

    def _pop_waiting(self) -> Optional[_DecodeRequest]:
        """Next waiter to try for a slot. FIFO without tenancy; with
        fair-share on, a deficit-weighted round-robin across the
        tenants PRESENT in the queue picks whose oldest request goes
        next — a 10:1 flood from one tenant still leaves the victim
        claiming slots at its weighted share (the bounded-starvation
        guarantee lives in :class:`~mmlspark_tpu.serving.tenancy.
        FairCycle`)."""
        with self._lock:
            if not self._waiting:
                return None
            ten = (getattr(self._server, "tenancy", None)
                   if self._server is not None else None)
            if ten is None or not ten.fair_share \
                    or len(self._waiting) == 1:
                return self._waiting.popleft()
            present: Dict[str, float] = {}
            for r in self._waiting:
                tid = getattr(r.pending, "tenant", None) or ANONYMOUS_ID
                if tid not in present:
                    present[tid] = ten.weight_of(tid)
            if len(present) == 1:
                return self._waiting.popleft()
            pick = self._fair.choose(present)
            for i, r in enumerate(self._waiting):
                if (getattr(r.pending, "tenant", None)
                        or ANONYMOUS_ID) == pick:
                    del self._waiting[i]
                    return r
            return self._waiting.popleft()

    def _admit_waiting(self) -> None:
        """Between steps: claim free slots (and, paged, the prompt's
        pages) for waiting requests — one prefill each. Cancelled/
        expired/disconnected waiters resolve WITHOUT ever claiming
        anything; a head-of-queue request the page pool cannot hold
        yet WAITS (admission order preserved — pages free as running
        requests finish)."""
        while self.pool.n_free > 0:
            req = self._pop_waiting()
            if req is None:
                return
            p = req.pending
            if req.cancelled:
                self._finish(req, "cancelled")
                continue
            if p.deadline is not None and p.deadline.expired:
                self._finish(req, "deadline", status=504,
                             error="deadline exceeded before decode")
                continue
            s = req.stream
            if s is not None and s.closed:
                self._finish(req, "disconnected", status=500,
                             error="client disconnected")
                continue
            pages: List[int] = []
            hit_len = 0
            if self.pages is not None:
                shared: List[int] = []
                if self.prefix is not None:
                    # longest cached prefix: matched pages arrive
                    # ref'd — on any bail-out below they are released
                    # (the cache keeps its own reference)
                    hit_len, shared = self.prefix.lookup(req.prompt)
                own = self._claim_pages(
                    self._pages_for(len(req.prompt) + 1) - len(shared))
                if own is None:
                    # not enough pages YET: head-of-line waits for
                    # running requests to release theirs (it looks up
                    # afresh next pass — the hit ledger only counts
                    # ADMITTED requests, so retry ticks cost nothing)
                    if shared:
                        self.pages.release(shared)
                    with self._lock:
                        self._waiting.appendleft(req)
                    return
                pages = shared + own
            slot = self.pool.claim()
            if slot is None:      # raced a concurrent release? retry
                if pages:
                    self.pages.release(pages)
                with self._lock:
                    self._waiting.appendleft(req)
                return
            if self.prefix is not None:
                # one monotonic hit-ledger bump per ADMITTED request
                self.prefix.count(hit_len)
            t0 = self._now()
            self._add_span(req, "queue_wait", req.t_submit, t0)
            if self._m_queue_wait is not None:
                self._m_queue_wait.labels().observe(
                    (t0 - req.t_submit) * 1000.0)
            table = None
            if self._tables is not None:
                self._tables[slot, :] = 0
                self._tables[slot, :len(pages)] = pages
                table = self._tables[slot]
            try:
                if self.fault_plan is not None:
                    self.fault_plan.raise_at("decode_prefill",
                                             clock=self.clock)
                if hit_len > 0:
                    first, last_logits = \
                        self.decoder.prefill_prefix_logits(
                            slot, req.prompt, hit_len, table,
                            draft=self._spec_capable(req))
                else:
                    first, last_logits = self.decoder.prefill_logits(
                        slot, req.prompt, table,
                        draft=self._spec_capable(req))
                if req.sampler is not None:
                    # the request's own seeded PRNG picks the first
                    # generated token from the prompt's last logits
                    first = req.sampler.sample(np.asarray(last_logits))
            except Exception as e:  # noqa: BLE001 — injected or real
                self.pool.release(slot)
                if pages:
                    self.pages.release(pages)
                if self._tables is not None:
                    self._tables[slot, :] = 0
                self._add_span(req, "prefill", t0, self._now(),
                               status="error")
                self._finish(req, "error", status=500,
                             error=f"prefill failed: {e}")
                continue
            t1 = self._now()
            req.t_prefill = t1
            req.t_decode = t1
            self.n_prefills += 1
            self.n_prompt_tokens += len(req.prompt)
            self.prefill_s += t1 - t0
            if self._m_prefill is not None:
                self._m_prefill.labels(
                    bucket_target(len(req.prompt),
                                  self.decoder.max_len)).observe(
                    (t1 - t0) * 1000.0)
            # prefill runs ONE request: its whole wall time is that
            # request's tenant's device time
            self._charge_device_ms((t1 - t0) * 1000.0, (req,))
            self._add_span(req, "prefill", t0, t1, slot=slot,
                           prompt_len=len(req.prompt),
                           prefix_hit=hit_len)
            req.slot = slot
            req.pages = pages
            req.hit_len = hit_len
            req.produced.append(first)
            self.n_tokens += 1
            # the first token exists HERE (prefill emits it): stamp
            # both timeline marks and drop the instant event on the
            # request's span so /trace/<id> shows the cadence start
            req.t_first = t1
            req.t_last = t1
            if req.n_timeline < _MAX_TIMELINE_SPANS:
                req.n_timeline += 1
                self._add_span(
                    req, "first_token", t1, t1,
                    ttft_ms=round((t1 - req.t_submit) * 1000.0, 3))
            self._tokens[slot] = first
            self._pos[slot] = len(req.prompt)
            with self._lock:
                self._active[slot] = req
                if len(self._active) > self.slots_high_water:
                    self.slots_high_water = len(self._active)
            self._emit_stream(req, [first])
            self._retire_if_done(req, first)

    def _retire_if_done(self, req: _DecodeRequest, tok: int) -> bool:
        """Post-token finish checks, cheapest terminal first."""
        eos = self.decoder.eos_id
        if eos is not None and tok == eos:
            self._finish(req, "eos")
            return True
        if len(req.produced) >= req.max_new:
            self._finish(req, "length")
            return True
        if req.slot is not None and \
                int(self._pos[req.slot]) >= self.decoder.max_len - 1:
            self._finish(req, "length")   # cache lane exhausted
            return True
        if req.cancelled:
            self._finish(req, "cancelled")
            return True
        s = req.stream
        if s is not None and s.closed:
            self._finish(req, "disconnected", status=500,
                         error="client disconnected mid-stream")
            return True
        p = req.pending
        if p.deadline is not None and p.deadline.expired:
            self._finish(req, "deadline", status=504,
                         error="deadline exceeded mid-decode")
            return True
        return False

    def _emit_stream(self, req: _DecodeRequest, toks) -> None:
        """Incremental token delivery for a streaming request: one SSE
        event per emitted token (speculative rounds emit a small
        burst). No-op for non-streamed requests and closed streams."""
        s = req.stream
        if s is None or s.closed:
            return
        base = len(req.produced) - len(toks)
        for off, tok in enumerate(toks):
            s.emit(b'data: {"token": %d, "i": %d}\n\n'
                   % (int(tok), base + off))

    def _ensure_pages(self, req: _DecodeRequest, upto_pos: int) -> bool:
        """Grow ``req``'s page table to cover virtual row
        ``upto_pos``; False when the pool cannot (caller decides:
        preempt for the step's own row, degrade to non-speculative
        for lookahead rows)."""
        need = self._pages_for(upto_pos + 1)
        have = len(req.pages)
        if need <= have:
            return True
        # growth evicts unreferenced cached pages before giving up:
        # live decodes always outrank cache residency
        got = self._claim_pages(need - have)
        if got is None:
            return False
        self._tables[req.slot, have:need] = got
        req.pages.extend(got)
        return True

    def _prepare_round(self):
        """Pre-step upkeep: reap dead slots, grow pages for every
        live slot's next row (preempting — finish_reason
        ``pages_exhausted`` — when the pool is dry), and pick the
        speculative cohort (spec-enabled slots whose lookahead window
        fits their lane and the pool). Returns the cohort dict."""
        for req in list(self._active.values()):
            p = req.pending
            s = req.stream
            if req.cancelled:
                self._finish(req, "cancelled")
            elif s is not None and s.closed:
                self._finish(req, "disconnected", status=500,
                             error="client disconnected mid-stream")
            elif p.deadline is not None and p.deadline.expired:
                self._finish(req, "deadline", status=504,
                             error="deadline exceeded mid-decode")
        if self.pages is not None:
            for slot, req in list(self._active.items()):
                if not self._ensure_pages(req, int(self._pos[slot])):
                    # the pool cannot hold this slot's NEXT row: the
                    # request ends with its partial output rather
                    # than corrupt anyone — never a mid-decode OOM
                    self.n_page_preempts += 1
                    self._finish(req, "pages_exhausted")
        spec: Dict[int, _DecodeRequest] = {}
        if self.decoder.has_draft:
            if self.spec_policy is not None \
                    and not self.spec_policy.should_speculate():
                # acceptance collapsed below break-even: single steps
                # until a probe round says the workload turned
                # draft-friendly again
                return spec
            k = self.decoder.spec_k
            for slot, req in self._active.items():
                if not self._spec_capable(req):
                    continue
                if int(self._pos[slot]) + k >= self.decoder.max_len:
                    continue          # lane end: single steps finish it
                if not self._ensure_pages(
                        req, int(self._pos[slot]) + k - 1):
                    continue          # pool tight: degrade, not block
                spec[slot] = req
        return spec

    def _run_step(self) -> None:
        spec = self._prepare_round()
        if not self._active:
            return
        if spec:
            self._run_spec_round(spec)
            return
        t0 = self._now()
        try:
            if self.fault_plan is not None:
                self.fault_plan.raise_at("decode_step",
                                         clock=self.clock)
            out, step_logits = self.decoder.step_logits(
                self._tokens, self._pos, self._tables)
        except Exception as e:  # noqa: BLE001 — injected or real
            # a failed step loses the affected requests (500, never
            # journaled — clients may retry) but NEVER a slot or page
            self.n_step_faults += 1
            logger.warning("decode step failed; failing %d in-slot "
                           "requests", len(self._active), exc_info=True)
            for req in list(self._active.values()):
                self._finish(req, "error", status=500,
                             error=f"decode step failed: {e}")
            return
        t1 = self._now()
        self.n_steps += 1
        if self._m_step is not None:
            self._m_step.labels().observe((t1 - t0) * 1000.0)
        self._charge_device_ms((t1 - t0) * 1000.0,
                               self._active.values())
        if self.decoder.has_draft and any(
                self._spec_capable(r) for r in self._active.values()):
            # draft-cache catch-up: a spec-capable slot stepping
            # WITHOUT the draft (policy suppression, page-tight
            # degradation, lane-end neighbours) would leave holes in
            # its draft lane, and a later probe round would propose
            # from garbage — acceptance would never recover. One cheap
            # draft step per plain round (same inputs/positions as the
            # target step) keeps both caches in lockstep; the draft's
            # token outputs are discarded.
            try:
                self.decoder.draft_step_logits(self._tokens, self._pos)
            except Exception:  # noqa: BLE001 — the draft is advisory:
                logger.warning(  # a broken draft must not fail decode
                    "draft catch-up step failed", exc_info=True)
        # one host fetch of the full [n_slots, vocab] logits per step,
        # paid ONLY while a sampling request is in a slot — pure-greedy
        # batches keep the token-only transfer
        logits_np = None
        if any(r.sampler is not None for r in self._active.values()):
            logits_np = np.asarray(step_logits)
        for slot, req in list(self._active.items()):
            tok = (int(out[slot]) if req.sampler is None
                   else req.sampler.sample(logits_np[slot]))
            req.produced.append(tok)
            self.n_tokens += 1
            req.t_last = t1          # one store/token: the TPOT stamp
            self._pos[slot] += 1
            self._tokens[slot] = tok
            self._emit_stream(req, [tok])
            self._retire_if_done(req, tok)

    def _run_spec_round(self, spec: Dict[int, _DecodeRequest]) -> None:
        """One speculative round: draft proposes ``spec_k`` tokens per
        slot, the target verifies them in ONE width-k pass, and each
        speculative slot accepts its longest agreeing prefix (exact
        argmax match for greedy slots, Leviathan rejection sampling
        for sampled opt-ins). Non-speculative slots ride the verify
        and consume only its first position — exactly a single step
        for them (their lookahead writes land on scratch/overwritten
        rows by construction)."""
        k = self.decoder.spec_k
        sampled_spec = [s for s, r in spec.items()
                        if r.sampler is not None]
        t0 = self._now()
        try:
            if self.fault_plan is not None:
                self.fault_plan.raise_at("decode_step",
                                         clock=self.clock)
            if not sampled_spec:
                # the fast path: k chained greedy draft steps in ONE
                # device program — one host round-trip per round
                props = self.decoder.propose(self._tokens, self._pos)
                draft_probs = None
            else:
                # sampled proposals need per-step draft distributions
                # on host: k separate draft steps, each slot drawing
                # from its own transformed draft distribution with
                # its own PRNG
                props = np.zeros((self.decoder.n_slots, k), np.int32)
                draft_probs: Dict[int, list] = {s: [] for s in
                                                sampled_spec}
                cur = self._tokens.copy()
                for j in range(k):
                    nxt, dlogits = self.decoder.draft_step_logits(
                        cur, self._pos + j)
                    dl_np = np.asarray(dlogits)
                    for s in range(self.decoder.n_slots):
                        if s in draft_probs:
                            q = spec[s].sampler.probs(dl_np[s])
                            draft_probs[s].append(q)
                            props[s, j] = spec[s].sampler.draw(q)
                        else:
                            props[s, j] = int(nxt[s])
                    cur = props[:, j].copy()
            ver_in = np.concatenate(
                [self._tokens[:, None], props[:, :k - 1]],
                axis=1).astype(np.int32)
            out_tok, ver_logits, ver_scores = \
                self.decoder.verify_logits(ver_in, self._pos,
                                           self._tables)
        except Exception as e:  # noqa: BLE001 — injected or real
            self.n_step_faults += 1
            logger.warning("speculative round failed; failing %d "
                           "in-slot requests", len(self._active),
                           exc_info=True)
            for req in list(self._active.values()):
                self._finish(req, "error", status=500,
                             error=f"decode step failed: {e}")
            return
        t1 = self._now()
        self.n_spec_rounds += 1
        if self._m_spec_round is not None:
            self._m_spec_round.labels().observe((t1 - t0) * 1000.0)
        self._charge_device_ms((t1 - t0) * 1000.0,
                               self._active.values())
        logits_np = None
        if any(r.sampler is not None
               for r in self._active.values()):
            logits_np = np.asarray(ver_logits)
        if spec:
            # per-proposal target log-probs from the verify's fused-CE
            # (or XLA) score head: the acceptance-QUALITY signal —
            # acceptance counts say how often the draft agreed,
            # this says how close the misses were
            sl = sorted(spec)
            mean_logp = float(np.mean(ver_scores[sl]))
            prev = self.spec_proposal_logp
            self.spec_proposal_logp = (
                mean_logp if prev is None
                else 0.8 * prev + 0.2 * mean_logp)
        round_proposed = round_accepted = 0
        for slot, req in list(self._active.items()):
            if slot not in spec:
                # non-speculative rider: position 0 of the verify IS
                # its single step
                tok = (int(out_tok[slot, 0]) if req.sampler is None
                       else req.sampler.sample(logits_np[slot, 0]))
                self._accept_tokens(req, slot, [tok], t_emit=t1)
                continue
            self.n_spec_proposed += k
            round_proposed += k
            acc_before = round_accepted
            emitted: List[int] = []
            if req.sampler is None:
                for j in range(k):
                    tgt = int(out_tok[slot, j])
                    emitted.append(tgt)
                    if int(props[slot, j]) != tgt:
                        break
                    self.n_spec_accepted += 1
                    round_accepted += 1
            else:
                smp = req.sampler
                for j in range(k):
                    d = int(props[slot, j])
                    p_t = smp.probs(logits_np[slot, j])
                    q_d = draft_probs[slot][j]
                    accept = (q_d[d] > 0.0 and
                              smp.uniform() <= min(
                                  1.0, float(p_t[d] / q_d[d])))
                    if accept:
                        emitted.append(d)
                        self.n_spec_accepted += 1
                        round_accepted += 1
                        continue
                    resid = np.maximum(p_t - q_d, 0.0)
                    tot = resid.sum()
                    emitted.append(smp.draw(resid / tot) if tot > 0
                                   else smp.draw(p_t))
                    break
            # per-round timeline span: the token cadence a /trace/<id>
            # tree shows (bounded per request — see _MAX_TIMELINE_SPANS)
            if req.n_timeline < _MAX_TIMELINE_SPANS:
                req.n_timeline += 1
                self._add_span(req, "spec_round", t0, t1,
                               proposed=k,
                               accepted=round_accepted - acc_before,
                               emitted=len(emitted))
            self._accept_tokens(req, slot, emitted, t_emit=t1)
        if self.spec_policy is not None:
            self.spec_policy.note(round_proposed, round_accepted)

    def _accept_tokens(self, req: _DecodeRequest, slot: int,
                       toks: List[int],
                       t_emit: Optional[float] = None) -> None:
        """Fold a burst of emitted tokens into the slot's state,
        stopping at the first terminal condition (EOS / budget / lane
        end / cancel / deadline) — unconsumed acceptances beyond a
        terminal are dropped, their cache rows repaired by later
        writes like any rejected proposal."""
        if t_emit is not None:
            req.t_last = t_emit      # whole burst emitted at one wall
        for tok in toks:
            tok = int(tok)
            req.produced.append(tok)
            self.n_tokens += 1
            self._pos[slot] += 1
            self._tokens[slot] = tok
            self._emit_stream(req, [tok])
            if self._retire_if_done(req, tok):
                break

    # -- observability -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            # snapshot under the lock: the loop thread churns _active
            # and the release ledger while scrapes read them
            waiting = len(self._waiting)
            active = sorted(self._active.items())
            releases = dict(self.releases)
        slots = [{"slot": s,
                  "rid": r.pending.rid,
                  "prompt_len": int(len(r.prompt)),
                  "n_tokens": len(r.produced),   # incremental progress
                  "max_new_tokens": r.max_new,
                  "n_pages": len(r.pages),
                  "prefix_hit_tokens": r.hit_len,
                  "streaming": r.stream is not None,
                  "sampling": (r.sampler.describe()
                               if r.sampler is not None else None)}
                 for s, r in active]
        pages = None
        if self.pages is not None:
            from mmlspark_tpu.parallel.dist import tree_bytes
            claimable = self.pages.n_pages - 1
            free = self.pages.n_free
            cached = (self.prefix.n_cached
                      if self.prefix is not None else 0)
            pages = {"page_size": self.decoder.page_size,
                     "n_pages": claimable,
                     "free": free,
                     # pages live requests hold (shared prefix pages
                     # count once however many readers share them)
                     "in_use": claimable - free - cached,
                     "cached": cached,
                     "high_water": self.pages.high_water,
                     "n_preempts": self.n_page_preempts,
                     "pool_bytes": tree_bytes(self.decoder.cache),
                     "per_slot": {str(s): len(r.pages)
                                  for s, r in active}}
        spec = None
        if self.decoder.has_draft:
            proposed = self.n_spec_proposed
            spec = {"k": self.decoder.spec_k,
                    "draft_layers": self.decoder.draft_cfg.n_layers,
                    "rounds": self.n_spec_rounds,
                    "proposed": proposed,
                    "accepted": self.n_spec_accepted,
                    "acceptance_rate": (
                        round(self.n_spec_accepted / proposed, 4)
                        if proposed else None),
                    "proposal_logp_ewma": (
                        round(self.spec_proposal_logp, 4)
                        if self.spec_proposal_logp is not None
                        else None),
                    "verify_ce_impl": self.decoder.verify_ce_impl,
                    "policy": (self.spec_policy.status()
                               if self.spec_policy is not None
                               else None)}
        return {"n_slots": self.decoder.n_slots,
                "slots_in_use": len(slots),
                "slots_free": self.pool.n_free,
                "slots_high_water": self.slots_high_water,
                "max_len": self.decoder.max_len,
                "paged": self.decoder.paged,
                # the decode-step gather engine: "pallas" = the fused
                # block-table kernel, "dense" = the materialized-lane
                # gather (CPU/mesh fallback)
                "attn_impl": self.decoder.attn_impl,
                # the prefill engine rides the same selection: under
                # "pallas" the cold prefills run streaming flash
                # attention (no [S, S] scores) and the prefix prefill
                # the fused block-table kernel (no [S, V] lane); the
                # non-paged decoder pins prefill to "dense"
                "attn_impl_prefill": (
                    self.decoder.attn_impl if self.decoder.paged
                    else "dense"),
                # int8-compute FFN: True when the served tree carries
                # quantize_decode_ffn's int8 weights + scale vectors
                "quantized_ffn": getattr(self.decoder,
                                         "quantized_ffn", False),
                "pages": pages,
                # the cross-request prefix cache (None = disabled):
                # radix hit counters, resident/evictable pages, and
                # the refcount ledger verdict
                "prefix_cache": (self.prefix.stats()
                                 if self.prefix is not None else None),
                "speculative": spec,
                "placement": self.decoder.placement(),
                "waiting": waiting,
                "max_waiting": self.max_waiting,
                "n_requests": self.n_requests,
                "n_steps": self.n_steps,
                "n_tokens": self.n_tokens,
                # goodput: tokens from requests that resolved cleanly
                # (eos/length) vs everything emitted — cancelled/
                # deadline/preempted work is real device time wasted
                "goodput": {
                    "tokens": self.n_goodput_tokens,
                    "total_tokens": self.n_tokens,
                    "ratio": (round(self.n_goodput_tokens
                                    / self.n_tokens, 4)
                              if self.n_tokens else None)},
                "n_prefills": self.n_prefills,
                "n_prompt_tokens": self.n_prompt_tokens,
                "prefill_s": round(self.prefill_s, 4),
                # prompt tokens served per prefill wall second —
                # cached-prefix tokens count (the cache shrinks the
                # denominator), so this is the prefix-cache A/B metric
                "prefill_tokens_per_s": (
                    round(self.n_prompt_tokens / self.prefill_s, 1)
                    if self.prefill_s > 0 else None),
                "n_step_faults": self.n_step_faults,
                "n_compiles": self.decoder.n_compiles(),
                # the live honest-429 inputs: slot-release gap EWMA
                # and the Retry-After a shed client would be told now
                # (None while the EWMA is cold — constant fallback)
                "release_gap_s": (
                    round(self.release_ewma.gap_s(), 4)
                    if self.release_ewma.gap_s() is not None else None),
                "retry_after_hint": (
                    round(self.retry_after_hint(), 4)
                    if self.retry_after_hint() is not None else None),
                "releases": releases,
                "active": slots}
