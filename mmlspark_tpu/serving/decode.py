"""Continuous batching for autoregressive decode.

The frame-serving plane (server.py) dispatches whole shape-bucketed
batches: right for stateless models, wrong for autoregressive decode,
where requests have private growing state (a KV cache) and finish at
different times — batching whole requests would hold every member
until the slowest one's last token. This module batches at the *slot*
level instead:

* a :class:`TransformerDecoder` owns ONE preallocated slot-indexed
  KV-cache pool (``models/transformer.init_kv_cache``) plus the jitted
  prefill/step functions built over it — fixed shapes, donated cache,
  so a warm decode loop performs **zero device allocations and zero
  retraces** however requests churn;
* a :class:`DecodeScheduler` runs the step loop: between any two
  decode steps, waiting requests claim free slots (one bucketed
  prefill each), finished requests (EOS / token budget / cache-lane
  end / deadline / cancel) release theirs, and the single-token step
  always runs over the full fixed ``[n_slots]`` batch. The loop never
  stops or retraces while traffic flows — joiners splice in between
  steps, leavers just return an index.

Requests ride the server's existing admission machinery
(:class:`~mmlspark_tpu.serving.server.ServingServer` routes its
``decode_path`` here): replay/join/shed/deadline semantics, the reply
journal, root spans, and the trace id all behave exactly as on the
frame plane. Tokens are emitted incrementally into the request's
in-flight state (visible via ``GET /decode/stats``); the reply carries
the full sequence once the request leaves its slot.

Observability: slot occupancy, decode steps, per-token counters,
prefill/step latency histograms, and queue-wait all land in the
server's registry (``docs/observability.md`` "Decode metrics"); every
request's trace shows ``queue_wait``/``prefill``/``decode`` children
under its root. Chaos: a ``fault_plan`` drives the ``decode_prefill``
and ``decode_step`` sites — an injected step fault 500s the affected
requests but **never strands a slot** (tests/test_serving_decode.py).
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.logs import get_logger
from mmlspark_tpu.core.resilience import SYSTEM_CLOCK, Clock
from mmlspark_tpu.parallel.sharding import bucket_target

logger = get_logger("serving.decode")


class DecodeOverloaded(RuntimeError):
    """The waiting queue is full: new decode work must shed (429)."""


class TransformerDecoder:
    """The model side of continuous batching: one KV pool + the jitted
    prefill/step pair over it, with host-side bookkeeping.

    Not thread-safe by design — exactly one :class:`DecodeScheduler`
    loop thread drives it (the cache is DONATED through every call;
    two concurrent calls would race one buffer). ``eos_id`` is the
    stop token (None = never stops early; requests end on their token
    budget). ``warmup()`` compiles the step and every prompt bucket;
    after it, :meth:`n_compiles` staying flat is the zero-retrace
    evidence the bench gates on."""

    def __init__(self, params, cfg, n_slots: int = 8,
                 max_len: int = 256, eos_id: Optional[int] = None,
                 donate: bool = True, mesh=None):
        from mmlspark_tpu.models import transformer as T
        self.cfg = cfg
        self.n_slots = int(n_slots)
        self.max_len = int(max_len)
        self.eos_id = eos_id
        self.mesh = mesh
        self.cache = T.init_kv_cache(cfg, self.n_slots, self.max_len)
        cache_sharding = None
        if mesh is not None:
            # tensor-parallel decode: ONE model + ONE KV pool span the
            # mesh — heads/MLP-hidden shard over the model axis
            # (decode_param_specs), each device's cache holds exactly
            # its heads' lanes (decode_cache_spec). The jitted pair
            # below compiles the SAME program as sharded computations;
            # shapes, donation, and compile-once are unchanged.
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            is_spec = lambda x: isinstance(x, PartitionSpec)  # noqa: E731
            p_sh = jax.tree.map(
                lambda s: NamedSharding(mesh, s),
                T.decode_param_specs(cfg, mesh), is_leaf=is_spec)
            params = jax.device_put(params, p_sh)
            cache_sharding = NamedSharding(mesh,
                                           T.decode_cache_spec(mesh))
            self.cache = jax.device_put(self.cache, cache_sharding)
        self.params = params
        self._prefill = T.build_prefill(cfg, donate=donate,
                                        cache_sharding=cache_sharding)
        self._step = T.build_decode_step(cfg, self.n_slots,
                                         self.max_len, donate=donate,
                                         cache_sharding=cache_sharding)

    def placement(self) -> Dict[str, Any]:
        """Where this decoder's params + KV pool live (the
        ``/decode/stats`` placement surface)."""
        if self.mesh is None:
            return {"mode": "single_device", "n_devices": 1}
        from mmlspark_tpu.parallel import dist
        out = {"mode": "tensor_parallel",
               "label": dist.placement_label(self.mesh)}
        out.update(dist.placement_report(
            {"params": self.params, "cache": self.cache}, self.mesh))
        return out

    # -- shapes --------------------------------------------------------------

    def prompt_buckets(self) -> List[int]:
        """The prefill shape ladder: pow2 buckets clamped at
        ``max_len`` (same policy as the frame plane's batch buckets —
        one ladder idiom framework-wide)."""
        return sorted({bucket_target(n, self.max_len)
                       for n in range(1, self.max_len + 1)})

    def pad_prompt(self, prompt: np.ndarray) -> np.ndarray:
        bucket = bucket_target(len(prompt), self.max_len)
        out = np.zeros(bucket, np.int32)
        out[:len(prompt)] = prompt
        return out

    # -- compute -------------------------------------------------------------

    def prefill_logits(self, slot: int, prompt: np.ndarray
                       ) -> "tuple[int, Any]":
        """Fill ``slot``'s cache lane from ``prompt``; returns the
        first generated greedy token AND the last-position logits (a
        device array — only a sampling caller pays the host fetch)."""
        import jax.numpy as jnp
        padded = self.pad_prompt(prompt)
        self.cache, nxt, logits = self._prefill(
            self.params, self.cache, jnp.asarray(padded),
            np.int32(slot), np.int32(len(prompt)))
        return int(nxt), logits

    def prefill(self, slot: int, prompt: np.ndarray) -> int:
        """Greedy :meth:`prefill_logits` (compat surface)."""
        return self.prefill_logits(slot, prompt)[0]

    def step_logits(self, tokens: np.ndarray, pos: np.ndarray
                    ) -> "tuple[np.ndarray, Any]":
        """One token for every slot: ``tokens``/``pos`` are the full
        fixed ``[n_slots]`` arrays (free slots ride along at token 0 /
        pos 0). Returns greedy next tokens plus the full per-slot
        logits (device array; fetched only when a sampler needs it)."""
        import jax.numpy as jnp
        self.cache, nxt, logits = self._step(
            self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(pos))
        return np.asarray(nxt), logits

    def step(self, tokens: np.ndarray, pos: np.ndarray) -> np.ndarray:
        """Greedy :meth:`step_logits` (compat surface)."""
        return self.step_logits(tokens, pos)[0]

    def n_compiles(self) -> int:
        """Compiled-executable count across prefill buckets + the step
        (jit cache sizes): flat after warmup = zero retraces."""
        return int(self._prefill._cache_size()
                   + self._step._cache_size())

    def warmup(self) -> int:
        """Compile the decode step and every prefill bucket before
        traffic (the cache content it writes is garbage on a FREE
        slot's lane, which the next real prefill overwrites). Returns
        the compile count — the post-warmup baseline."""
        zeros_t = np.zeros(self.n_slots, np.int32)
        self.step(zeros_t, zeros_t.copy())
        for bucket in self.prompt_buckets():
            self.prefill(0, np.zeros(min(bucket, self.max_len - 1),
                                     np.int32))
        return self.n_compiles()


class Sampler:
    """Per-request seeded token sampling over the step's full logits.

    Greedy decode stays the device-side argmax (no logits transfer);
    a request that asks for ``temperature > 0`` gets temperature /
    top-k / nucleus (top-p) sampling on host from its slot's logits
    row, driven by its own ``numpy`` PRNG — so one ``seed`` makes a
    sampled decode bit-for-bit reproducible whatever other requests
    share the batch (slot independence extends to randomness)."""

    __slots__ = ("temperature", "top_k", "top_p", "seed", "_rng")

    def __init__(self, temperature: float, top_k: int = 0,
                 top_p: float = 1.0, seed: Optional[int] = None):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = seed
        self._rng = np.random.default_rng(seed)

    def sample(self, logits: np.ndarray) -> int:
        l = logits.astype(np.float64) / max(self.temperature, 1e-6)
        if 0 < self.top_k < l.size:
            kth = np.partition(l, -self.top_k)[-self.top_k]
            l = np.where(l < kth, -np.inf, l)
        l = l - l.max()
        p = np.exp(l)
        p /= p.sum()
        if self.top_p < 1.0:
            order = np.argsort(-p, kind="stable")
            cum = np.cumsum(p[order])
            # smallest prefix whose mass reaches top_p (>= 1 token)
            keep = int(np.searchsorted(cum, self.top_p)) + 1
            mask = np.zeros(p.size, bool)
            mask[order[:keep]] = True
            p = np.where(mask, p, 0.0)
            p /= p.sum()
        return int(self._rng.choice(p.size, p=p))

    def describe(self) -> Dict[str, Any]:
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed}


class SlotPool:
    """Free-slot index pool. Claim/release are O(1) under one lock;
    the scheduler loop is the only claimer, but cancel paths and tests
    read ``n_free`` concurrently."""

    def __init__(self, n_slots: int):
        self.n_slots = int(n_slots)
        self._free = list(range(self.n_slots - 1, -1, -1))
        self._lock = threading.Lock()

    def claim(self) -> Optional[int]:
        with self._lock:
            return self._free.pop() if self._free else None

    def release(self, slot: int) -> None:
        with self._lock:
            if slot in self._free:
                raise RuntimeError(f"slot {slot} double-released")
            self._free.append(slot)

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)


class _DecodeRequest:
    """Per-request decode state, riding alongside the server's
    ``_PendingRequest`` (``pending`` — reply/status/event/callbacks/
    deadline/trace/span all live there)."""

    __slots__ = ("pending", "prompt", "max_new", "produced", "slot",
                 "cancelled", "t_submit", "t_prefill", "t_decode",
                 "sampler")

    def __init__(self, pending, prompt: np.ndarray, max_new: int,
                 sampler: Optional[Sampler] = None):
        self.pending = pending
        self.prompt = prompt
        self.max_new = int(max_new)
        self.sampler = sampler
        self.produced: List[int] = []       # incremental emission
        self.slot: Optional[int] = None
        self.cancelled = False
        self.t_submit: float = 0.0
        self.t_prefill: float = 0.0
        self.t_decode: float = 0.0


class DecodeScheduler:
    """The continuous-batching step loop.

    ``submit()`` (any thread) parses and enqueues; the loop thread
    admits waiting requests into free slots between steps, runs the
    fixed-shape decode step while any slot is live, and resolves
    requests through the server's commit path (journal + spans +
    waiter release) — or a standalone default when unbound (direct
    scheduler tests).

    Slot lifecycle (docs/serving.md "Continuous batching"):

    ``waiting -> prefill(slot claimed) -> stepping -> released`` on
    the first of: EOS, ``max_new_tokens`` produced, cache lane full
    (``max_len``), deadline expired, cancel, or an injected/real step
    fault. Every exit path releases the slot — the slot-leak chaos
    test churns all of them and asserts ``n_free == n_slots`` after.
    """

    def __init__(self, decoder: TransformerDecoder,
                 max_waiting: int = 256,
                 max_new_tokens_default: int = 64,
                 clock: Clock = SYSTEM_CLOCK,
                 fault_plan=None,
                 registry=None, tracer=None,
                 idle_wait_s: float = 0.02):
        self.decoder = decoder
        self.max_waiting = int(max_waiting)
        self.max_new_tokens_default = int(max_new_tokens_default)
        self.clock = clock
        self.fault_plan = fault_plan
        self.tracer = tracer
        self.idle_wait_s = float(idle_wait_s)
        self.pool = SlotPool(decoder.n_slots)
        self._waiting: deque = deque()
        self._by_rid: Dict[str, _DecodeRequest] = {}
        self._active: Dict[int, _DecodeRequest] = {}
        self._tokens = np.zeros(decoder.n_slots, np.int32)
        self._pos = np.zeros(decoder.n_slots, np.int32)
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # resolved by bind(); standalone default releases the pending
        # directly (event + callbacks), no journal
        self._commit: Callable[[Any], None] = self._standalone_commit
        self.n_requests = 0
        self.n_steps = 0
        self.n_tokens = 0
        self.n_prefills = 0
        self.n_step_faults = 0
        self.releases: Dict[str, int] = {}   # finish_reason -> count
        self._m_prefill = None
        self._m_step = None
        self._m_queue_wait = None
        if registry is not None:
            self._register_metrics(registry)

    # -- wiring --------------------------------------------------------------

    def bind(self, server) -> None:
        """Attach to a :class:`ServingServer`: its registry, tracer,
        clock, and commit path (journaled exactly-once replies) become
        this scheduler's."""
        self.clock = server.clock
        self.tracer = server.tracer
        self._commit = server._commit
        self._register_metrics(server.registry)

    def _register_metrics(self, m) -> None:
        m.gauge("serving_decode_slots_in_use",
                "KV-cache slots currently decoding."
                ).set_function(lambda: len(self._active))
        m.gauge("serving_decode_slots_free",
                "Free KV-cache slots.").set_function(
            lambda: self.pool.n_free)
        m.gauge("serving_decode_waiting",
                "Decode requests admitted but not yet in a slot."
                ).set_function(lambda: len(self._waiting))
        for name, help_, fn in (
            ("serving_decode_requests_total",
             "Decode requests that entered the scheduler.",
             lambda: self.n_requests),
            ("serving_decode_steps_total",
             "Single-token decode steps executed (each covers every "
             "live slot).", lambda: self.n_steps),
            ("serving_decode_tokens_total",
             "Tokens emitted to live requests.",
             lambda: self.n_tokens),
            ("serving_decode_prefills_total",
             "Prompt prefills (slot claims).",
             lambda: self.n_prefills),
            ("serving_decode_step_faults_total",
             "Decode steps that raised (injected or real); affected "
             "requests 500, slots are released.",
             lambda: self.n_step_faults),
        ):
            m.counter(name, help_).set_function(fn)
        self._m_prefill = m.histogram(
            "serving_prefill_latency_ms",
            "Prompt prefill wall-clock per prompt bucket.",
            labels=("bucket",))
        self._m_step = m.histogram(
            "serving_decode_step_latency_ms",
            "Single-token decode step wall-clock (all slots at once).")
        self._m_queue_wait = m.histogram(
            "serving_decode_queue_wait_ms",
            "Submit -> slot-claim wait per decode request.")

    # -- admission (any thread) ----------------------------------------------

    def overloaded(self) -> bool:
        return len(self._waiting) >= self.max_waiting

    def parse(self, payload: Any) -> "tuple[np.ndarray, int]":
        """Payload -> (prompt tokens, max_new). Raises ValueError on
        anything the decode plane cannot serve (the caller 400s)."""
        if not isinstance(payload, dict):
            raise ValueError("decode payload must be a JSON object")
        prompt = payload.get("prompt")
        if not isinstance(prompt, list) or not prompt or \
                not all(isinstance(t, int) and not isinstance(t, bool)
                        and 0 <= t for t in prompt):
            # bool is an int subclass: [true, false] must 400, not
            # silently decode as tokens [1, 0]
            raise ValueError(
                'decode payload needs "prompt": [token ids] '
                '(non-empty list of non-negative ints)')
        if any(t >= self.decoder.cfg.vocab for t in prompt):
            raise ValueError(
                f"prompt token out of range (vocab "
                f"{self.decoder.cfg.vocab})")
        if len(prompt) >= self.decoder.max_len:
            raise ValueError(
                f"prompt length {len(prompt)} >= max_len "
                f"{self.decoder.max_len} (no room to generate)")
        max_new = payload.get("max_new_tokens",
                              self.max_new_tokens_default)
        if not isinstance(max_new, int) or isinstance(max_new, bool) \
                or max_new < 1:
            raise ValueError('"max_new_tokens" must be a positive int')
        # the cache lane bounds the sequence: clamp the budget to it
        max_new = min(max_new, self.decoder.max_len - len(prompt))
        return np.asarray(prompt, np.int32), max_new, \
            self._parse_sampling(payload)

    @staticmethod
    def _parse_sampling(payload: dict) -> Optional[Sampler]:
        """Request-selectable sampling: ``temperature`` (> 0 turns
        sampling on; 0/absent = greedy, the default), ``top_k``,
        ``top_p``, ``seed``. Bad values 400 like any other payload
        error."""
        temp = payload.get("temperature", 0)
        if isinstance(temp, bool) or not isinstance(temp, (int, float)) \
                or not np.isfinite(temp) or temp < 0:
            raise ValueError(
                '"temperature" must be a finite number >= 0 '
                '(0 = greedy)')
        top_k = payload.get("top_k", 0)
        if isinstance(top_k, bool) or not isinstance(top_k, int) \
                or top_k < 0:
            raise ValueError('"top_k" must be an int >= 0 (0 = off)')
        top_p = payload.get("top_p", 1.0)
        if isinstance(top_p, bool) or not isinstance(top_p, (int, float)) \
                or not 0.0 < float(top_p) <= 1.0:
            raise ValueError('"top_p" must be in (0, 1]')
        seed = payload.get("seed")
        if seed is not None and (isinstance(seed, bool)
                                 or not isinstance(seed, int)):
            raise ValueError('"seed" must be an int')
        if float(temp) == 0.0:
            if "temperature" not in payload and \
                    (int(top_k) > 0 or float(top_p) < 1.0):
                # EFFECTIVE knobs with temperature ABSENT: serve them
                # at temperature 1 rather than silently decoding
                # greedy. An EXPLICIT "temperature": 0 always wins —
                # 0 is documented as greedy, and overriding it to
                # unseeded T=1 sampling would hand the client exactly
                # the nondeterminism it asked to avoid. No-op values
                # (top_k: 0, top_p: 1.0 — both documented "off") stay
                # greedy either way.
                return Sampler(1.0, int(top_k), float(top_p), seed)
            return None
        return Sampler(float(temp), int(top_k), float(top_p), seed)

    def submit(self, pending) -> None:
        """Enqueue one admitted request (already past the server's
        replay/join/shed/doa checks). Raises ValueError on a bad
        payload (caller replies 400), DecodeOverloaded when the
        waiting queue is full (caller replies 429)."""
        prompt, max_new, sampler = self.parse(pending.payload)
        req = _DecodeRequest(pending, prompt, max_new, sampler)
        req.t_submit = self.clock.now()
        with self._lock:
            if len(self._waiting) >= self.max_waiting:
                raise DecodeOverloaded("decode waiting queue full")
            self._waiting.append(req)
            self._by_rid[pending.rid] = req
            self.n_requests += 1
        self._work.set()

    def cancel(self, rid: str) -> bool:
        """Flag a waiting or in-slot request cancelled; it resolves
        (partial tokens, ``finish_reason: "cancelled"``) and frees its
        slot at the next loop pass. Returns False for unknown rids."""
        with self._lock:
            req = self._by_rid.get(rid)
            if req is None:
                return False
            req.cancelled = True
        self._work.set()
        return True

    # -- resolution ----------------------------------------------------------

    @staticmethod
    def _standalone_commit(p) -> None:
        p.event.set()
        for cb in p.callbacks:
            try:
                cb(p)
            except Exception:  # noqa: BLE001 — mirror server._release
                logger.warning("reply callback failed", exc_info=True)

    def _now(self) -> float:
        return (self.tracer.clock.now() if self.tracer is not None
                else self.clock.now())

    def _add_span(self, req: _DecodeRequest, name: str, t0: float,
                  t1: float, status: str = "ok", **attrs) -> None:
        if self.tracer is not None and req.pending.span is not None:
            self.tracer.add(name, t0, t1, parent=req.pending.span,
                            status=status, **attrs)

    def _finish(self, req: _DecodeRequest, reason: str,
                status: int = 200,
                error: Optional[str] = None) -> None:
        """Resolve a request and (if it held one) free its slot —
        EVERY exit path funnels here, so a slot can never leak."""
        if req.slot is not None:
            with self._lock:
                # under the lock so stats() can snapshot _active
                # against the loop thread's churn
                self._active.pop(req.slot, None)
            self._tokens[req.slot] = 0
            self._pos[req.slot] = 0
            self.pool.release(req.slot)
            t1 = self._now()
            self._add_span(req, "decode", req.t_decode, t1,
                           status="ok" if status == 200 else "error",
                           slot=req.slot, n_tokens=len(req.produced),
                           finish_reason=reason)
            req.slot = None
        with self._lock:
            self._by_rid.pop(req.pending.rid, None)
            self.releases[reason] = self.releases.get(reason, 0) + 1
        p = req.pending
        if status == 200:
            p.status = 200
            p.reply = json.dumps(
                {"tokens": req.produced,
                 "n_tokens": len(req.produced),
                 "prompt_len": int(len(req.prompt)),
                 "finish_reason": reason}).encode()
        else:
            p.status = status
            p.reply = json.dumps(
                {"error": error or reason,
                 "tokens": req.produced,
                 "n_tokens": len(req.produced),
                 "finish_reason": reason}).encode()
        self._commit(p)

    # -- the loop ------------------------------------------------------------

    def start(self) -> "DecodeScheduler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="decode-scheduler")
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # the loop is stuck inside a prefill/step (hung device,
                # first-compile of a big model): finishing its in-slot
                # requests from HERE would race its own retirement path
                # — double slot releases, double commits. Leave them to
                # the daemon thread; stranded clients 504 at
                # request_timeout (the server stop() idiom).
                logger.warning(
                    "decode loop did not stop in %.1fs; leaving "
                    "in-flight slots to it", timeout)
                return
        # the loop is dead: resolve stragglers so no client hangs
        with self._lock:
            waiting = list(self._waiting)
            self._waiting.clear()
        for req in waiting:
            self._finish(req, "error", status=503,
                         error="decode scheduler stopping")
        for req in list(self._active.values()):
            self._finish(req, "error", status=503,
                         error="decode scheduler stopping")

    def _loop(self) -> None:
        while not self._stop.is_set():
            # dead waiters resolve EVERY pass, slots full or not: with
            # every slot pinned by long decodes, a cancelled/expired
            # waiter must still get its prompt reply (and stop counting
            # toward overloaded()) instead of rotting until the
            # frontend's request_timeout
            self._reap_waiting()
            self._admit_waiting()
            if not self._active:
                # fully idle (nothing waiting either) -> block until
                # submit()/cancel()/stop() wakes us, no 50 Hz poll;
                # with waiters held back by deadline-less slots the
                # short timeout keeps their deadlines honest
                self._work.wait(self.idle_wait_s
                                if self._waiting else None)
                self._work.clear()
                continue
            self._run_step()

    def _reap_waiting(self) -> None:
        with self._lock:
            if not self._waiting:
                return
            keep, dead = deque(), []
            for req in self._waiting:
                p = req.pending
                if req.cancelled or (p.deadline is not None
                                     and p.deadline.expired):
                    dead.append(req)
                else:
                    keep.append(req)
            self._waiting = keep
        for req in dead:
            if req.cancelled:
                self._finish(req, "cancelled")
            else:
                self._finish(req, "deadline", status=504,
                             error="deadline exceeded before decode")

    def _pop_waiting(self) -> Optional[_DecodeRequest]:
        with self._lock:
            return self._waiting.popleft() if self._waiting else None

    def _admit_waiting(self) -> None:
        """Between steps: claim free slots for waiting requests (one
        prefill each). Cancelled/expired waiters resolve WITHOUT ever
        claiming a slot."""
        while self.pool.n_free > 0:
            req = self._pop_waiting()
            if req is None:
                return
            p = req.pending
            if req.cancelled:
                self._finish(req, "cancelled")
                continue
            if p.deadline is not None and p.deadline.expired:
                self._finish(req, "deadline", status=504,
                             error="deadline exceeded before decode")
                continue
            slot = self.pool.claim()
            if slot is None:      # raced a concurrent release? retry
                with self._lock:
                    self._waiting.appendleft(req)
                return
            t0 = self._now()
            self._add_span(req, "queue_wait", req.t_submit, t0)
            if self._m_queue_wait is not None:
                self._m_queue_wait.labels().observe(
                    (t0 - req.t_submit) * 1000.0)
            try:
                if self.fault_plan is not None:
                    self.fault_plan.raise_at("decode_prefill",
                                             clock=self.clock)
                first, last_logits = self.decoder.prefill_logits(
                    slot, req.prompt)
                if req.sampler is not None:
                    # the request's own seeded PRNG picks the first
                    # generated token from the prompt's last logits
                    first = req.sampler.sample(np.asarray(last_logits))
            except Exception as e:  # noqa: BLE001 — injected or real
                self.pool.release(slot)
                self._add_span(req, "prefill", t0, self._now(),
                               status="error")
                self._finish(req, "error", status=500,
                             error=f"prefill failed: {e}")
                continue
            t1 = self._now()
            req.t_prefill = t1
            req.t_decode = t1
            self.n_prefills += 1
            if self._m_prefill is not None:
                self._m_prefill.labels(
                    bucket_target(len(req.prompt),
                                  self.decoder.max_len)).observe(
                    (t1 - t0) * 1000.0)
            self._add_span(req, "prefill", t0, t1, slot=slot,
                           prompt_len=len(req.prompt))
            req.slot = slot
            req.produced.append(first)
            self.n_tokens += 1
            self._tokens[slot] = first
            self._pos[slot] = len(req.prompt)
            with self._lock:
                self._active[slot] = req
            self._retire_if_done(req, first)

    def _retire_if_done(self, req: _DecodeRequest, tok: int) -> bool:
        """Post-token finish checks, cheapest terminal first."""
        eos = self.decoder.eos_id
        if eos is not None and tok == eos:
            self._finish(req, "eos")
            return True
        if len(req.produced) >= req.max_new:
            self._finish(req, "length")
            return True
        if req.slot is not None and \
                int(self._pos[req.slot]) >= self.decoder.max_len - 1:
            self._finish(req, "length")   # cache lane exhausted
            return True
        if req.cancelled:
            self._finish(req, "cancelled")
            return True
        p = req.pending
        if p.deadline is not None and p.deadline.expired:
            self._finish(req, "deadline", status=504,
                         error="deadline exceeded mid-decode")
            return True
        return False

    def _run_step(self) -> None:
        # pre-step reap: expired/cancelled slots free BEFORE paying a
        # step for them (and their lanes stop being written)
        for req in list(self._active.values()):
            p = req.pending
            if req.cancelled:
                self._finish(req, "cancelled")
            elif p.deadline is not None and p.deadline.expired:
                self._finish(req, "deadline", status=504,
                             error="deadline exceeded mid-decode")
        if not self._active:
            return
        t0 = self._now()
        try:
            if self.fault_plan is not None:
                self.fault_plan.raise_at("decode_step",
                                         clock=self.clock)
            out, step_logits = self.decoder.step_logits(
                self._tokens, self._pos)
        except Exception as e:  # noqa: BLE001 — injected or real
            # a failed step loses the affected requests (500, never
            # journaled — clients may retry) but NEVER a slot
            self.n_step_faults += 1
            logger.warning("decode step failed; failing %d in-slot "
                           "requests", len(self._active), exc_info=True)
            for req in list(self._active.values()):
                self._finish(req, "error", status=500,
                             error=f"decode step failed: {e}")
            return
        t1 = self._now()
        self.n_steps += 1
        if self._m_step is not None:
            self._m_step.labels().observe((t1 - t0) * 1000.0)
        # one host fetch of the full [n_slots, vocab] logits per step,
        # paid ONLY while a sampling request is in a slot — pure-greedy
        # batches keep the token-only transfer
        logits_np = None
        if any(r.sampler is not None for r in self._active.values()):
            logits_np = np.asarray(step_logits)
        for slot, req in list(self._active.items()):
            tok = (int(out[slot]) if req.sampler is None
                   else req.sampler.sample(logits_np[slot]))
            req.produced.append(tok)
            self.n_tokens += 1
            self._pos[slot] += 1
            self._tokens[slot] = tok
            self._retire_if_done(req, tok)

    # -- observability -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            # snapshot under the lock: the loop thread churns _active
            # and the release ledger while scrapes read them
            waiting = len(self._waiting)
            active = sorted(self._active.items())
            releases = dict(self.releases)
        slots = [{"slot": s,
                  "rid": r.pending.rid,
                  "prompt_len": int(len(r.prompt)),
                  "n_tokens": len(r.produced),   # incremental progress
                  "max_new_tokens": r.max_new,
                  "sampling": (r.sampler.describe()
                               if r.sampler is not None else None)}
                 for s, r in active]
        return {"n_slots": self.decoder.n_slots,
                "slots_in_use": len(slots),
                "slots_free": self.pool.n_free,
                "max_len": self.decoder.max_len,
                "placement": self.decoder.placement(),
                "waiting": waiting,
                "max_waiting": self.max_waiting,
                "n_requests": self.n_requests,
                "n_steps": self.n_steps,
                "n_tokens": self.n_tokens,
                "n_prefills": self.n_prefills,
                "n_step_faults": self.n_step_faults,
                "n_compiles": self.decoder.n_compiles(),
                "releases": releases,
                "active": slots}
