"""The quantized serving wire: per-version QuantizationConfig.

``cifar10_scoring_u8_v1`` proved the shape of the win — u8 ingest beats
f32 ~1.5x because the wire (JSON payload bytes, frame assembly, and the
host->device upload) carries 2-4x fewer bytes per request and the
dequantize (``x * scale + zero_point``) fuses into the model's first
layer on device. But that was a one-off ``input_dtype`` knob on
``NNModel``; this module makes it a first-class serving-plane feature:

* a :class:`QuantizationConfig` rides each
  :class:`~mmlspark_tpu.serving.rollout.ModelVersion` (boot config via
  ``ServingServer(quantization=...)``; rollout configs via
  ``POST /rollout/stage {"quantization": {...}}`` — the staged
  version's config survives verify -> warmup -> flip untouched);
* the dispatch stage casts the assembled columnar frame to the wire
  dtype (saturating — out-of-range payload values clamp, the standard
  quantization semantics, never wrap into garbage) right before the
  model sees it, so quantized buckets compile once at warmup and the
  jitted forward's input dtype never flips mid-flight;
* ``serving_wire_bytes_total{dtype}`` counts the bytes each dispatch
  actually put on the device wire, ``GET /stats`` reports the active
  config, and dispatch spans carry ``wire_dtype`` — the evidence that
  the quantized plane is engaged, not just configured.

Config validation is strict and happens at CONSTRUCTION (so a
malformed scale/zero-point in a rollout body is a 400 at the stage
endpoint, never a batch of garbage dispatched at serving time): the
scale must be a finite non-zero number, the zero_point finite, the
wire dtype one of ``uint8``/``int8``.

Parity contract: dequantized values are ``wire * scale + zero_point``
in the model's compute dtype (bf16 for bf16 models) with f32
accumulation inside the matmuls — ``tests/test_serving_quant.py`` pins
row-wise agreement with the f32 plane within the quantization step's
tolerance on both frontends.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["QuantizationConfig"]

_WIRE_DTYPES = {
    "uint8": (np.uint8, 0, 255),
    "int8": (np.int8, -128, 127),
}


class QuantizationConfig:
    """How a model version's request payloads cross the wire.

    ``wire_dtype`` — ``"uint8"`` or ``"int8"``: the integer dtype
    payload values are cast to for assembly + host->device transfer
    (4x fewer bytes than f32, 2x than bf16).

    ``scale`` / ``zero_point`` — the on-device dequantization
    ``x * scale + zero_point``, fused into the model's first layer by
    XLA (for :class:`~mmlspark_tpu.models.nn.NNModel` via its
    ``input_scale``/``input_offset`` params). Defaults: ``1/255`` and
    ``0.0`` — u8 images to ``[0, 1]``.

    ``columns`` — the input columns the wire dtype applies to (None =
    every numeric input column; reply columns are never touched).
    """

    __slots__ = ("wire_dtype", "scale", "zero_point", "columns")

    def __init__(self, wire_dtype: str = "uint8",
                 scale: float = 1.0 / 255.0, zero_point: float = 0.0,
                 columns: Optional[List[str]] = None):
        if wire_dtype not in _WIRE_DTYPES:
            raise ValueError(
                f"wire_dtype must be one of {sorted(_WIRE_DTYPES)}, "
                f"got {wire_dtype!r}")
        try:
            scale = float(scale)
            zero_point = float(zero_point)
        except (TypeError, ValueError):
            raise ValueError(
                "quantization scale/zero_point must be numbers, got "
                f"scale={scale!r} zero_point={zero_point!r}") from None
        if not math.isfinite(scale) or scale == 0.0:
            # a zero or non-finite scale dequantizes every payload to
            # one constant (or NaN) — refuse at config time, not after
            # a batch of garbage replies
            raise ValueError(
                f"quantization scale must be finite and non-zero, "
                f"got {scale!r}")
        if not math.isfinite(zero_point):
            raise ValueError(
                f"quantization zero_point must be finite, got "
                f"{zero_point!r}")
        if columns is not None:
            if not isinstance(columns, (list, tuple)) or \
                    not all(isinstance(c, str) for c in columns):
                raise ValueError("quantization columns must be a list "
                                 f"of column names, got {columns!r}")
            columns = list(columns)
        self.wire_dtype = wire_dtype
        self.scale = scale
        self.zero_point = zero_point
        self.columns = columns

    # -- construction --------------------------------------------------------

    @classmethod
    def from_value(cls, value: Any) -> Optional["QuantizationConfig"]:
        """Coerce a config from user input: an existing config passes
        through, a dict becomes one (unknown keys refused — a typoed
        ``zero_pont`` must not silently default), None stays None.
        Raises ``ValueError`` on anything malformed — the rollout
        endpoint turns that into a 400."""
        if value is None or isinstance(value, cls):
            return value
        if not isinstance(value, dict):
            raise ValueError(
                f"quantization must be a JSON object, got "
                f"{type(value).__name__}")
        unknown = set(value) - {"wire_dtype", "scale", "zero_point",
                                "columns"}
        if unknown:
            raise ValueError(
                f"unknown quantization keys {sorted(unknown)}")
        return cls(**value)

    # -- the wire cast -------------------------------------------------------

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(_WIRE_DTYPES[self.wire_dtype][0])

    def applies_to(self, column: str) -> bool:
        return self.columns is None or column in self.columns

    def quantize_column(self, arr: np.ndarray) -> np.ndarray:
        """Cast one assembled column to the wire dtype (saturating:
        values outside the dtype's range clamp to its edges — the
        standard quantized-tensor semantics; integer casts that WRAP
        would dispatch garbage for one out-of-range payload value).
        Non-numeric (object/string) columns pass through untouched."""
        if arr.dtype == self.np_dtype:
            return arr
        if arr.dtype == np.dtype("O") or arr.dtype.kind not in "fiub":
            return arr
        _, lo, hi = _WIRE_DTYPES[self.wire_dtype]
        if arr.dtype.kind == "f":
            # round-to-nearest, not truncation: a client's fp-noisy
            # 254.9999 must land on 255, not 254 (astype truncates
            # toward zero — a one-sided LSB of error otherwise)
            return np.clip(np.rint(arr), lo, hi).astype(self.np_dtype)
        if arr.dtype.kind in "iu" and arr.size:
            # integer payloads already in range (the steady state once
            # clients send wire-ready values) skip the clip's full-size
            # temporary: two C-speed scans, one cast
            mn, mx = arr.min(), arr.max()
            if lo <= mn and mx <= hi:
                return arr.astype(self.np_dtype)
        return np.clip(arr, lo, hi).astype(self.np_dtype)

    def quantize_frame(self, df):
        """Cast every applicable column of a columnar frame to the
        wire dtype; returns the frame unchanged when nothing needs the
        cast (the steady state once clients send integer payloads)."""
        out = {}
        changed = False
        for name in df.columns:
            col = df[name]
            if self.applies_to(name):
                q = self.quantize_column(col)
                changed = changed or q is not col
                out[name] = q
            else:
                out[name] = col
        if not changed:
            return df
        from mmlspark_tpu.core.dataframe import DataFrame
        return DataFrame(out)

    # -- model wiring --------------------------------------------------------

    def configure_model(self, model) -> None:
        """Point a model's ingest at this config: for models with the
        ``NNModel`` quantization surface (``input_dtype`` +
        ``input_scale``/``input_offset`` params) the wire dtype and
        dequant constants are set so the on-device dequantize matches
        the wire exactly. A model that carries its OWN ``quantization``
        param (a persisted quantized checkpoint restaged under a new
        config) has it replaced too — that param takes precedence
        inside the model, so leaving the old one would silently
        dequantize with the superseded constants. Models without the
        surface are left alone — they see the integer columns and
        handle them as data."""
        if hasattr(model, "input_dtype") and \
                hasattr(model, "input_scale"):
            model.input_dtype = self.wire_dtype
            model.input_scale = self.scale
            model.input_offset = self.zero_point
            if getattr(model, "quantization", None) is not None \
                    and model.quantization != self:
                model.quantization = self

    # -- surfaces ------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {"wire_dtype": self.wire_dtype, "scale": self.scale,
                "zero_point": self.zero_point, "columns": self.columns}

    def __repr__(self) -> str:
        return (f"QuantizationConfig(wire_dtype={self.wire_dtype!r}, "
                f"scale={self.scale!r}, zero_point={self.zero_point!r},"
                f" columns={self.columns!r})")

    def __eq__(self, other) -> bool:
        return isinstance(other, QuantizationConfig) and \
            self.to_dict() == other.to_dict()
