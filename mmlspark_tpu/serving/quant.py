"""The quantized serving wire: per-version QuantizationConfig.

``cifar10_scoring_u8_v1`` proved the shape of the win — u8 ingest beats
f32 ~1.5x because the wire (JSON payload bytes, frame assembly, and the
host->device upload) carries 2-4x fewer bytes per request and the
dequantize (``x * scale + zero_point``) fuses into the model's first
layer on device. But that was a one-off ``input_dtype`` knob on
``NNModel``; this module makes it a first-class serving-plane feature:

* a :class:`QuantizationConfig` rides each
  :class:`~mmlspark_tpu.serving.rollout.ModelVersion` (boot config via
  ``ServingServer(quantization=...)``; rollout configs via
  ``POST /rollout/stage {"quantization": {...}}`` — the staged
  version's config survives verify -> warmup -> flip untouched);
* the dispatch stage casts the assembled columnar frame to the wire
  dtype (saturating — out-of-range payload values clamp, the standard
  quantization semantics, never wrap into garbage) right before the
  model sees it, so quantized buckets compile once at warmup and the
  jitted forward's input dtype never flips mid-flight;
* ``serving_wire_bytes_total{dtype}`` counts the bytes each dispatch
  actually put on the device wire, ``GET /stats`` reports the active
  config, and dispatch spans carry ``wire_dtype`` — the evidence that
  the quantized plane is engaged, not just configured.

Config validation is strict and happens at CONSTRUCTION (so a
malformed scale/zero-point in a rollout body is a 400 at the stage
endpoint, never a batch of garbage dispatched at serving time): the
scale must be a finite non-zero number, the zero_point finite, the
wire dtype one of ``uint8``/``int8``.

Parity contract: dequantized values are ``wire * scale + zero_point``
in the model's compute dtype (bf16 for bf16 models) with f32
accumulation inside the matmuls — ``tests/test_serving_quant.py`` pins
row-wise agreement with the f32 plane within the quantization step's
tolerance on both frontends.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["QuantizationConfig", "ComputeQuantization"]

_WIRE_DTYPES = {
    "uint8": (np.uint8, 0, 255),
    "int8": (np.int8, -128, 127),
}

_ACT_DTYPES = ("bfloat16", "float32")


class ComputeQuantization:
    """The ON-DEVICE half of the quantization story (ISSUE 17): int8
    weights / low-precision activations inside the matmuls themselves,
    not just on the wire.

    ``weight_dtype`` — ``"int8"`` (the only engine): every eligible
    weight matrix is stored int8 in HBM with per-output-channel
    symmetric scales (``amax(|w|) / 127`` over the input axes, f32,
    computed ONCE at rollout stage time) and dequantizes into the
    matmul — XLA fuses the ``w_q * scale`` into the contraction, the
    int8->float cast is exact, and the accumulator stays f32.

    ``activation_dtype`` — what the activations meet the weights as on
    the MXU: ``"bfloat16"`` (the TPU-native fast path) or
    ``"float32"`` (full-precision activations against int8 weights —
    the conservative A/B arm). Softmax, normalization, and the
    residual stream stay f32 either way, mirroring the train path's
    ``cfg.dtype`` flow.

    ``tolerance`` — the row-wise RELATIVE tolerance the quantized
    plane must hold against the f32 reference: the rollout verify step
    refuses to stage a config outside it (state -> ``error``, the
    active version keeps serving — automatic rollback) and the
    shadow-traffic comparator uses it instead of the exact-parity
    default while an int8-compute version is staged.

    ``scale_multiplier`` — a deliberate scale corruption (!= 1.0) for
    chaos/rollback drills: the bench gate stages a broken config and
    proves the verify step catches it BEFORE the flip.
    """

    __slots__ = ("weight_dtype", "activation_dtype", "tolerance",
                 "scale_multiplier")

    def __init__(self, weight_dtype: str = "int8",
                 activation_dtype: str = "bfloat16",
                 tolerance: float = 5e-2,
                 scale_multiplier: float = 1.0):
        if weight_dtype != "int8":
            raise ValueError(
                f"compute weight_dtype must be 'int8', got "
                f"{weight_dtype!r}")
        if activation_dtype not in _ACT_DTYPES:
            raise ValueError(
                f"compute activation_dtype must be one of "
                f"{list(_ACT_DTYPES)}, got {activation_dtype!r}")
        try:
            tolerance = float(tolerance)
            scale_multiplier = float(scale_multiplier)
        except (TypeError, ValueError):
            raise ValueError(
                "compute tolerance/scale_multiplier must be numbers, "
                f"got {tolerance!r} / {scale_multiplier!r}") from None
        if not math.isfinite(tolerance) or tolerance <= 0.0:
            raise ValueError(
                f"compute tolerance must be finite and positive, got "
                f"{tolerance!r}")
        if not math.isfinite(scale_multiplier) \
                or scale_multiplier == 0.0:
            raise ValueError(
                f"compute scale_multiplier must be finite and "
                f"non-zero, got {scale_multiplier!r}")
        self.weight_dtype = weight_dtype
        self.activation_dtype = activation_dtype
        self.tolerance = tolerance
        self.scale_multiplier = scale_multiplier

    @classmethod
    def from_value(cls, value: Any) -> Optional["ComputeQuantization"]:
        if value is None or isinstance(value, cls):
            return value
        if not isinstance(value, dict):
            raise ValueError(
                f"quantization compute must be a JSON object, got "
                f"{type(value).__name__}")
        unknown = set(value) - {"weight_dtype", "activation_dtype",
                                "tolerance", "scale_multiplier"}
        if unknown:
            raise ValueError(
                f"unknown quantization compute keys {sorted(unknown)}")
        return cls(**value)

    def to_dict(self) -> Dict[str, Any]:
        return {"weight_dtype": self.weight_dtype,
                "activation_dtype": self.activation_dtype,
                "tolerance": self.tolerance,
                "scale_multiplier": self.scale_multiplier}

    def __repr__(self) -> str:
        return (f"ComputeQuantization("
                f"weight_dtype={self.weight_dtype!r}, "
                f"activation_dtype={self.activation_dtype!r}, "
                f"tolerance={self.tolerance!r}, "
                f"scale_multiplier={self.scale_multiplier!r})")

    def __eq__(self, other) -> bool:
        return isinstance(other, ComputeQuantization) and \
            self.to_dict() == other.to_dict()


def quantize_param_tree(params, comp: ComputeQuantization):
    """Per-channel int8 quantization of a model param tree — the
    scale-derivation step, run ONCE at rollout stage time.

    Every eligible leaf (a ``kernel`` weight matrix of ndim >= 2 —
    flax Dense ``(I, O)`` and Conv ``(..., I, O)`` kernels; biases,
    norms, and everything 1-D stay f32) is replaced IN PLACE in the
    returned tree by its int8 rounding under symmetric per-output-
    channel scales ``amax(|w|, input axes) / 127`` (zero channels
    guard to scale 1.0). The scales ride OUTSIDE the tree in a dict
    keyed by the leaf's path string, so the quantized tree keeps the
    exact structure placement/sharding machinery expects. The
    config's ``scale_multiplier`` folds into the stored scales — the
    deliberate-corruption knob the rollback drills stage."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    scales: Dict[str, np.ndarray] = {}
    leaves = []
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        arr = np.asarray(leaf)
        name = str(path[-1]) if path else ""
        if "kernel" in name and arr.ndim >= 2 \
                and arr.dtype.kind == "f":
            s = np.max(np.abs(arr), axis=tuple(range(arr.ndim - 1)))
            s = (s / 127.0).astype(np.float32)
            s = np.where(s > 0, s, np.float32(1.0))
            q = np.clip(np.rint(arr / s), -127, 127).astype(np.int8)
            scales[key] = (s * np.float32(comp.scale_multiplier))
            leaves.append(q)
        else:
            leaves.append(leaf)
    if not scales:
        raise ValueError(
            "compute quantization found no eligible kernel leaves in "
            "the param tree — nothing would be quantized")
    return jax.tree_util.tree_unflatten(treedef, leaves), scales


def dequantize_param_tree(qparams, scales: Dict[str, np.ndarray],
                          activation_dtype: str):
    """The forward-time inverse: int8 kernels back to
    ``activation_dtype`` via their per-channel scales (full-precision
    f32 multiply first, one downcast after — XLA fuses the whole
    dequant into the consuming matmul, so no dequantized copy persists
    in HBM). Traced inside the jitted forward; the scale dict entries
    become constants of the executable."""
    import jax
    import jax.numpy as jnp

    act = jnp.dtype(activation_dtype)

    def deq(path, leaf):
        key = jax.tree_util.keystr(path)
        s = scales.get(key)
        if s is None:
            return leaf
        return (leaf.astype(jnp.float32) * s).astype(act)

    return jax.tree_util.tree_map_with_path(deq, qparams)


class QuantizationConfig:
    """How a model version's request payloads cross the wire.

    ``wire_dtype`` — ``"uint8"`` or ``"int8"``: the integer dtype
    payload values are cast to for assembly + host->device transfer
    (4x fewer bytes than f32, 2x than bf16). ``"none"`` leaves
    payloads in their native float dtype — the compute-only shape
    (``{"wire_dtype": "none", "compute": {...}}``) quantizes weights
    on device without touching ingest.

    ``scale`` / ``zero_point`` — the on-device dequantization
    ``x * scale + zero_point``, fused into the model's first layer by
    XLA (for :class:`~mmlspark_tpu.models.nn.NNModel` via its
    ``input_scale``/``input_offset`` params). Defaults: ``1/255`` and
    ``0.0`` — u8 images to ``[0, 1]`` (``1.0`` / ``0.0`` under
    ``wire_dtype: "none"``, where there is no wire step to invert;
    anything else there is refused).

    ``columns`` — the input columns the wire dtype applies to (None =
    every numeric input column; reply columns are never touched).

    ``compute`` — optional :class:`ComputeQuantization`: int8 weights
    / low-precision activations INSIDE the model's matmuls (the wire
    fields above only cover ingest). None = f32 compute, the default.
    """

    __slots__ = ("wire_dtype", "scale", "zero_point", "columns",
                 "compute")

    def __init__(self, wire_dtype: str = "uint8",
                 scale: Optional[float] = None,
                 zero_point: float = 0.0,
                 columns: Optional[List[str]] = None,
                 compute: Any = None):
        if wire_dtype != "none" and wire_dtype not in _WIRE_DTYPES:
            raise ValueError(
                f"wire_dtype must be one of "
                f"{sorted(_WIRE_DTYPES) + ['none']}, got {wire_dtype!r}")
        if scale is None:
            scale = 1.0 if wire_dtype == "none" else 1.0 / 255.0
        try:
            scale = float(scale)
            zero_point = float(zero_point)
        except (TypeError, ValueError):
            raise ValueError(
                "quantization scale/zero_point must be numbers, got "
                f"scale={scale!r} zero_point={zero_point!r}") from None
        if not math.isfinite(scale) or scale == 0.0:
            # a zero or non-finite scale dequantizes every payload to
            # one constant (or NaN) — refuse at config time, not after
            # a batch of garbage replies
            raise ValueError(
                f"quantization scale must be finite and non-zero, "
                f"got {scale!r}")
        if not math.isfinite(zero_point):
            raise ValueError(
                f"quantization zero_point must be finite, got "
                f"{zero_point!r}")
        if wire_dtype == "none" and (scale != 1.0
                                     or zero_point != 0.0):
            # no wire cast means no dequant step to invert — a
            # non-identity scale here would silently rescale raw f32
            # payloads
            raise ValueError(
                "wire_dtype 'none' requires scale=1.0/zero_point=0.0, "
                f"got scale={scale!r} zero_point={zero_point!r}")
        if columns is not None:
            if not isinstance(columns, (list, tuple)) or \
                    not all(isinstance(c, str) for c in columns):
                raise ValueError("quantization columns must be a list "
                                 f"of column names, got {columns!r}")
            columns = list(columns)
        self.wire_dtype = wire_dtype
        self.scale = scale
        self.zero_point = zero_point
        self.columns = columns
        self.compute = ComputeQuantization.from_value(compute)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_value(cls, value: Any) -> Optional["QuantizationConfig"]:
        """Coerce a config from user input: an existing config passes
        through, a dict becomes one (unknown keys refused — a typoed
        ``zero_pont`` must not silently default), None stays None.
        Raises ``ValueError`` on anything malformed — the rollout
        endpoint turns that into a 400."""
        if value is None or isinstance(value, cls):
            return value
        if not isinstance(value, dict):
            raise ValueError(
                f"quantization must be a JSON object, got "
                f"{type(value).__name__}")
        unknown = set(value) - {"wire_dtype", "scale", "zero_point",
                                "columns", "compute"}
        if unknown:
            raise ValueError(
                f"unknown quantization keys {sorted(unknown)}")
        return cls(**value)

    # -- the wire cast -------------------------------------------------------

    @property
    def np_dtype(self) -> np.dtype:
        if self.wire_dtype == "none":
            return np.dtype(np.float32)
        return np.dtype(_WIRE_DTYPES[self.wire_dtype][0])

    def applies_to(self, column: str) -> bool:
        return self.columns is None or column in self.columns

    def quantize_column(self, arr: np.ndarray) -> np.ndarray:
        """Cast one assembled column to the wire dtype (saturating:
        values outside the dtype's range clamp to its edges — the
        standard quantized-tensor semantics; integer casts that WRAP
        would dispatch garbage for one out-of-range payload value).
        Non-numeric (object/string) columns pass through untouched."""
        if self.wire_dtype == "none" or arr.dtype == self.np_dtype:
            return arr
        if arr.dtype == np.dtype("O") or arr.dtype.kind not in "fiub":
            return arr
        _, lo, hi = _WIRE_DTYPES[self.wire_dtype]
        if arr.dtype.kind == "f":
            # round-to-nearest, not truncation: a client's fp-noisy
            # 254.9999 must land on 255, not 254 (astype truncates
            # toward zero — a one-sided LSB of error otherwise)
            return np.clip(np.rint(arr), lo, hi).astype(self.np_dtype)
        if arr.dtype.kind in "iu" and arr.size:
            # integer payloads already in range (the steady state once
            # clients send wire-ready values) skip the clip's full-size
            # temporary: two C-speed scans, one cast
            mn, mx = arr.min(), arr.max()
            if lo <= mn and mx <= hi:
                return arr.astype(self.np_dtype)
        return np.clip(arr, lo, hi).astype(self.np_dtype)

    def quantize_frame(self, df):
        """Cast every applicable column of a columnar frame to the
        wire dtype; returns the frame unchanged when nothing needs the
        cast (the steady state once clients send integer payloads)."""
        out = {}
        changed = False
        for name in df.columns:
            col = df[name]
            if self.applies_to(name):
                q = self.quantize_column(col)
                changed = changed or q is not col
                out[name] = q
            else:
                out[name] = col
        if not changed:
            return df
        from mmlspark_tpu.core.dataframe import DataFrame
        return DataFrame(out)

    # -- model wiring --------------------------------------------------------

    def configure_model(self, model) -> None:
        """Point a model's ingest at this config: for models with the
        ``NNModel`` quantization surface (``input_dtype`` +
        ``input_scale``/``input_offset`` params) the wire dtype and
        dequant constants are set so the on-device dequantize matches
        the wire exactly. A model that carries its OWN ``quantization``
        param (a persisted quantized checkpoint restaged under a new
        config) has it replaced too — that param takes precedence
        inside the model, so leaving the old one would silently
        dequantize with the superseded constants. Models without the
        surface are left alone — they see the integer columns and
        handle them as data."""
        if hasattr(model, "input_dtype") and \
                hasattr(model, "input_scale"):
            # "none" = native float payloads: "auto" keeps the model's
            # arch-driven transfer dtype and the identity scale/offset
            # below make the input dequant a no-op
            model.input_dtype = ("auto" if self.wire_dtype == "none"
                                 else self.wire_dtype)
            model.input_scale = self.scale
            model.input_offset = self.zero_point
            if self.compute is not None:
                # compute quantization lives ON the model (the int8
                # tree + scales hang off model.quantization.compute) —
                # a model staged without its own config must adopt
                # this one or it serves f32 silently
                if getattr(model, "quantization", None) != self:
                    model.quantization = self
            elif getattr(model, "quantization", None) is not None \
                    and model.quantization != self:
                model.quantization = self

    # -- surfaces ------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {"wire_dtype": self.wire_dtype, "scale": self.scale,
                "zero_point": self.zero_point, "columns": self.columns,
                "compute": (self.compute.to_dict()
                            if self.compute is not None else None)}

    def __repr__(self) -> str:
        return (f"QuantizationConfig(wire_dtype={self.wire_dtype!r}, "
                f"scale={self.scale!r}, zero_point={self.zero_point!r},"
                f" columns={self.columns!r}, compute={self.compute!r})")

    def __eq__(self, other) -> bool:
        return isinstance(other, QuantizationConfig) and \
            self.to_dict() == other.to_dict()
