"""Serving: batched HTTP inference frontends + multi-host coordination.

Capability parity with Spark Serving (`src/io/http` serving sources/sinks)
rebuilt for the TPU execution model — see :mod:`mmlspark_tpu.serving.server`.

The socket edge is selectable: the default event-loop frontend
(:mod:`mmlspark_tpu.serving.frontend` — keep-alive connection reuse,
zero-copy framing, ``SO_REUSEPORT`` acceptors) or the threaded
``http.server`` baseline (``frontend="threaded"``). See
``docs/serving.md`` "The socket edge".

Observability: every worker serves ``GET /metrics`` (Prometheus text
format) and carries ``X-Trace-Id`` through its whole data plane; the
:class:`ServingCoordinator` aggregates the fleet — ``GET /fleet`` merges
every worker's ``/stats`` (naming the slowest stage fleet-wide) and
``GET /fleet/metrics`` merges their scrapes into one exposition. See
``docs/observability.md``.
"""

from mmlspark_tpu.serving.server import (
    ServingClient, ServingCoordinator, ServingServer,
)
from mmlspark_tpu.serving.capture import TrafficCapture
from mmlspark_tpu.serving.consolidator import PartitionConsolidator
from mmlspark_tpu.serving.decode import (
    DecodeOverloaded, DecodeScheduler, PagePool, PrefixCache, Sampler,
    SlotPool, TransformerDecoder,
)
from mmlspark_tpu.serving.frontend import EventLoopFrontend
from mmlspark_tpu.serving.incident import FanoutNotifier, IncidentManager
from mmlspark_tpu.serving.policy import (
    AdaptiveBatchPolicy, PriorityShedPolicy, SpeculationPolicy,
)
from mmlspark_tpu.serving.quant import QuantizationConfig
from mmlspark_tpu.serving.rollout import (
    ModelVersionManager, RolloutError, RolloutOrchestrator,
)
from mmlspark_tpu.serving.tenancy import (
    FairCycle, Tenant, TenantRegistry, TokenBucket, extract_api_key,
)

__all__ = ["ServingServer", "ServingCoordinator", "ServingClient",
           "PartitionConsolidator", "EventLoopFrontend",
           "ModelVersionManager", "RolloutError", "RolloutOrchestrator",
           "DecodeScheduler", "DecodeOverloaded", "SlotPool", "PagePool",
           "PrefixCache",
           "TransformerDecoder", "AdaptiveBatchPolicy",
           "QuantizationConfig",
           "SpeculationPolicy", "Sampler", "TrafficCapture",
           "Tenant", "TenantRegistry", "TokenBucket", "FairCycle",
           "PriorityShedPolicy", "extract_api_key",
           "IncidentManager", "FanoutNotifier"]
