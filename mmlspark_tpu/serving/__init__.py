"""Serving: batched HTTP inference frontends + multi-host coordination.

Capability parity with Spark Serving (`src/io/http` serving sources/sinks)
rebuilt for the TPU execution model — see :mod:`mmlspark_tpu.serving.server`.

Observability: every worker serves ``GET /metrics`` (Prometheus text
format) and carries ``X-Trace-Id`` through its whole data plane; the
:class:`ServingCoordinator` aggregates the fleet — ``GET /fleet`` merges
every worker's ``/stats`` (naming the slowest stage fleet-wide) and
``GET /fleet/metrics`` merges their scrapes into one exposition. See
``docs/observability.md``.
"""

from mmlspark_tpu.serving.server import (
    ServingClient, ServingCoordinator, ServingServer,
)
from mmlspark_tpu.serving.consolidator import PartitionConsolidator

__all__ = ["ServingServer", "ServingCoordinator", "ServingClient",
           "PartitionConsolidator"]
