"""Serving: batched HTTP inference frontends + multi-host coordination.

Capability parity with Spark Serving (`src/io/http` serving sources/sinks)
rebuilt for the TPU execution model — see :mod:`mmlspark_tpu.serving.server`.
"""

from mmlspark_tpu.serving.server import (
    ServingClient, ServingCoordinator, ServingServer,
)
from mmlspark_tpu.serving.consolidator import PartitionConsolidator

__all__ = ["ServingServer", "ServingCoordinator", "ServingClient",
           "PartitionConsolidator"]
