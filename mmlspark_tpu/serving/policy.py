"""Adaptive micro-batch dispatch policy.

The frame plane's fixed ``max_latency_ms`` knob answers one question —
"how long may a batch wait for batch-mates?" — with a constant. The
right answer depends on two things the server can *measure*: how fast
requests are arriving (wait w seconds and ~rate*w more show up) and
how much a bigger shape bucket actually costs to dispatch (the
per-bucket latency histograms the telemetry layer already collects).

:class:`AdaptiveBatchPolicy` learns both online and picks the wait
that maximizes dispatch *throughput* (rows per second through the
model): for each reachable bucket it scores ``bucket / (time_to_fill
+ service_time(bucket))`` and waits just long enough to fill the best
one — under a hard ``ceiling_ms`` so latency can never run away, and
never waiting at all when arrivals are too slow to fill a bigger
bucket in time. Until it has a believable arrival-rate estimate and
``min_count`` histogram samples it returns ``None`` and the fixed
knob keeps ruling (the same warm-up contract as
:class:`~mmlspark_tpu.core.tracing.AdaptiveThreshold`).

A/B selectable: ``ServingServer(batch_policy="adaptive")`` wires this
in; ``"fixed"`` (the default) keeps the constant knob — both planes
share every other stage, so the bench/test comparison isolates the
policy.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from mmlspark_tpu.core.resilience import SYSTEM_CLOCK, Clock

#: Tenant priority classes, most- to least-latency-sensitive. The
#: ordering is the shed ordering under pressure: ``background`` sheds
#: first, ``batch`` next, ``interactive`` only when the queue is full.
PRIORITY_CLASSES = ("interactive", "batch", "background")


class PriorityShedPolicy:
    """Map queue pressure to a per-priority-class shed verdict.

    Below the ``high_water`` fraction of capacity nobody sheds; above
    it the classes peel off in reverse priority order — ``background``
    at ``high_water``, ``batch`` midway between high water and full,
    ``interactive`` only at a genuinely full queue (which is exactly
    the pre-tenancy behavior, so latency-sensitive traffic is never
    worse off under this policy than under the plain full-queue
    check). A full queue sheds every class regardless.
    """

    def __init__(self, high_water: float = 0.5):
        hw = min(max(float(high_water), 0.0), 1.0)
        self.high_water = hw
        self._thresholds = {"background": hw,
                            "batch": (hw + 1.0) / 2.0,
                            "interactive": 1.0}

    def threshold(self, priority: str) -> float:
        """Pressure fraction at which ``priority`` starts shedding."""
        return self._thresholds.get(priority, 1.0)

    def should_shed(self, depth: int, capacity: int,
                    priority: str) -> bool:
        if capacity <= 0:
            return False
        if depth >= capacity:
            return True
        return depth >= self._thresholds.get(priority, 1.0) * capacity


class AdaptiveBatchPolicy:
    """Learn the arrival-rate/batch-size tradeoff online.

    ``stats_fn`` returns ``[(bucket_rows, edges, counts), ...]`` — one
    entry per per-bucket dispatch-latency histogram child.
    ``bucket_ladder`` is the reachable bucket set (the pow2 ladder
    clamped at ``max_batch_size``). ``ceiling_ms`` bounds any wait the
    policy may choose (the old fixed knob becomes the ceiling, so
    "adaptive" can only ever wait *less* than the configured worst
    case).

    Hot-path cost: :meth:`note_arrival` is one clock read + two float
    ops per request (called at enqueue); :meth:`tick` is one int bump
    per batch, with a bounded histogram walk every ``refresh_every``-th
    batch (the :class:`AdaptiveThreshold` cadence idiom).
    """

    def __init__(self, stats_fn: Callable[[], List[Tuple[int,
                                                         Sequence[float],
                                                         Sequence[int]]]],
                 bucket_ladder: Sequence[int],
                 ceiling_ms: float = 10.0,
                 quantile: float = 0.5,
                 min_count: int = 32,
                 refresh_every: int = 16,
                 ewma_alpha: float = 0.1,
                 max_gap_s: float = 5.0,
                 clock: Clock = SYSTEM_CLOCK):
        self.stats_fn = stats_fn
        self.ladder = sorted(int(b) for b in bucket_ladder)
        self.ceiling_ms = float(ceiling_ms)
        self.quantile = float(quantile)
        self.min_count = int(min_count)
        self.refresh_every = max(int(refresh_every), 1)
        self.alpha = float(ewma_alpha)
        self.max_gap_s = float(max_gap_s)
        self.clock = clock
        # inter-arrival EWMA (seconds); None until two arrivals seen
        self._gap_s: Optional[float] = None
        self._last_arrival: Optional[float] = None
        self._arrival_lock = threading.Lock()
        # bucket -> learned service time (ms); refreshed off-path
        self.service_ms: Dict[int, float] = {}
        self._n_samples = 0
        self._since = 0
        self.n_refreshes = 0
        self.last_wait_ms: Optional[float] = None

    # -- online inputs -------------------------------------------------------

    def note_arrival(self) -> None:
        """Called at ingress enqueue: fold one inter-arrival gap into
        the EWMA. Gaps past ``max_gap_s`` (an idle lull) reset the
        estimate instead of polluting it — after a quiet minute the
        first burst re-learns the rate from scratch."""
        now = self.clock.now()
        with self._arrival_lock:
            last, self._last_arrival = self._last_arrival, now
            if last is None:
                return
            gap = now - last
            if gap > self.max_gap_s:
                self._gap_s = None
                return
            self._gap_s = (gap if self._gap_s is None
                           else (1 - self.alpha) * self._gap_s
                           + self.alpha * gap)

    def tick(self, n: int = 1) -> None:
        """Per-batch cadence bump; every ``refresh_every``-th walks
        the histograms (racy plain int by design — a lost tick delays
        one refresh, free vs a lock on the commit path)."""
        self._since += n
        if self._since >= self.refresh_every:
            self._since = 0
            self.refresh()

    def refresh(self) -> None:
        """Re-read the per-bucket dispatch histograms into the service
        -time table (one quantile per seen bucket)."""
        from mmlspark_tpu.core.telemetry import quantile_from_buckets
        table: Dict[int, float] = {}
        total = 0
        for bucket, edges, counts in self.stats_fn():
            n = sum(counts)
            if n == 0:
                continue
            total += n
            q = quantile_from_buckets(tuple(edges), list(counts),
                                      self.quantile)
            if q is not None:
                table[int(bucket)] = q
        self.service_ms = table
        self._n_samples = total
        self.n_refreshes += 1

    # -- the decision --------------------------------------------------------

    @property
    def rate_per_s(self) -> Optional[float]:
        gap = self._gap_s
        return (1.0 / gap) if gap and gap > 0 else None

    def _service(self, bucket: int) -> Optional[float]:
        """Service time (ms) for ``bucket``: measured when seen;
        otherwise scaled from the nearest measured bucket (dispatch
        cost grows at most linearly in rows for a compiled shape —
        a conservative fill-in until the bucket is actually
        dispatched)."""
        if bucket in self.service_ms:
            return self.service_ms[bucket]
        if not self.service_ms:
            return None
        near = min(self.service_ms,
                   key=lambda b: abs(math.log(b) - math.log(bucket)))
        return self.service_ms[near] * max(bucket / near, 1.0)

    def decide_wait_ms(self, queued: int) -> Optional[float]:
        """The batch-mate wait for a batch currently holding
        ``queued`` rows; ``None`` = not warmed up, caller falls back
        to the fixed knob. 0.0 = dispatch now."""
        rate = self.rate_per_s
        if rate is None or self._n_samples < self.min_count:
            self.last_wait_ms = None
            return None
        queued = max(int(queued), 1)
        now_bucket = self._bucket_for(queued)
        base_svc = self._service(now_bucket)
        if base_svc is None:
            self.last_wait_ms = None
            return None
        # dispatch-now serves the REAL queued rows (the batch pads to
        # now_bucket regardless) — scoring the padded capacity here
        # would make waiting look never-worth-it at high rates, the
        # exact regime the policy exists for
        best_score = queued / max(base_svc, 1e-6)      # rows/ms, wait 0
        best_wait = 0.0
        for b in self.ladder:
            if b <= queued:
                continue
            wait_ms = (b - queued) / rate * 1000.0
            if wait_ms > self.ceiling_ms:
                break                     # ladder ascends: all later
            svc = self._service(b)        # buckets wait even longer
            if svc is None:
                continue
            score = b / max(wait_ms + svc, 1e-6)
            if score > best_score:
                best_score, best_wait = score, wait_ms
        self.last_wait_ms = round(best_wait, 3)
        return best_wait

    def _bucket_for(self, n: int) -> int:
        for b in self.ladder:
            if b >= n:
                return b
        return self.ladder[-1] if self.ladder else n

    def status(self) -> Dict[str, object]:
        rate = self.rate_per_s
        return {"rate_per_s": round(rate, 3) if rate else None,
                "n_samples": self._n_samples,
                "n_refreshes": self.n_refreshes,
                "service_ms": {str(k): round(v, 4)
                               for k, v in sorted(
                                   self.service_ms.items())},
                "last_wait_ms": self.last_wait_ms,
                "ceiling_ms": self.ceiling_ms}


class SpeculationPolicy:
    """Acceptance-gated speculation: keep speculative decoding on only
    while it pays.

    A speculative round costs one draft propose plus one width-k
    verify; it beats plain stepping only when the target accepts
    enough proposals. A drifting workload (or a draft that never
    agreed — the failure mode of a badly matched model pair) can push
    acceptance below break-even, at which point speculation is
    actively SLOWER than single-token decode. This policy tracks an
    acceptance EWMA fed by the scheduler after every round and turns
    speculation off below ``min_rate``; every ``reprobe_every``-th
    round while off, one PROBE round runs anyway so a workload that
    becomes draft-friendly again is rediscovered — the policy is
    hysteretic, never sticky-dead.

    ``warmup_rounds`` rounds always speculate (the EWMA needs
    evidence before it may veto)."""

    def __init__(self, min_rate: float = 0.3, alpha: float = 0.2,
                 warmup_rounds: int = 8, reprobe_every: int = 32):
        self.min_rate = float(min_rate)
        self.alpha = float(alpha)
        self.warmup_rounds = int(warmup_rounds)
        self.reprobe_every = max(int(reprobe_every), 1)
        self.rate: Optional[float] = None   # acceptance EWMA
        self.n_rounds = 0
        self.n_suppressed = 0
        self._since_probe = 0

    def should_speculate(self) -> bool:
        """Consulted once per scheduler round BEFORE the cohort is
        built; counts suppressed rounds toward the re-probe cadence."""
        if self.n_rounds < self.warmup_rounds or self.rate is None \
                or self.rate >= self.min_rate:
            return True
        self._since_probe += 1
        if self._since_probe >= self.reprobe_every:
            self._since_probe = 0
            return True                     # probe round
        self.n_suppressed += 1
        return False

    def note(self, proposed: int, accepted: int) -> None:
        """Fold one completed round's acceptance into the EWMA."""
        if proposed <= 0:
            return
        self.n_rounds += 1
        r = accepted / proposed
        self.rate = (r if self.rate is None
                     else (1 - self.alpha) * self.rate + self.alpha * r)

    def status(self) -> Dict[str, object]:
        return {"min_rate": self.min_rate,
                "acceptance_ewma": (round(self.rate, 4)
                                    if self.rate is not None else None),
                "n_rounds": self.n_rounds,
                "n_suppressed": self.n_suppressed,
                "speculating": (self.rate is None
                                or self.rate >= self.min_rate
                                or self.n_rounds < self.warmup_rounds)}
