"""Declarative SLOs + multi-window burn-rate alerting.

The serving plane's operational contract, stated as data: an
:class:`SLOPolicy` names an objective (availability, or a latency
threshold at a quantile) over metrics that already live in a
:class:`~mmlspark_tpu.core.telemetry.MetricsRegistry`, and the
:class:`SLOEngine` evaluates it with the multi-window burn-rate method
(the SRE-workbook alerting recipe): an alert condition holds only when
BOTH a long and a short window burn error budget faster than the
window pair's threshold — the long window filters blips, the short
window makes the alert resolve promptly once the cause is gone.

Everything here runs OFF the hot path. The engine never instruments
requests; it snapshots counter values and histogram bucket counts when
``evaluate()`` is called (``GET /alerts`` / ``GET /slo``, a scrape of
the firing gauge, or a test driving a ManualClock) and does window
math over the snapshot history. Counter deltas are clamped at zero so
a worker restart's counter reset reads as "no traffic", never negative
traffic (the fleet_stats idiom).

Alert lifecycle is a small state machine::

    ok -> pending --(for_s held)--> firing --(clear held
          resolve_after_s)--> resolved -> pending ...

``for_s`` and ``resolve_after_s`` are the anti-flap hysteresis: a burn
touching the threshold for one evaluation does not fire, and a firing
alert does not resolve until the condition has been clear for the
configured quiet period.

The optional :class:`AlertNotifier` POSTs firing/resolved transitions
to a webhook through the resilient HTTP client with a PRIVATE breaker
board (the MetricsPusher idiom): a dead alert receiver can never open
model-egress breakers, and notification failures are counted, never
raised.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from mmlspark_tpu.core.resilience import SYSTEM_CLOCK, Clock
from mmlspark_tpu.core.telemetry import quantile_from_buckets

#: default multi-window burn-rate pairs ``(long_s, short_s,
#: burn_threshold)`` — the SRE-workbook page/ticket pair: 14.4x burn
#: over (5 min, 1 min) exhausts a 30-day budget in ~2 days; 6x over
#: (1 h, 5 min) in ~5 days.
DEFAULT_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (300.0, 60.0, 14.4),
    (3600.0, 300.0, 6.0),
)


class SLOPolicy:
    """One declarative objective.

    ``kind="availability"``: ``objective`` is the good-fraction target
    (0.999 = "99.9% of ``total_metric`` must not be ``bad_metric``");
    burn rate = (bad/total over the window) / (1 - objective).

    ``kind="latency"``: ``objective`` is the fraction of observations
    that must land at or under ``threshold_ms`` on the histogram
    ``metric``; burn rate = (fraction over threshold) / (1 -
    objective). ``quantile`` is reported alongside (measured via
    :func:`quantile_from_buckets` over the long-window bucket deltas)
    so an operator sees the actual tail, not just the verdict.

    ``labels`` optionally restricts which children of the metric
    families count (exact-match on a subset of label names) — a
    per-route or per-tenant SLO is the same policy with a filter.
    """

    KINDS = ("availability", "latency")

    def __init__(self, name: str, kind: str, objective: float,
                 total_metric: Optional[str] = None,
                 bad_metric: Optional[str] = None,
                 metric: Optional[str] = None,
                 threshold_ms: Optional[float] = None,
                 quantile: float = 0.95,
                 labels: Optional[Dict[str, str]] = None,
                 windows: Iterable[Tuple[float, float, float]]
                 = DEFAULT_WINDOWS,
                 for_s: float = 0.0,
                 resolve_after_s: float = 60.0):
        if kind not in self.KINDS:
            raise ValueError(f"unknown SLO kind {kind!r} "
                             f"(expected one of {self.KINDS})")
        if not 0.0 < float(objective) < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {objective!r}")
        if kind == "availability":
            if not total_metric or not bad_metric:
                raise ValueError("availability SLOs need total_metric "
                                 "and bad_metric counter names")
        else:
            if not metric or threshold_ms is None:
                raise ValueError("latency SLOs need a histogram "
                                 "metric name and threshold_ms")
        self.name = str(name)
        self.kind = kind
        self.objective = float(objective)
        self.total_metric = total_metric
        self.bad_metric = bad_metric
        self.metric = metric
        self.threshold_ms = (float(threshold_ms)
                             if threshold_ms is not None else None)
        self.quantile = float(quantile)
        self.labels = dict(labels or {})
        self.windows = tuple(
            (float(l), float(s), float(t)) for l, s, t in windows)
        if not self.windows or any(
                l <= s for l, s, _ in self.windows):
            raise ValueError(
                "windows must be non-empty (long_s, short_s, "
                f"burn_threshold) triples with long > short, "
                f"got {windows!r}")
        self.for_s = float(for_s)
        self.resolve_after_s = float(resolve_after_s)

    @classmethod
    def from_value(cls, value: Any) -> "SLOPolicy":
        """A policy, a config dict, or a JSON string of one."""
        if isinstance(value, SLOPolicy):
            return value
        if isinstance(value, str):
            value = json.loads(value)
        if not isinstance(value, dict):
            raise ValueError(
                f"cannot build an SLOPolicy from {type(value).__name__}")
        return cls(**value)

    def metrics(self) -> Tuple[str, ...]:
        if self.kind == "availability":
            return (self.total_metric, self.bad_metric)
        return (self.metric,)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name, "kind": self.kind,
            "objective": self.objective,
            "windows": [list(w) for w in self.windows],
            "for_s": self.for_s,
            "resolve_after_s": self.resolve_after_s,
        }
        if self.kind == "availability":
            out["total_metric"] = self.total_metric
            out["bad_metric"] = self.bad_metric
        else:
            out["metric"] = self.metric
            out["threshold_ms"] = self.threshold_ms
            out["quantile"] = self.quantile
        if self.labels:
            out["labels"] = dict(self.labels)
        return out


class _AlertState:
    """The per-policy state machine (engine-lock protected)."""

    __slots__ = ("state", "pending_since", "last_violated", "fired_at",
                 "resolved_at", "n_fired", "n_resolved", "transitions")

    def __init__(self):
        self.state = "ok"
        self.pending_since: Optional[float] = None
        self.last_violated: Optional[float] = None
        self.fired_at: Optional[float] = None
        self.resolved_at: Optional[float] = None
        self.n_fired = 0
        self.n_resolved = 0
        # entries into each state, for the transitions counter view
        self.transitions: Dict[str, int] = {
            "pending": 0, "firing": 0, "resolved": 0}


class AlertNotifier:
    """Webhook delivery for firing/resolved transitions.

    Copies the MetricsPusher wiring exactly: the resilient
    :class:`~mmlspark_tpu.io.http.HTTPClient` with a bounded
    :class:`~mmlspark_tpu.core.resilience.RetryPolicy` and a PRIVATE
    :class:`~mmlspark_tpu.core.resilience.BreakerBoard` — the alert
    receiver's health is isolated from every other egress surface.
    Sends run on a short-lived daemon thread so a transition noticed
    during a metrics scrape never blocks the scrape on the webhook.
    Never raises."""

    def __init__(self, url: str, timeout: float = 5.0,
                 headers: Optional[Dict[str, str]] = None):
        self.url = url
        self.timeout = float(timeout)
        self.headers = dict(headers or {})
        self.n_sent = 0
        self.n_errors = 0
        self.last_status: Optional[int] = None
        self._client = None
        self._lock = threading.Lock()

    def _get_client(self):
        # lazy: io.http is only imported when a transition actually
        # needs delivering (mirrors MetricsPusher._get_client)
        if self._client is None:
            from mmlspark_tpu.core.resilience import (
                BreakerBoard, RetryPolicy,
            )
            from mmlspark_tpu.io.http import HTTPClient
            self._client = HTTPClient(
                timeout=self.timeout,
                policy=RetryPolicy(max_attempts=3, base=0.2, cap=2.0),
                breakers=BreakerBoard(failure_threshold=5,
                                      reset_timeout=30.0))
        return self._client

    def notify(self, event: Dict[str, Any]) -> None:
        """Fire-and-forget delivery of one transition event."""
        threading.Thread(target=self._send, args=(event,),
                         daemon=True, name="slo-notify").start()

    def _send(self, event: Dict[str, Any]) -> None:
        try:
            from mmlspark_tpu.core.tracing import trace_context
            from mmlspark_tpu.io.http import HTTPRequestData
            h = {"Content-Type": "application/json"}
            h.update(self.headers)
            req = HTTPRequestData(url=self.url, method="POST",
                                  headers=h,
                                  body=json.dumps(event).encode())
            # fresh trace id, no ambient span: a flaky receiver must
            # not churn the trace store every transition
            with trace_context():
                resp = self._get_client().send([req])[0]
            with self._lock:
                self.last_status = (resp.status_code
                                    if resp is not None else None)
                if resp is not None and 200 <= resp.status_code < 300:
                    self.n_sent += 1
                else:
                    self.n_errors += 1
        except Exception:  # noqa: BLE001 — alerting must never raise
            with self._lock:
                self.n_errors += 1
            from mmlspark_tpu.core.logs import get_logger
            get_logger("slo").warning(
                "alert webhook %s failed", self.url, exc_info=True)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {"url": self.url, "n_sent": self.n_sent,
                    "n_errors": self.n_errors,
                    "last_status": self.last_status}


class SLOEngine:
    """Burn-rate evaluation over a registry's counter/histogram state.

    ``evaluate()`` takes one snapshot of every policy-referenced
    family, appends it to a bounded history, and computes each
    policy's per-window burn rates from clamped deltas — then advances
    the alert state machines and (optionally) notifies transitions.
    Call it from ``GET /alerts`` / ``GET /slo`` handlers or a test
    loop; nothing here touches the request hot path.

    ``max_samples`` bounds history memory; when the ring is full the
    oldest snapshots drop and the long window degrades gracefully to
    "since the oldest retained sample" (reported as the effective
    window)."""

    def __init__(self, registry, policies: Iterable[SLOPolicy],
                 clock: Clock = SYSTEM_CLOCK,
                 notifier: Optional[AlertNotifier] = None,
                 max_samples: int = 4096,
                 min_eval_interval_s: float = 0.0):
        self.registry = registry
        self.policies: List[SLOPolicy] = [
            SLOPolicy.from_value(p) for p in policies]
        names = [p.name for p in self.policies]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate policy names in {names}")
        self.clock = clock
        self.notifier = notifier
        self._wanted = {m for p in self.policies for m in p.metrics()}
        self._history: "deque[Tuple[float, dict]]" = deque(
            maxlen=max(int(max_samples), 2))
        self._alerts: Dict[str, _AlertState] = {
            p.name: _AlertState() for p in self.policies}
        self._lock = threading.Lock()
        self._last_report: Optional[Dict[str, Any]] = None
        self._last_eval: Optional[float] = None
        self.n_evaluations = 0
        self.min_eval_interval_s = float(min_eval_interval_s)

    # -- snapshotting ---------------------------------------------------------

    def _collect(self) -> dict:
        """One snapshot of every policy-referenced family:
        ``{metric: (kind, edges, label_names, {label_key: value})}``
        where value is a float (counter/gauge) or a per-bucket count
        list (histogram)."""
        snap: dict = {}
        for fam in self.registry.families():
            if fam.name not in self._wanted:
                continue
            if fam.kind == "histogram":
                snap[fam.name] = (
                    "h", fam.buckets, fam.label_names,
                    {key: list(child.stats()["buckets"])
                     for key, child in fam.children()})
            else:
                snap[fam.name] = (
                    "c", None, fam.label_names,
                    {key: float(child.value)
                     for key, child in fam.children()})
        return snap

    def _baseline(self, now: float, window_s: float
                  ) -> Optional[Tuple[float, dict]]:
        """The OLDEST snapshot inside the window (first sample at or
        after ``now - window_s``, current sample excluded) — the
        window never stretches over older traffic, so a fresh error
        burst cannot be diluted by healthy history from before the
        window. Falls back to the newest sample before the window
        when an evaluation gap left none inside it (honest partial
        coverage); None when the current sample is the only one."""
        if len(self._history) < 2:
            return None
        target = now - window_s
        newest_before = None
        for t, snap in list(self._history)[:-1]:
            if t >= target:
                return (t, snap)
            newest_before = (t, snap)
        return newest_before

    @staticmethod
    def _match(policy_labels: Dict[str, str],
               label_names: Tuple[str, ...],
               key: Tuple[str, ...]) -> bool:
        if not policy_labels:
            return True
        have = dict(zip(label_names, key))
        return all(have.get(k) == v for k, v in policy_labels.items())

    def _deltas(self, metric: str, cur: dict, base: dict,
                labels: Dict[str, str]):
        """Per-child clamped deltas for one metric between two
        snapshots: ``(edges_or_None, {label_key: delta})`` where delta
        is a float or a per-bucket list. Missing metric -> empty."""
        cur_e = cur.get(metric)
        if cur_e is None:
            return None, {}
        kind, edges, label_names, cur_children = cur_e
        base_children = (base.get(metric) or (None, None, None, {}))[3]
        out: Dict[Tuple[str, ...], Any] = {}
        for key, val in cur_children.items():
            if not self._match(labels, label_names, key):
                continue
            prev = base_children.get(key)
            if kind == "h":
                if prev is None or len(prev) != len(val):
                    prev = [0] * len(val)
                # Prometheus reset semantics per bucket: a count below
                # its baseline means the worker restarted — the delta
                # is the post-reset count, never negative
                out[key] = [c - p if c >= p else c
                            for c, p in zip(val, prev)]
            else:
                prev_v = prev if prev is not None else 0.0
                out[key] = (val - prev_v if val >= prev_v
                            else max(val, 0.0))
        return edges, out

    # -- burn math ------------------------------------------------------------

    def _availability_burn(self, policy: SLOPolicy, cur: dict,
                           base: dict) -> Tuple[float, float, float,
                                                Dict[Tuple[str, ...],
                                                     float]]:
        """``(burn, bad, total, per_child_bad)`` over one window."""
        _, bad_d = self._deltas(policy.bad_metric, cur, base,
                                policy.labels)
        _, tot_d = self._deltas(policy.total_metric, cur, base,
                                policy.labels)
        bad = float(sum(bad_d.values()))
        total = float(sum(tot_d.values()))
        rate = bad / total if total > 0 else 0.0
        return rate / (1.0 - policy.objective), bad, total, bad_d

    def _latency_burn(self, policy: SLOPolicy, cur: dict, base: dict
                      ) -> Tuple[float, float, float,
                                 Optional[Tuple[tuple, List[int]]]]:
        """``(burn, over, total, (edges, summed_deltas))``."""
        edges, deltas = self._deltas(policy.metric, cur, base,
                                     policy.labels)
        if edges is None or not deltas:
            return 0.0, 0.0, 0.0, None
        summed = [0] * (len(edges) + 1)
        for counts in deltas.values():
            for i, c in enumerate(counts):
                summed[i] += c
        total = float(sum(summed))
        if total <= 0:
            return 0.0, 0.0, 0.0, (edges, summed)
        # observations in buckets whose upper edge is <= threshold are
        # good; the first edge >= threshold is the boundary (ladder
        # edges rarely equal the threshold exactly — the honest
        # reading is "at most this many were over")
        good = 0.0
        for i, edge in enumerate(edges):
            if edge <= policy.threshold_ms:
                good += summed[i]
            else:
                break
        over = total - good
        burn = (over / total) / (1.0 - policy.objective)
        return burn, over, total, (edges, summed)

    # -- evaluation -----------------------------------------------------------

    def maybe_evaluate(self) -> None:
        """Opportunistic evaluation for scrape-time freshness: skips
        when another thread is evaluating or the min interval has not
        elapsed. Never blocks."""
        if not self._lock.acquire(blocking=False):
            return
        try:
            now = self.clock.now()
            if self._last_eval is not None and \
                    now - self._last_eval < max(
                        self.min_eval_interval_s, 1.0):
                return
            self._evaluate_locked(now)
        finally:
            self._lock.release()

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One full evaluation pass; returns the burn report and
        advances alert states."""
        with self._lock:
            return self._evaluate_locked(
                self.clock.now() if now is None else float(now))

    def observe(self, now: float, snap: dict) -> Dict[str, Any]:
        """Evaluate against an externally-collected snapshot (the
        TSDB Recorder's unified scrape — see
        :meth:`mmlspark_tpu.core.tsdb.Scrape.slo_snapshot`), so one
        scrape per interval feeds the dumper, the TSDB, AND this
        engine's history instead of each taking its own."""
        with self._lock:
            return self._evaluate_locked(float(now), snap)

    def wanted_metrics(self) -> set:
        """The metric names the policies reference — what an external
        snapshot must cover."""
        return set(self._wanted)

    def _evaluate_locked(self, now: float,
                         snap: Optional[dict] = None) -> Dict[str, Any]:
        if snap is None:
            snap = self._collect()
        if self._history and self._history[-1][0] >= now:
            # same (or rewound) instant: replace rather than duplicate
            self._history.pop()
        self._history.append((now, snap))
        # prune beyond the widest long window (plus slack for the
        # baseline just outside it)
        horizon = now - 2.0 * max(
            l for p in self.policies for l, _, _ in p.windows)
        while len(self._history) > 2 and self._history[1][0] <= horizon:
            self._history.popleft()
        transitions: List[Dict[str, Any]] = []
        report_policies = []
        for policy in self.policies:
            rep = self._evaluate_policy(policy, now, snap)
            self._advance_alert(policy, rep, now, transitions)
            alert = self._alerts[policy.name]
            rep["state"] = alert.state
            rep["fired_at"] = alert.fired_at
            rep["resolved_at"] = alert.resolved_at
            rep["n_fired"] = alert.n_fired
            report_policies.append(rep)
        self._last_eval = now
        self.n_evaluations += 1
        report = {
            "at": now,
            "n_evaluations": self.n_evaluations,
            "n_samples": len(self._history),
            "policies": report_policies,
            "firing": sum(1 for r in report_policies
                          if r["state"] == "firing"),
        }
        self._last_report = report
        if self.notifier is not None:
            for ev in transitions:
                self.notifier.notify(ev)
        return report

    def _evaluate_policy(self, policy: SLOPolicy, now: float,
                         snap: dict) -> Dict[str, Any]:
        windows = []
        violated = False
        long_detail: Dict[str, Any] = {}
        for long_s, short_s, threshold in policy.windows:
            row: Dict[str, Any] = {"long_s": long_s, "short_s": short_s,
                                   "burn_threshold": threshold}
            burns = {}
            for tag, win in (("long", long_s), ("short", short_s)):
                base = self._baseline(now, win)
                if base is None:
                    burns[tag] = 0.0
                    row[f"burn_{tag}"] = 0.0
                    row[f"window_{tag}_s"] = 0.0
                    continue
                b_t, b_snap = base
                row[f"window_{tag}_s"] = round(now - b_t, 3)
                if policy.kind == "availability":
                    burn, bad, total, bad_children = \
                        self._availability_burn(policy, snap, b_snap)
                    if tag == "long" and not long_detail:
                        long_detail = {"bad": bad, "total": total,
                                       "error_rate": round(
                                           bad / total, 6)
                                       if total > 0 else 0.0,
                                       "_bad_children": bad_children}
                else:
                    burn, over, total, hist = \
                        self._latency_burn(policy, snap, b_snap)
                    if tag == "long" and not long_detail:
                        long_detail = {"over_threshold": over,
                                       "total": total}
                        if hist is not None:
                            edges, counts = hist
                            q = quantile_from_buckets(
                                edges, counts, policy.quantile)
                            long_detail["measured_ms"] = (
                                round(q, 3) if q is not None else None)
                burns[tag] = burn
                row[f"burn_{tag}"] = round(burn, 4)
            row["violated"] = (burns.get("long", 0.0) >= threshold
                               and burns.get("short", 0.0) >= threshold)
            violated = violated or row["violated"]
            windows.append(row)
        rep: Dict[str, Any] = {
            "policy": policy.name, "kind": policy.kind,
            "objective": policy.objective,
            "windows": windows, "violated": violated,
        }
        if policy.kind == "latency":
            rep["threshold_ms"] = policy.threshold_ms
            rep["quantile"] = policy.quantile
        if policy.labels:
            rep["labels"] = dict(policy.labels)
        bad_children = long_detail.pop("_bad_children", None)
        rep.update(long_detail)
        if policy.kind == "availability" and bad_children:
            # per-child attribution over the first long window: who is
            # actually burning budget (the coordinator's per-worker
            # labels land here)
            fam = snap.get(policy.bad_metric)
            label_names = fam[2] if fam is not None else ()
            rows = sorted(
                ((key, delta) for key, delta in bad_children.items()
                 if delta > 0),
                key=lambda kv: -kv[1])[:8]
            rep["attribution"] = [
                {"labels": dict(zip(label_names, key)),
                 "bad": delta} for key, delta in rows]
        return rep

    def _advance_alert(self, policy: SLOPolicy, rep: Dict[str, Any],
                       now: float,
                       transitions: List[Dict[str, Any]]) -> None:
        alert = self._alerts[policy.name]
        violated = rep["violated"]
        if violated:
            alert.last_violated = now
            if alert.state in ("ok", "resolved"):
                alert.state = "pending"
                alert.pending_since = now
                alert.transitions["pending"] += 1
            if alert.state == "pending" and \
                    now - (alert.pending_since or now) >= policy.for_s:
                alert.state = "firing"
                alert.fired_at = now
                alert.n_fired += 1
                alert.transitions["firing"] += 1
                transitions.append(self._event("firing", policy, rep,
                                               now))
        else:
            if alert.state == "pending":
                # never fired: fold straight back to ok, no event
                alert.state = "ok"
                alert.pending_since = None
            elif alert.state == "firing":
                # the quiet clock counts from the LAST violated
                # evaluation — a re-violation mid-quiet resets it
                ref = alert.last_violated if alert.last_violated \
                    is not None else (alert.fired_at or now)
                if now - ref >= policy.resolve_after_s:
                    alert.state = "resolved"
                    alert.resolved_at = now
                    alert.n_resolved += 1
                    alert.transitions["resolved"] += 1
                    transitions.append(self._event("resolved", policy,
                                                   rep, now))

    @staticmethod
    def _event(kind: str, policy: SLOPolicy, rep: Dict[str, Any],
               now: float) -> Dict[str, Any]:
        return {"type": kind, "policy": policy.name,
                "slo_kind": policy.kind,
                "objective": policy.objective,
                "at_mono": now, "at_unix": time.time(),
                "report": {k: v for k, v in rep.items()
                           if k != "attribution"},
                "attribution": rep.get("attribution")}

    # -- views ----------------------------------------------------------------

    def alerts(self) -> Dict[str, Any]:
        """Evaluate, then return the compact alert view (state + the
        violating window pair per policy) — the ``GET /alerts``
        body."""
        report = self.evaluate()
        alerts = []
        for rep in report["policies"]:
            if rep["state"] == "ok" and not rep["violated"]:
                continue
            alerts.append({
                "policy": rep["policy"], "kind": rep["kind"],
                "state": rep["state"],
                "objective": rep["objective"],
                "violated": rep["violated"],
                "windows": [w for w in rep["windows"]
                            if w["violated"]] or rep["windows"],
                "fired_at": rep.get("fired_at"),
                "resolved_at": rep.get("resolved_at"),
                "n_fired": rep.get("n_fired", 0),
                "attribution": rep.get("attribution"),
            })
        return {"at": report["at"], "firing": report["firing"],
                "alerts": alerts}

    def firing(self) -> List[str]:
        with self._lock:
            return [name for name, a in self._alerts.items()
                    if a.state == "firing"]

    def status(self) -> Dict[str, Any]:
        """Compact engine state for ``/stats`` echo — last evaluation
        summary, no fresh evaluation."""
        with self._lock:
            return {
                "n_policies": len(self.policies),
                "policies": {p.name: self._alerts[p.name].state
                             for p in self.policies},
                "firing": [n for n, a in self._alerts.items()
                           if a.state == "firing"],
                "n_evaluations": self.n_evaluations,
                "n_samples": len(self._history),
                "last_eval": self._last_eval,
                "notifier": (self.notifier.status()
                             if self.notifier is not None else None),
            }

    def register_metrics(self, m) -> None:
        """Firing gauges + transition counters as exposition-time
        views (the serving counter idiom). The gauge's view runs
        :meth:`maybe_evaluate` first so an external scraper sees
        current state without anything else polling ``/alerts``."""
        g = m.gauge("serving_slo_alerts_firing",
                    "1 while the policy's alert is firing.",
                    labels=("policy",))
        c = m.counter("serving_slo_transitions_total",
                      "Alert state-machine entries, by policy and "
                      "destination state.", labels=("policy", "state"))
        first = True
        for policy in self.policies:
            alert = self._alerts[policy.name]
            if first:
                # one child refreshes state per scrape; the rest read
                first = False

                def _firing_fresh(a=alert):
                    self.maybe_evaluate()
                    return 1.0 if a.state == "firing" else 0.0

                g.labels(policy.name).set_function(_firing_fresh)
            else:
                g.labels(policy.name).set_function(
                    lambda a=alert: 1.0 if a.state == "firing" else 0.0)
            for state in ("pending", "firing", "resolved"):
                c.labels(policy.name, state).set_function(
                    lambda a=alert, s=state: a.transitions[s])


# -- stock policy sets --------------------------------------------------------

def default_worker_policies(
        has_decoder: bool = False,
        windows: Iterable[Tuple[float, float, float]] = DEFAULT_WINDOWS,
        for_s: float = 0.0,
        resolve_after_s: float = 60.0) -> List[SLOPolicy]:
    """The stock per-worker objectives: request availability, dispatch
    latency, and (decode planes) TTFT/TPOT. Thresholds are deliberately
    loose — they are the "is it on fire" layer, not a tuning tool;
    operators override via ``ServingServer(slo=[...])``."""
    kw = dict(windows=windows, for_s=for_s,
              resolve_after_s=resolve_after_s)
    policies = [
        SLOPolicy("availability", "availability", 0.999,
                  total_metric="serving_requests_total",
                  bad_metric="serving_errors_total", **kw),
        SLOPolicy("dispatch_latency", "latency", 0.99,
                  metric="serving_dispatch_latency_ms",
                  threshold_ms=1000.0, quantile=0.95, **kw),
    ]
    if has_decoder:
        policies.append(SLOPolicy(
            "decode_ttft", "latency", 0.99,
            metric="serving_decode_ttft_ms",
            threshold_ms=2500.0, quantile=0.95, **kw))
        policies.append(SLOPolicy(
            "decode_tpot", "latency", 0.99,
            metric="serving_decode_tpot_ms",
            threshold_ms=250.0, quantile=0.95, **kw))
    return policies


def resolve_policies(value: Any,
                     has_decoder: bool = False) -> List[SLOPolicy]:
    """The ``ServingServer(slo=...)`` surface: None -> the stock set;
    a list -> explicit policies (dicts or :class:`SLOPolicy`); a dict
    -> the stock set with ``windows``/``for_s``/``resolve_after_s``
    overridden (plus an optional ``"policies"`` list replacing the
    stock set outright)."""
    if value is None:
        return default_worker_policies(has_decoder)
    if isinstance(value, dict):
        if "policies" in value:
            return [SLOPolicy.from_value(p) for p in value["policies"]]
        kw: Dict[str, Any] = {"has_decoder": has_decoder}
        for k in ("windows", "for_s", "resolve_after_s"):
            if k in value:
                kw[k] = value[k]
        return default_worker_policies(**kw)
    return [SLOPolicy.from_value(p) for p in value]
