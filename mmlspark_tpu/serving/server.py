"""Serving: HTTP frontend -> pipelined data plane -> jitted inference -> replies.

Capability parity with Spark Serving (`HTTPSourceV2.scala:50,178,272`,
`HTTPSinkV2.scala:20-106`, `DistributedHTTPSource.scala:89,244`,
`ServingUDFs.scala:15`) rebuilt for the TPU execution model: instead of
streaming rows through a query plan, each host runs an HTTP server whose
requests are micro-batched into a columnar frame, pushed through any
fitted Transformer (whose own jitted/sharded forward runs on TPU), and
answered from the output columns. Request identity -> reply routing is
the in-process equivalent of the reference's exchange-id state holder.

The data plane is a staged pipeline (the TPU-side analogue of the
reference's micro-batch assembly overlapping engine execution):

1. **collect + assemble** — drain the request queue into a micro-batch,
   run deadline check #1, build the columnar frame directly from the
   payloads (no per-row dict round-trip for homogeneous JSON objects),
   and pad it up to a power-of-two **shape bucket**
   (:func:`mmlspark_tpu.parallel.sharding.pad_to_bucket`), so
   steady-state traffic dispatches a fixed set of compiled shapes and
   the jitted forward never retraces;
2. **dispatch** — push the bucketed frame through the model and hand the
   output straight to the encoders, so host work for batch N+1 overlaps
   model execution for batch N;
3. **encode + commit** — unpad, select ``reply_cols``, JSON-encode
   (columnar fast path for scalar reply columns), run deadline check #2,
   and commit replies/journal exactly as the serial plane did.

``pipeline=False`` runs the same three stages inline on one thread (the
pre-pipeline behavior; also the A/B baseline for
``tools/bench_serving_pipeline.py``). Per-stage wall-clock timings and a
recompile counter (new dispatch shapes seen) are exported via
``GET /stats``.

Telemetry (see ``docs/observability.md``): every worker serves a
Prometheus text exposition at ``GET /metrics`` (per-stage span
histograms, per-bucket dispatch latency, backlog/inflight gauges,
shed/deadline/recompile counters, process vitals) from a per-server
:class:`~mmlspark_tpu.core.telemetry.MetricsRegistry` plus the
process-wide one; every request carries an ``X-Trace-Id`` (inbound or
minted at ingress) through the staged pipeline, journal lines, log
records, and any model-internal HTTP egress; and the coordinator's
``GET /fleet`` / ``GET /fleet/metrics`` merge N workers into one view
that names the fleet's slowest stage.

Multi-host: workers register with a :class:`ServingCoordinator` (parity:
DriverServiceUtils' coordination server, `HTTPSourceV2.scala:111-167`).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse as _urlparse
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from queue import Empty, Full, Queue, SimpleQueue
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from collections import deque

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.logs import get_logger, install_log_ring
from mmlspark_tpu.core.profiler import SamplingProfiler
from mmlspark_tpu.core.profiling import (
    CompileLedger, DeviceProfiler, MfuMeter, ProfilerBusy,
    StageTimings, device_memory_stats, process_rss_bytes,
    process_uptime_s,
)
from mmlspark_tpu.parallel.sharding import (
    bucket_ladder, bucket_target, padded_device_batch,
)
from mmlspark_tpu.core.resilience import (
    SYSTEM_CLOCK, BreakerBoard, Clock, Deadline, DeadlineExceeded,
    RetryPolicy,
)
from mmlspark_tpu.core.serialize import _jsonify
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.core.telemetry import (
    CONTENT_TYPE as _METRICS_CONTENT_TYPE,
    OPENMETRICS_CONTENT_TYPE as _OPENMETRICS_CONTENT_TYPE,
    MetricsRegistry, REGISTRY,
    TRACE_HEADER, current_trace_id, merge_prometheus, new_trace_id,
    register_build_info, render_registries, render_samples,
    trace_context,
)
from mmlspark_tpu.core.tsdb import (
    AnomalyDetector, AnomalyWatch, DEFAULT_TIERS, QueryError, Recorder,
    RecordingRule, TimeSeriesStore, default_serving_rules,
    default_serving_watches,
)
from mmlspark_tpu.core.tracing import (
    CAPTURE_HEADER, PARENT_SPAN_HEADER, TRACER, AdaptiveThreshold,
    ambient_tracer, capture_hint, extract_span_context, format_span_id,
    merge_traces, span_tree, to_perfetto,
)
from mmlspark_tpu.serving.decode import DecodeOverloaded, DecodeScheduler
from mmlspark_tpu.serving.frontend import EventLoopFrontend, batched_replies
from mmlspark_tpu.serving.incident import FanoutNotifier, IncidentManager
from mmlspark_tpu.serving.policy import AdaptiveBatchPolicy
from mmlspark_tpu.serving.quant import QuantizationConfig
from mmlspark_tpu.serving.rollout import (
    ModelVersionManager, RolloutError, RolloutOrchestrator,
)
from mmlspark_tpu.serving.slo import (
    AlertNotifier, DEFAULT_WINDOWS, SLOEngine, SLOPolicy,
    resolve_policies,
)
from mmlspark_tpu.serving.tenancy import (
    ANONYMOUS_ID, FairCycle, TenantRegistry, extract_api_key,
)

logger = get_logger("serving")


class _Server(ThreadingHTTPServer):
    # the stdlib default backlog (5) resets connections under bursty load;
    # serving frontends must absorb a full batch's worth of simultaneous
    # connects
    request_queue_size = 1024
    daemon_threads = True


# anonymous request ids: a process-unique random prefix + a counter.
# uuid4() costs an os.urandom syscall per request — pure overhead for
# requests that never supplied an X-Request-Id (their rid only keys the
# in-flight table, never crosses the wire)
import itertools

_RID_PREFIX = uuid.uuid4().hex[:16]
_RID_COUNTER = itertools.count()    # .__next__ is atomic under the GIL

#: cap on remembered dispatch shapes (recompile dedup / /stats evidence);
#: a healthy bucketed worker uses ~log2(max_batch_size) of these
_MAX_SHAPES_TRACKED = 1024


class _PendingRequest:
    __slots__ = ("rid", "payload", "event", "reply", "status", "deadline",
                 "trace", "span", "t_enqueue", "callbacks", "stream",
                 "tenant")

    def __init__(self, payload: Any, rid: Optional[str] = None,
                 deadline: Optional[Deadline] = None,
                 trace: Optional[str] = None):
        self.rid = rid or f"{_RID_PREFIX}-{next(_RID_COUNTER):x}"
        self.payload = payload
        self.event = threading.Event()
        # completion fan-out: the threaded frontend's handler threads
        # block on ``event``; the event-loop frontend registers a
        # callback here instead (fired at commit, from whichever stage
        # thread resolves the request) — both may be active at once
        # when a threaded retry joins a request an event-loop client
        # enqueued, or vice versa
        self.callbacks: List[Any] = []
        self.reply: Optional[bytes] = None
        self.status = 200
        self.deadline = deadline
        # the request's X-Trace-Id (inbound or minted at ingress):
        # carried on the work item because the staged pipeline crosses
        # threads, where contextvars do not follow — each stage
        # re-enters trace_context from this field
        self.trace = trace or new_trace_id()
        # the request's ROOT span (and enqueue timestamp): carried for
        # the same cross-thread reason — each stage records its child
        # spans (queue_wait/assemble/dispatch/encode/commit) under this
        # parent. None for synthetic warmup work, which records nothing.
        self.span = None
        self.t_enqueue: Optional[float] = None
        # token-streaming handle (decode plane, stream=1): the decode
        # scheduler emits per-token SSE events through it and finishes
        # the chunked body at resolution; None for everything else
        self.stream = None
        # owning tenant id while this request holds a tenant in-flight
        # slot (tenancy enabled); cleared by the release funnel so the
        # slot can never be returned twice
        self.tenant: Optional[str] = None


class _ThreadedStream:
    """Token-stream handle for the threaded frontend: the decode
    scheduler's ``emit``/``finish`` land on a queue the blocked
    handler thread drains into chunked writes (the threaded analogue
    of :class:`~mmlspark_tpu.serving.frontend._EventLoopStream`).
    ``closed`` flips on a write error (client gone) or a stalled
    stream; producers poll it and cancel."""

    __slots__ = ("q", "closed", "done", "t_first")

    def __init__(self):
        self.q: "Queue[tuple]" = Queue()
        self.closed = False
        self.done = False
        # monotonic stamp of the first chunk actually written to the
        # client socket — the socket-edge TTFT (0.0 = none yet)
        self.t_first = 0.0

    def emit(self, data: bytes) -> None:
        if not (self.closed or self.done):
            self.q.put((data, False))

    def finish(self, data: bytes = b"") -> None:
        if self.closed or self.done:
            return
        self.done = True
        self.q.put((data, True))


def _stream_requested(path: str, payload: Any) -> bool:
    """Token streaming opt-in: ``?stream=1`` on the decode path or
    ``"stream": true`` in the payload. The query is parsed per
    parameter — ``stream=10`` or ``upstream=1`` must NOT upgrade a
    client that expects a plain JSON reply."""
    q = path.partition("?")[2]
    if q and any(p == "stream=1" for p in q.split("&")):
        return True
    return isinstance(payload, dict) and payload.get("stream") is True


class ServingServer:
    """One host's serving frontend.

    ``model`` is any Transformer; request JSON objects become rows of a
    micro-batched frame, ``reply_cols`` (default: columns the model added)
    are returned per row as JSON.
    """

    def __init__(self, model: Transformer, host: str = "127.0.0.1",
                 port: int = 0, api_path: str = "/predict",
                 max_batch_size: int = 64, max_latency_ms: float = 10.0,
                 reply_cols: Optional[List[str]] = None,
                 request_timeout: float = 30.0,
                 journal_size: int = 4096,
                 journal_ttl: Optional[float] = None,
                 journal_path: Optional[str] = None,
                 idle_timeout: Optional[float] = 60.0,
                 max_queue: int = 1024,
                 shed_retry_after: float = 0.1,
                 pipeline: bool = True,
                 bucket_batches: bool = True,
                 encoder_threads: int = 2,
                 max_inflight_batches: int = 2,
                 slow_trace_ms: Optional[float] = 250.0,
                 adaptive_slow_trace: bool = True,
                 adaptive_floor_ms: float = 25.0,
                 adaptive_ceiling_ms: float = 5000.0,
                 adaptive_min_count: int = 50,
                 tracer=None,
                 frontend: str = "eventloop",
                 acceptors: int = 1,
                 reuse_port: bool = False,
                 max_conns_per_ip: int = 0,
                 max_pipelined_per_iter: int = 16,
                 model_version: str = "v1",
                 verify_checkpoints: bool = True,
                 rollout_fault_plan=None,
                 decoder: Optional[DecodeScheduler] = None,
                 decode_path: str = "/generate",
                 batch_policy: str = "fixed",
                 capture=None,
                 quantization=None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 ssl_context=None,
                 tenancy=None,
                 slo=None,
                 slo_webhook: Optional[str] = None,
                 tsdb=None,
                 profile_dir: Optional[str] = None,
                 cpu_profiler=None,
                 incidents=None,
                 clock: Clock = SYSTEM_CLOCK):
        self.api_path = api_path
        self.max_batch_size = int(max_batch_size)
        self.max_latency_ms = float(max_latency_ms)
        self.reply_cols = reply_cols
        self.request_timeout = request_timeout
        # -- data plane: with ``pipeline`` (the default) collection,
        # model dispatch, and reply encoding run as separate stages on
        # their own threads, so host JSON/frame work for batch N+1
        # overlaps model execution for batch N. ``bucket_batches`` pads
        # every live batch up to the shared power-of-two bucket ladder
        # (pad_to_bucket) so steady-state traffic hits a fixed set of
        # compiled executables: models see padded row counts; replies
        # are always unpadded. ``max_inflight_batches`` bounds the
        # pipeline depth (backpressure to the collector), and
        # ``encoder_threads`` sizes the reply-encoder pool.
        self.pipeline = bool(pipeline)
        self.bucket_batches = bool(bucket_batches)
        self.encoder_threads = max(int(encoder_threads), 1)
        self.max_inflight_batches = max(int(max_inflight_batches), 1)
        # -- telemetry: a PER-SERVER registry (two workers in one test
        # process must never mix counts) rendered by ``GET /metrics``
        # together with the process-wide REGISTRY. StageTimings is a
        # thin view over the same registry, so /stats and /metrics
        # report the one set of samples. The pre-existing plain-int
        # counters (n_shed, n_recompiles, ...) stay the source of truth
        # — the registry exposes them through exposition-time callbacks,
        # so the request hot path pays nothing for the counter surface;
        # only the per-bucket dispatch histogram adds a (sub-us) observe
        # per BATCH.
        # the server's injectable clock feeds the registry too, so
        # chaos tests drive Histogram.time() spans deterministically
        self.registry = MetricsRegistry(clock=clock)
        self.timings = StageTimings(registry=self.registry,
                                    metric="serving_stage_duration_ms")
        # -- versioned hot-swap: the manager owns the ACTIVE model
        # version the dispatch stage reads (one snapshot per batch, so
        # a flip lands between batches and in-flight batches finish on
        # the version that dispatched them), plus at most one staged
        # next version (loaded/digest-verified/bucket-warmed in the
        # background) and the previous version kept resident for
        # instant rollback — see serving/rollout.py and docs/serving.md
        # "Zero-downtime rollout". ``model_version`` names the boot
        # version; ``verify_checkpoints=False`` disables the strict
        # flip-eligibility digest check (tests only).
        # -- the quantized wire (optional): a per-version
        # QuantizationConfig rides the ModelVersion — the dispatch
        # stage casts the assembled frame to the wire dtype (u8/int8)
        # right after its version snapshot, the model dequantizes on
        # device (x*scale+zero_point fused into the first layer), and
        # serving_wire_bytes_total{dtype} counts what actually crossed
        # to the device. Validated at construction: a malformed
        # scale/zero-point raises here (and 400s at the rollout
        # endpoint), never dispatches garbage. When the model itself
        # carries a config (a persisted quantized checkpoint), it is
        # adopted — one source of truth either way.
        quantization = QuantizationConfig.from_value(quantization)
        if quantization is None:
            quantization = QuantizationConfig.from_value(
                getattr(model, "quantization", None))
        if quantization is not None:
            quantization.configure_model(model)
        self.versions = ModelVersionManager(
            self, model, version=model_version,
            verify_checkpoints=verify_checkpoints,
            fault_plan=rollout_fault_plan,
            quantization=quantization)
        self._m_wire_bytes = self.registry.counter(
            "serving_wire_bytes_total",
            "Bytes of assembled frame columns dispatched into the "
            "model, labeled by column dtype — the bytes-on-wire "
            "evidence that the quantized plane is engaged (u8 rows "
            "are 4x smaller than f32).", labels=("dtype",))
        # remembered by warmup(): staged versions warm with the same
        # payload schema unless the rollout supplies its own
        self.warmup_payload: Any = None
        # -- tracing: one root span per request, child spans per stage,
        # recorded into the process-wide flight recorder. Tail capture:
        # a completed trace is RETAINED (GET /trace/<id>) only when its
        # root exceeded ``slow_trace_ms`` (per-route threshold, keyed by
        # api_path) or ended non-ok (error/shed/deadline/timeout);
        # everything else is dropped after the histograms have their
        # samples. ``tracer`` is injectable so tests drive captures with
        # a ManualClock-backed private tracer. NOTE: thresholds are
        # per-(tracer, route) — two servers sharing the process TRACER
        # and one api_path share one threshold (last constructed wins);
        # inject private tracers where that matters (tests, A/B tools).
        self.tracer = tracer if tracer is not None else TRACER
        self.slow_trace_ms = slow_trace_ms
        self.tracer.set_threshold(api_path, slow_trace_ms)
        if decoder is not None:
            # the decode route shares the configured threshold — without
            # this, trace-everything mode (0.0) never applied to decode
            # requests and their token-timeline spans were unreachable
            # via GET /trace/<id>
            self.tracer.set_threshold(decode_path, slow_trace_ms)
        self._m_dispatch = self.registry.histogram(
            "serving_dispatch_latency_ms",
            "Model dispatch wall-clock per shape bucket (label = padded "
            "row count actually dispatched).", labels=("bucket",))
        # billing-grade device-time attribution: each batch's dispatch
        # wall-clock is pro-rated across the tenants whose rows rode it
        # (the decode plane pro-rates its step/spec-round/prefill time
        # the same way through this family — see decode.py)
        self._m_tenant_device = self.registry.counter(
            "serving_tenant_device_ms_total",
            "Device wall-clock milliseconds attributed to each tenant: "
            "batch dispatch pro-rated by rows, decode steps pro-rated "
            "by active slots, prefill charged to its request.",
            labels=("tenant",))
        # -- adaptive tail-capture threshold: once the route has enough
        # dispatch-latency samples (adaptive_min_count — until then the
        # configured slow_trace_ms keeps ruling), the threshold tracks
        # the route's own p95 (clamped to [floor, ceiling]), refreshed
        # every few batches from the encoder thread — a route whose
        # baseline is 8 ms captures its 40 ms outliers, one whose
        # baseline is 400 ms stops capturing everything. Disabled when
        # adaptation is off or the fixed threshold is a sentinel
        # (0 = trace-everything harness mode, None = errors only).
        self.adaptive: Optional[AdaptiveThreshold] = None
        if adaptive_slow_trace and slow_trace_ms is not None \
                and slow_trace_ms > 0:
            fam = self._m_dispatch
            self.adaptive = AdaptiveThreshold(
                self.tracer, api_path,
                lambda: [(fam.buckets, c.stats()["buckets"])
                         for _, c in fam.children()],
                floor_ms=adaptive_floor_ms,
                ceiling_ms=adaptive_ceiling_ms,
                min_count=adaptive_min_count)
        # -- adaptive micro-batching (A/B vs the fixed knob): with
        # ``batch_policy="adaptive"`` the collector's batch-mate wait
        # is decided per batch from the measured arrival rate and the
        # per-bucket dispatch-latency histograms, with the configured
        # ``max_latency_ms`` demoted to a hard ceiling — see
        # serving/policy.py and docs/serving.md "Adaptive batching".
        # ``"fixed"`` (the default) keeps the constant knob.
        self.batch_policy = str(batch_policy)
        if self.batch_policy not in ("fixed", "adaptive"):
            raise ValueError(
                f"unknown batch_policy {batch_policy!r} "
                "(expected 'fixed' or 'adaptive')")
        self.adaptive_batcher: Optional[AdaptiveBatchPolicy] = None
        if self.batch_policy == "adaptive":
            fam = self._m_dispatch

            def _bucket_stats():
                out = []
                for key, child in fam.children():
                    try:
                        rows = int(key[0])
                    except (IndexError, ValueError):
                        continue
                    out.append((rows, fam.buckets,
                                child.stats()["buckets"]))
                return out

            self.adaptive_batcher = AdaptiveBatchPolicy(
                _bucket_stats, self._bucket_sizes(),
                ceiling_ms=self.max_latency_ms, clock=clock)
        # -- continuous-batching decode plane (optional): POSTs to
        # ``decode_path`` route to a DecodeScheduler (slot-indexed
        # KV-cache continuous batching — serving/decode.py) through
        # the SAME admission path as the frame plane, so replay/join/
        # shed/deadline/journal semantics are identical. GET
        # /decode/stats exposes slot occupancy + in-flight progress.
        self.decode_path = decode_path
        self.decoder = decoder
        self.n_recompiles = 0
        self._shapes_seen: set = set()
        self._stats_lock = threading.Lock()
        # accepted-but-undispatched request count: the overload signal.
        # The ingress queue alone no longer measures backlog — the
        # pipelined collector drains it into the dispatch stage — so
        # shedding counts every request that has been accepted but has
        # not yet entered the model (ingress queue + staged batches).
        self._n_backlog = 0
        self._dispatch_q: "Queue[dict]" = Queue(
            maxsize=self.max_inflight_batches)
        self._encode_q: "Queue[dict]" = Queue(
            maxsize=2 * self.max_inflight_batches)
        # None (stdlib idiom) and <= 0 both mean "no keep-alive reap"
        self.idle_timeout = (float(idle_timeout)
                             if idle_timeout is not None else 0.0)
        # -- degradation under overload: beyond ``max_queue`` queued
        # requests (0 = unbounded) NEW work is shed with 429 +
        # Retry-After instead of queueing into a timeout — the client
        # gets an honest backpressure signal while replays/joins of
        # already-accepted work keep succeeding. ``clock`` feeds
        # per-request deadlines (X-Deadline-Ms): injectable so chaos
        # tests expire deadlines without wall-clock waits.
        self.max_queue = int(max_queue)
        self.shed_retry_after = float(shed_retry_after)
        self.clock = clock
        # -- tenant isolation (optional): ``tenancy`` is a
        # TenantRegistry / config dict / JSON path; when omitted the
        # MMLSPARK_TENANTS env var is consulted. With a registry, API
        # keys resolve to tenants at the edge, _admit charges token
        # buckets + in-flight caps per tenant, shedding becomes
        # priority-aware past the registry's high-water mark, and the
        # collector assembles batches in deficit-weighted round-robin
        # order per tenant (see serving/tenancy.py and docs/serving.md
        # "Tenancy & overload control"). All of it is host-side
        # bookkeeping BEFORE batch assembly — dispatch shapes, and
        # therefore the compiled-executable set, are tenant-blind.
        self.tenancy: Optional[TenantRegistry] = \
            TenantRegistry.from_value(tenancy, clock=clock)
        if self.tenancy is None and tenancy is None:
            self.tenancy = TenantRegistry.from_env(clock=clock)
        # collector-thread-only fair-share state (never touched by the
        # ingress threads — they only feed self._queue)
        self._fair_cycle = FairCycle()
        self._fair_q: Dict[str, "deque[_PendingRequest]"] = {}
        self._fair_total = 0
        self._m_tenant_latency = None
        self.n_shed = 0
        self.n_deadline_expired = 0
        # 5xx replies committed (model/encode failures): the per-worker
        # error signal the rollout canary comparison reads
        self.n_errors = 0
        self._draining = threading.Event()
        self._active_batches = 0
        # SimpleQueue, not Queue: the ingress handoff runs once PER
        # REQUEST from the frontend threads — the C-implemented
        # lock-free put/get is measurably cheaper than Queue's Python
        # lock + condvar at serving rates (the stage queues below keep
        # Queue for its maxsize backpressure)
        self._queue: "SimpleQueue[_PendingRequest]" = SimpleQueue()
        self._stop = threading.Event()
        # -- the socket edge: ``frontend="eventloop"`` (the default)
        # serves ingress from selectors-based non-blocking accept/read/
        # write loops — HTTP/1.1 keep-alive steady state, zero-copy
        # framing, vectored single-syscall replies, and optional
        # SO_REUSEPORT multi-acceptor loops (``acceptors``/
        # ``reuse_port``) — see serving/frontend.py and docs/serving.md
        # "The socket edge". ``frontend="threaded"`` keeps the
        # thread-per-connection http.server plane as the A/B baseline.
        # Both speak to the SAME staged data plane; only the edge
        # differs.
        self.frontend = str(frontend)
        if self.frontend == "eventloop":
            self._server = None
            self._frontend: Optional[EventLoopFrontend] = \
                EventLoopFrontend(
                    self, host, port,
                    acceptors=acceptors, reuse_port=reuse_port,
                    idle_timeout=self.idle_timeout,
                    request_timeout=self.request_timeout,
                    max_conns_per_ip=max_conns_per_ip,
                    max_pipelined_per_iter=max_pipelined_per_iter,
                    tls_cert=tls_cert, tls_key=tls_key,
                    ssl_context=ssl_context,
                    registry=self.registry, name="serving")
            self.host, self.port = (self._frontend.host,
                                    self._frontend.port)
        elif self.frontend == "threaded":
            if tls_cert or tls_key or ssl_context is not None:
                # TLS termination lives in the event-loop state machine
                # (non-blocking handshakes); the threaded A/B plane
                # stays plaintext rather than growing a second,
                # blocking TLS implementation that could drift
                raise ValueError(
                    "TLS requires frontend='eventloop' (the threaded "
                    "plane is the plaintext A/B baseline)")
            self._frontend = None
            self._server = _Server((host, port), self._handler_class())
            self.host, self.port = self._server.server_address[:2]
        else:
            raise ValueError(
                f"unknown frontend {frontend!r} "
                "(expected 'eventloop' or 'threaded')")
        self._threads: List[threading.Thread] = []
        self.n_requests = 0
        self.n_batches = 0
        # exactly-once reply semantics (parity: the continuous reader's
        # per-epoch offset commits, `HTTPSourceV2.scala:272,312`): a
        # client-supplied X-Request-Id keys a committed-reply journal, so
        # a retried/re-submitted request returns the SAME reply without
        # re-running inference; retries racing the original join its
        # in-flight entry instead of enqueuing a second compute.
        #
        # The journal is a bounded window, not an infinite log: entries
        # are evicted beyond ``journal_size`` commits (LRU) or after
        # ``journal_ttl`` seconds. A retry landing AFTER its entry was
        # evicted cannot be deduplicated — it re-executes. To make that
        # window *observable* rather than silent, evicted ids are kept in
        # a cheap id-only ring (16x journal_size); a rid seen there is a
        # detected past-window retry: it re-executes with a warning log,
        # an ``X-Replay-Window-Missed: 1`` response header, and the
        # ``n_window_missed`` counter (surfaced via ``GET /status``).
        self.journal_size = int(journal_size)
        # 0/negative means "no age-out", matching idle_timeout's idiom
        self.journal_ttl = (float(journal_ttl)
                            if journal_ttl is not None and journal_ttl > 0
                            else None)
        # rid -> (status, reply, committed_at_mono, trace_id)
        self._journal: "OrderedDict[str, Tuple[int, bytes, float, str]]" \
            = OrderedDict()
        self._evicted: "OrderedDict[str, None]" = OrderedDict()
        self._inflight: Dict[str, _PendingRequest] = {}
        self._commit_lock = threading.Lock()
        self.n_replayed = 0
        self.n_journal_evicted = 0
        self.n_window_missed = 0
        # -- durable journal (optional): the in-memory journal dies with
        # the process, so a pod crash-restart (exactly the k8s scenario)
        # would lose the replay window and a client retry spanning the
        # restart would re-execute. With ``journal_path`` (any io.fs
        # path — a PVC mount, gs://...), every commit appends one JSON
        # line and ServingServer REPLAYS the file on construction:
        # committed replies survive restarts, surfaced via
        # ``journal_recovered`` in ``GET /status``. Wall-clock
        # timestamps ride the file so the TTL window spans restarts.
        # Journal lines are written by a DEDICATED writer thread: the
        # commit path only enqueues the encoded line, so file append
        # latency (a real cost when journal_path is a remote io.fs
        # target like gs://, where every append is object I/O) never
        # lands on request tail latency or serializes commits (r4
        # advisor). Durability window: a reply can be released a few
        # microseconds before its line is flushed, so a crash in that
        # gap downgrades exactly-once to at-least-once for the affected
        # requests — the same contract as the reference's epoch commits.
        self.journal_path = journal_path
        self.n_journal_recovered = 0
        self._journal_fh = None
        self._journal_file_lines = 0   # appended since last compaction
        self._journal_queue: "Queue[bytes]" = Queue()
        if journal_path:
            self._recover_journal()
        # -- traffic capture (optional): an opt-in, bounded,
        # NON-BLOCKING journal of committed request/reply rows (plus
        # sampled shadow-diff rows) — the feedstock of the retrain
        # loop. The encoder stage offers each committed batch; a
        # dedicated writer thread does all file I/O, and a full queue
        # drops the batch (counted) rather than delay live traffic.
        # See serving/capture.py and docs/streaming.md.
        self.capture = capture
        # warmup() flips this around its synthetic batches so they are
        # never captured as traffic (warmup runs serially pre-start)
        self._in_warmup = False
        if capture is not None:
            capture.bind(self.registry)
        if self.decoder is not None:
            # bound last: bind reads the server's clock/tracer/registry
            # and commit path, all of which must exist first
            self.decoder.bind(self)
        # -- SLO engine (on by default): declarative burn-rate alerting
        # over this worker's OWN registry — ``slo`` is False (off), a
        # policy list / config dict (serving/slo.py), or None for the
        # stock worker policies (availability + dispatch latency, plus
        # TTFT/TPOT when the decode plane exists). Evaluation is pulled
        # by scrapes of ``GET /alerts`` / ``GET /slo`` and by the
        # firing-gauge exposition callback — nothing runs on the
        # request hot path. ``slo_webhook`` POSTs each firing/resolved
        # transition (own breaker board, never blocks evaluation).
        self.slo: Optional[SLOEngine] = None
        if slo is not False:
            self.slo = SLOEngine(
                self.registry,
                resolve_policies(slo,
                                 has_decoder=self.decoder is not None),
                clock=clock,
                notifier=(AlertNotifier(slo_webhook)
                          if slo_webhook else None))
        # -- retrospective plane (on by default): the embedded TSDB +
        # background Recorder (core/tsdb.py). ``tsdb`` is False (off),
        # None for stock tiers/rules/watches, or a config dict:
        # interval_s, tiers, max_series, snapshot_dir/keep/prefix,
        # budget_ms, rules (list of RecordingRule or dicts; None =
        # stock), watches (likewise), anomaly (False disables
        # detection). ONE scrape per tick feeds the TSDB, the optional
        # .prom dumper, and the SLO engine's snapshot history — a
        # server with a Recorder must not also run a MetricsSnapshot.
        # ``GET /query`` / ``GET /query_range`` serve the store;
        # anomaly transitions ride the SLO notifier and merge into
        # ``GET /alerts``.
        self.tsdb: Optional[TimeSeriesStore] = None
        self.recorder: Optional[Recorder] = None
        self.anomalies: Optional[AnomalyDetector] = None
        if tsdb is not False:
            cfg = dict(tsdb) if isinstance(tsdb, dict) else {}
            has_decoder = self.decoder is not None
            self.tsdb = TimeSeriesStore(
                tiers=cfg.get("tiers", DEFAULT_TIERS),
                max_series=cfg.get("max_series", 8192))
            rules = cfg.get("rules")
            rules = (default_serving_rules(
                         has_decoder=has_decoder,
                         has_tenancy=self.tenancy is not None)
                     if rules is None
                     else [RecordingRule.from_value(r) for r in rules])
            # incident bundles dump exactly these precomputed series
            self._tsdb_rules = rules
            if cfg.get("anomaly", True):
                watches = cfg.get("watches")
                watches = (default_serving_watches(
                               has_decoder=has_decoder)
                           if watches is None
                           else [AnomalyWatch.from_value(w)
                                 for w in watches])
                self.anomalies = AnomalyDetector(
                    self.tsdb, watches, clock=clock,
                    notifier=(self.slo.notifier
                              if self.slo is not None else None))
            self.recorder = Recorder(
                (self.registry, REGISTRY), store=self.tsdb,
                interval_s=cfg.get("interval_s", 10.0), clock=clock,
                snapshot_dir=cfg.get("snapshot_dir"),
                snapshot_keep=cfg.get("keep", 24),
                snapshot_prefix=cfg.get("prefix", "metrics"),
                slo=self.slo, rules=rules, detector=self.anomalies,
                ingest_budget_ms=cfg.get("budget_ms", 25.0))
        # -- device observability: one-at-a-time on-demand profiler
        # windows (POST /profile -> jax.profiler trace on disk), the
        # bounded compile-event ledger the dispatch stage feeds, and
        # the per-bucket MFU meter (flops via the model's
        # dispatch_flops/cost_analysis hook, when it has one)
        self.profiler = DeviceProfiler(base_dir=profile_dir)
        self.compile_ledger = CompileLedger()
        self.mfu = MfuMeter()
        self._flops_cache: Dict[tuple, Optional[float]] = {}
        # -- postmortem plane: always-on sampling CPU profiler +
        # anomaly-triggered incident capture. ``cpu_profiler`` is None
        # for the stock always-on sampler (50 hz, ~3 min retention),
        # False/{"hz": 0} to disable, or a config dict (hz,
        # retention_s, max_depth, max_stacks). ``GET /profile/cpu``
        # serves windows/diffs; the incident bundle reads the same
        # ring. ``incidents`` is None/False (off — nothing written
        # unless asked), a directory path, or a config dict (dir,
        # cooldown_s, max_incidents, profile_pre_s, profile_post_s,
        # lookback_s, series_step_s): when set, every SLO/anomaly
        # pending->firing transition snapshots an evidence bundle to
        # ``<dir>/<id>/`` — see serving/incident.py and
        # docs/observability.md "The postmortem plane".
        self.cpu_profiler: Optional[SamplingProfiler] = None
        if cpu_profiler is not False:
            pcfg = (dict(cpu_profiler) if isinstance(cpu_profiler, dict)
                    else {})
            if float(pcfg.get("hz", 50.0)) > 0:
                self.cpu_profiler = SamplingProfiler(
                    hz=pcfg.get("hz", 50.0),
                    retention_s=pcfg.get("retention_s", 180.0),
                    max_depth=pcfg.get("max_depth", 48),
                    max_stacks=pcfg.get("max_stacks", 8192),
                    clock=clock)
        # the process-wide log ring (core/logs.py): what GET /logs
        # serves and what the incident bundle snapshots
        self.log_ring = install_log_ring()
        self.incidents: Optional[IncidentManager] = None
        if incidents:
            icfg = ({"dir": incidents} if isinstance(incidents, str)
                    else dict(incidents))
            self.incidents = IncidentManager(
                icfg["dir"],
                tsdb=self.tsdb,
                tracer=self.tracer,
                profiler=self.cpu_profiler,
                log_ring=self.log_ring,
                stats_fn=self._incident_stats,
                related_exprs=[r.record for r in
                               getattr(self, "_tsdb_rules", [])],
                cooldown_s=icfg.get("cooldown_s", 300.0),
                max_incidents=icfg.get("max_incidents", 16),
                profile_pre_s=icfg.get("profile_pre_s", 60.0),
                profile_post_s=icfg.get("profile_post_s", 30.0),
                lookback_s=icfg.get("lookback_s", 600.0),
                series_step_s=icfg.get("series_step_s", 10.0),
                clock=clock)
            # fan alert transitions out to BOTH the webhook notifier
            # (when configured) and the incident manager — the SLO
            # engine and the anomaly detector keep their single
            # notifier slot, the fan-out sits behind it
            fan = FanoutNotifier(
                self.slo.notifier if self.slo is not None else None,
                self.incidents)
            if self.slo is not None:
                self.slo.notifier = fan
            if self.anomalies is not None:
                self.anomalies.notifier = fan
        self._register_metric_views()

    @property
    def model(self):
        """The ACTIVE model version's transformer. Kept as a property
        so the pre-rollout ``server.model`` surface still works; the
        dispatch stage itself snapshots the whole
        :class:`~mmlspark_tpu.serving.rollout.ModelVersion` per batch
        (model + version label together, so a mid-batch flip can't
        split them)."""
        return self.versions.active.model

    def _charge_tenant_device(self, pendings, total_ms: float) -> None:
        """Pro-rate one batch's dispatch wall-clock across the tenants
        whose rows rode it (equal share per row — rows are what the
        batch is made of). Tenant ids resolve to their bounded metric
        labels via the tenant registry; unattributed traffic charges
        to the anonymous tenant. One counter inc per distinct tenant
        per batch — micro-cost on the dispatch (not request) path."""
        if total_ms <= 0 or not pendings:
            return
        counts: Dict[Optional[str], int] = {}
        for p in pendings:
            tid = getattr(p, "tenant", None)
            counts[tid] = counts.get(tid, 0) + 1
        share = total_ms / len(pendings)
        for tid, n in counts.items():
            if tid is None:
                label = ANONYMOUS_ID
            elif self.tenancy is not None:
                label = self.tenancy.label_of(tid)
            else:
                label = str(tid)
            self._m_tenant_device.labels(label).inc(share * n)

    def _register_metric_views(self) -> None:
        """Expose the server's existing counters/state as registry
        families via exposition-time callbacks: ``GET /metrics`` reads
        them live, the hot paths keep their plain-int increments (int
        reads are tear-free under the GIL)."""
        m = self.registry
        for name, help_, fn in (
            ("serving_requests_total",
             "Requests that entered a batch (includes synthetic warmup "
             "rows).", lambda: self.n_requests),
            ("serving_batches_total",
             "Micro-batches processed.", lambda: self.n_batches),
            ("serving_shed_total",
             "New requests refused with 429 under overload.",
             lambda: self.n_shed),
            ("serving_deadline_missed_total",
             "Requests 504ed because their X-Deadline-Ms budget expired "
             "(at ingress, before dispatch, or before commit).",
             lambda: self.n_deadline_expired),
            ("serving_recompiles_total",
             "Distinct dispatch shapes seen (each forces a jit retrace "
             "in any jitted model).", lambda: self.n_recompiles),
            ("serving_replayed_total",
             "Requests answered from the exactly-once reply journal.",
             lambda: self.n_replayed),
            ("serving_journal_evicted_total",
             "Journal entries evicted past the replay window.",
             lambda: self.n_journal_evicted),
            ("serving_window_missed_total",
             "Retries that arrived after their journal entry was "
             "evicted (re-executed).", lambda: self.n_window_missed),
            ("serving_errors_total",
             "Requests answered 5xx (model/encode failures) — the "
             "per-worker error signal rollout canarying compares.",
             lambda: self.n_errors),
        ):
            m.counter(name, help_).set_function(fn)
        m.gauge("serving_backlog",
                "Requests accepted but not yet dispatched into the "
                "model (the shedding signal).").set_function(self.backlog)
        m.gauge("serving_inflight_batches",
                "Batches between collection and commit."
                ).set_function(lambda: self._active_batches)
        m.gauge("serving_journal_entries",
                "Live replay-journal entries."
                ).set_function(lambda: len(self._journal))
        # build identity: a constant-1 gauge whose labels ARE the value
        # (version/jax/jaxlib/device_kind/frontend) — joinable against
        # every other serving metric, echoed in /stats as "build"
        self.build = register_build_info(self.registry,
                                         frontend=self.frontend)
        # HBM accounting from the runtime allocator (0s on CPU backends
        # — the families still exist so dashboards don't 404)
        for name, help_, key in (
            ("serving_hbm_bytes_in_use",
             "Device HBM bytes currently allocated (device 0).",
             "bytes_in_use"),
            ("serving_hbm_peak_bytes",
             "Device HBM high-water mark since process start.",
             "peak_bytes"),
            ("serving_hbm_bytes_limit",
             "Device HBM allocator limit.", "bytes_limit"),
        ):
            m.gauge(name, help_).set_function(
                lambda k=key: device_memory_stats().get(k, 0))
        if self.slo is not None:
            self.slo.register_metrics(m)
        if self.tenancy is not None:
            self._register_tenant_metric_views()
        # process vitals belong to the PROCESS-wide registry: two
        # co-hosted workers read the same RSS, and the fleet merge
        # (which scrapes ?scope=server) must not sum it once per worker
        REGISTRY.gauge(
            "process_uptime_seconds",
            "Seconds since process start (resets on restart)."
        ).set_function(process_uptime_s)
        REGISTRY.gauge(
            "process_rss_bytes",
            "Resident set size (leak evidence across chaos drills)."
        ).set_function(lambda: process_rss_bytes() or 0)

    def _register_tenant_metric_views(self) -> None:
        """Per-tenant metric families, exposition-time views over the
        registry's plain counters. Cardinality is bounded by the
        registry's BoundedLabelSet: the first ``label_cap`` tenants
        (declaration order) get their own label value, the tail folds
        into ``other`` — a child's view function sums every state
        mapped to its label, so ``other`` is one honest aggregate row,
        not last-writer-wins."""
        m, reg = self.registry, self.tenancy
        c_req = m.counter(
            "serving_tenant_requests_total",
            "Requests admitted per tenant (replays and sheds are "
            "counted separately).", labels=("tenant",))
        c_shed = m.counter(
            "serving_tenant_shed_total",
            "Requests refused per tenant, by reason: rate (token "
            "bucket empty), concurrency (in-flight cap), overload "
            "(priority-aware queue-pressure shed).",
            labels=("tenant", "reason"))
        c_tok = m.counter(
            "serving_tenant_tokens_total",
            "Decode-plane tokens generated per tenant.",
            labels=("tenant",))
        c_good = m.counter(
            "serving_tenant_goodput_tokens_total",
            "Decode-plane tokens from requests that resolved cleanly "
            "(eos/length) per tenant — the numerator of per-tenant "
            "goodput.", labels=("tenant",))
        g_inf = m.gauge(
            "serving_tenant_inflight",
            "Requests currently holding a tenant in-flight slot.",
            labels=("tenant",))
        self._m_tenant_latency = m.histogram(
            "serving_tenant_request_latency_ms",
            "Enqueue->commit wall-clock per tenant (the per-tenant "
            "dispatch-latency surface; admission-rejected requests "
            "never reach it).", labels=("tenant",))
        for label in reg.labels():
            states = reg.states_for_label(label)
            c_req.labels(label).set_function(
                lambda ss=states: sum(s.n_requests for s in ss))
            c_tok.labels(label).set_function(
                lambda ss=states: sum(s.n_tokens for s in ss))
            c_good.labels(label).set_function(
                lambda ss=states:
                sum(s.n_goodput_tokens for s in ss))
            g_inf.labels(label).set_function(
                lambda ss=states: sum(s.inflight for s in ss))
            for reason, attr in (("rate", "n_shed_rate"),
                                 ("concurrency", "n_shed_concurrency"),
                                 ("overload", "n_shed_overload")):
                c_shed.labels(label, reason).set_function(
                    lambda ss=states, a=attr:
                    sum(getattr(s, a) for s in ss))

    # -- HTTP side -----------------------------------------------------------

    def _handler_class(self):
        serving = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: keep-alive sockets (every reply carries an
            # explicit Content-Length) — per-request TCP connects would
            # dominate the latency the server exists to minimize.
            # Nagle must go with it: status/headers/body are separate
            # writes, and Nagle + delayed ACK turns each keep-alive
            # response into a 40 ms stall. The idle timeout reaps
            # keep-alive connections so parked clients can't pin
            # handler threads forever.
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True
            # 0/negative means "no reap"; a literal 0 would set a
            # NON-BLOCKING socket and kill every connection instantly
            timeout = (serving.idle_timeout
                       if serving.idle_timeout > 0 else None)

            # the Date header is formatted per reply by the stdlib
            # (strftime + tuple math); at thousands of replies/sec that
            # is real CPU for a value that changes once a second
            _date_cache = [0.0, ""]

            def date_time_string(self, timestamp=None):
                if timestamp is not None:
                    return super().date_time_string(timestamp)
                cache = type(self)._date_cache
                now = time.time()
                if now - cache[0] >= 1.0:
                    # value BEFORE timestamp: a concurrent reader that
                    # sees the fresh timestamp must never read the old
                    # (or startup-empty) string
                    cache[1] = super().date_time_string(now)
                    cache[0] = now
                return cache[1]

            def _reply(self, status: int, body: bytes, replayed=False,
                       window_missed=False, retry_after=None,
                       trace=None, ctype="application/json", extra=()):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                if trace:
                    # echo the trace id so a client that did not supply
                    # one can still correlate its reply with worker logs
                    self.send_header(TRACE_HEADER, trace)
                if replayed:
                    self.send_header("X-Replayed", "1")
                if window_missed:
                    self.send_header("X-Replay-Window-Missed", "1")
                if retry_after is not None:
                    self.send_header("Retry-After", str(retry_after))
                for k, v in extra:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                # one write for status+headers+body: Nagle is disabled,
                # so the stdlib's separate end_headers()/body writes
                # would leave as separate packets. HTTP/0.9 requests
                # (e.g. `nc`-style probes) never get a headers buffer —
                # fall back to the stdlib path for them
                buf = getattr(self, "_headers_buffer", None)
                if buf:
                    buf.append(b"\r\n")
                    self.wfile.write(b"".join(buf) + body)
                    self._headers_buffer = []
                else:
                    self.end_headers()
                    self.wfile.write(body)

            def do_GET(self):
                # one route table for both frontends: the threaded
                # handler and the event-loop frontend's handle_request
                # serve the SAME _get_route result — observability
                # endpoints cannot drift between the A/B planes
                route = serving._get_route(self.path, self.headers)
                if route is None:
                    self.send_error(404)
                    return
                status, body, ctype, extra = route
                self._reply(status, body, ctype=ctype, extra=extra)

            def do_POST(self):
                # the decode path matches on the BASE path so the
                # streaming opt-in query (?stream=1) still routes here
                is_decode = (serving.decoder is not None
                             and self.path.partition("?")[0]
                             == serving.decode_path)
                if self.path != serving.api_path and not is_decode:
                    # control-plane POSTs (rollout admin) share one
                    # route table with the event-loop frontend
                    length = int(self.headers.get("Content-Length", 0))
                    routed = serving._post_route(
                        self.path, self.rfile.read(length))
                    if routed is None:
                        self.send_error(404)
                        return
                    status, rbody, ctype = routed
                    self._reply(status, rbody, ctype=ctype)
                    return
                # trace ingress: adopt the inbound X-Trace-Id or mint
                # one; bound for this handler thread's logs, carried on
                # the pending request for the stage threads, echoed on
                # every reply. The request's ROOT span opens here and
                # closes when the reply is written — finishing it runs
                # the tail-capture decision (slow or non-ok traces are
                # retained for GET /trace/<id>). An inbound
                # X-Parent-Span-Id (strictly validated; malformed
                # values are dropped, never sanitized into a wrong
                # link) parents this root under the CALLER's egress
                # span, so the worker-side tree stitches into the
                # caller's distributed trace at GET /fleet/trace/<id>.
                tid, parent_sid = extract_span_context(self.headers)
                with trace_context(tid):
                    root = serving.tracer.start(
                        "request", trace_id=tid,
                        remote_parent=parent_sid,
                        route=(serving.decode_path if is_decode
                               else serving.api_path))
                    if capture_hint(self.headers):
                        # the X-Capture wire hint: retain this trace
                        # end to end, thresholds notwithstanding
                        root.force = True
                    status = "error"
                    try:
                        status = self._do_predict(tid, root,
                                                  decode=is_decode)
                    finally:
                        serving.tracer.finish(root, status=status)

            def _do_predict(self, tid, root, decode=False):
                """Serve one POST; returns the root span's terminal
                status (``ok``/``shed``/``deadline``/``timeout``/
                ``error`` — everything but ``ok`` is tail-captured)."""
                if serving._draining.is_set():
                    # graceful drain: accepted work finishes, new work
                    # is refused so the orchestrator's retry lands on a
                    # live worker
                    self._reply(503, b'{"error": "draining"}',
                                retry_after=serving.shed_retry_after,
                                trace=tid)
                    return "shed"
                length = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    # _reply (not send_error): even a rejected request
                    # must echo its trace id, or the client cannot
                    # correlate the failure with worker logs
                    self._reply(400, b'{"error": "invalid JSON"}',
                                trace=tid)
                    return "error"

                deadline = Deadline.from_headers(self.headers,
                                                 clock=serving.clock)
                rid = self.headers.get("X-Request-Id")
                tenant = serving._resolve_tenant(self.headers)
                if tenant is serving._TENANT_REJECTED:
                    self._reply(401, serving._UNKNOWN_KEY_BODY,
                                trace=tid)
                    return "error"
                if tenant is not None:
                    root.set_attr("tenant", tenant.id)
                kind, pending, committed, window_missed, shed = \
                    serving._admit(payload, rid, deadline, tid,
                                   decode=decode, tenant=tenant)
                if rid:
                    root.set_attr("rid", rid)
                if kind == "replay":
                    root.set_attr("replayed", True)
                    self._reply(committed[0], committed[1],
                                replayed=True, trace=tid)
                    return "ok"
                if kind == "shed":
                    self._reply(429, shed["body"],
                                retry_after=shed["retry_after"],
                                trace=tid)
                    return "shed"
                if kind == "doa":
                    self._reply(504, pending.reply, trace=tid)
                    return "deadline"
                if kind == "enqueue":
                    if decode:
                        stream = (_ThreadedStream()
                                  if _stream_requested(self.path,
                                                       payload)
                                  else None)
                        pending.stream = stream
                        err = serving._enqueue_decode(pending, root)
                        if err is not None:
                            pending.stream = None
                            e_status, e_body = err
                            self._reply(
                                e_status, e_body, trace=tid,
                                retry_after=(
                                    serving._decode_retry_after()
                                    if e_status == 429 else None))
                            return ("shed" if e_status == 429
                                    else "error")
                        if stream is not None:
                            return self._serve_stream(tid, pending,
                                                      stream)
                    else:
                        serving._enqueue(pending, root)
                if not pending.event.wait(serving.request_timeout):
                    # the stuck-batch timeout is the reply operators
                    # most need to trace: echo the id here too
                    self._reply(504, b'{"error": "inference timed out"}',
                                trace=tid)
                    return "timeout"
                # a joined duplicate is only "replayed" if the reply was
                # actually committed — errors are never journaled, so
                # they must not carry the committed-replay marker
                self._reply(pending.status, pending.reply or b"{}",
                            replayed=(kind == "join"
                                      and pending.status == 200),
                            window_missed=window_missed, trace=tid)
                return ("ok" if pending.status == 200 else
                        "deadline" if pending.status == 504 else "error")

            def _serve_stream(self, tid, pending, stream) -> str:
                """Drain the decode scheduler's token events into
                chunked SSE writes from this handler thread — the
                threaded analogue of the event-loop stream. The stream
                was attached BEFORE submit, so no token can slip out
                unstreamed; a write failure (client gone) flips
                ``closed`` and the scheduler cancels the decode."""
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Transfer-Encoding", "chunked")
                self.send_header("Cache-Control", "no-cache")
                self.send_header(TRACE_HEADER, tid)
                self.end_headers()
                while True:
                    try:
                        data, end = stream.q.get(
                            timeout=serving.request_timeout)
                    except Empty:
                        # no event within the stuck-batch budget: give
                        # up exactly like the non-streamed 504 path
                        stream.closed = True
                        self.close_connection = True
                        return "timeout"
                    try:
                        if data:
                            self.wfile.write(b"%x\r\n" % len(data)
                                             + data + b"\r\n")
                            if stream.t_first == 0.0:
                                stream.t_first = time.monotonic()
                        if end:
                            self.wfile.write(b"0\r\n\r\n")
                            break
                        self.wfile.flush()
                    except OSError:
                        stream.closed = True
                        self.close_connection = True
                        return "error"
                return ("ok" if pending.status == 200 else
                        "deadline" if pending.status == 504 else
                        "error")

            def log_message(self, *args):  # quiet
                pass

        return Handler

    # -- shared ingress (both frontends) -------------------------------------

    def _get_route(self, path: str, headers
                   ) -> Optional[Tuple[int, bytes, str, tuple]]:
        """The GET route table: ``(status, body, content_type, extra
        headers)`` or None for 404. The threaded handler and the
        event-loop frontend both serve exactly this, so the
        observability surface cannot drift between the A/B planes."""
        if path == "/healthz":
            # liveness: the process answers HTTP at all
            return 200, b'{"ok": true}', "application/json", ()
        if path == "/readyz":
            # readiness: flips 503 the moment drain starts, so an
            # orchestrator stops routing BEFORE the listener goes away
            # (the k8s readiness-probe contract)
            if self._draining.is_set() or self._stop.is_set():
                return (503, b'{"ready": false, "reason": "draining"}',
                        "application/json", ())
            body = {"ready": True,
                    "queue_depth": self.backlog(),
                    "max_queue": self.max_queue}
            return 200, json.dumps(body).encode(), "application/json", ()
        base = path.split("?", 1)[0]
        if base == "/metrics":
            # Prometheus text exposition: the per-server registry
            # (stage/dispatch histograms + counter views) plus the
            # process-wide one (trainer, HTTP egress, breakers, Timer
            # stages). ``?scope=server`` limits to the per-server
            # registry — the fleet merge scrapes that, so co-hosted
            # workers sharing one process REGISTRY never double-count
            # its families in the sum. Exemplars ride ONLY the
            # OpenMetrics exposition (Accept-negotiated, or forced via
            # ?exemplars=1): the classic 0.0.4 grammar has no exemplar
            # production and a strict scraper would fail the whole
            # scrape on the trailer
            server_only = "scope=server" in path
            regs = (self.registry,) if server_only \
                else (self.registry, REGISTRY)
            accept = headers.get("Accept", "") if headers is not None \
                else ""
            openmetrics = ("application/openmetrics-text"
                           in (accept or "")
                           or "exemplars=1" in path)
            body = render_registries(*regs, exemplars=openmetrics)
            if openmetrics:
                body += "# EOF\n"
            return (200, body.encode(),
                    _OPENMETRICS_CONTENT_TYPE if openmetrics
                    else _METRICS_CONTENT_TYPE, ())
        if path == "/stats":
            # data-plane observability: per-stage timings, the bucket
            # set actually dispatched, and the recompile counter (a
            # dispatch shape seen for the first time forces a
            # trace/compile in any jitted model) — the evidence that
            # the bucketed pipeline holds a fixed compiled-shape set
            # after warm-up
            with self._stats_lock:
                stats = {
                    "pipeline": self.pipeline,
                    "bucket_batches": self.bucket_batches,
                    "encoder_threads": self.encoder_threads,
                    "n_batches": self.n_batches,
                    "n_requests": self.n_requests,
                    "n_recompiles": self.n_recompiles,
                    "dispatch_sizes": sorted(
                        {k[0] for k in self._shapes_seen}),
                    "inflight_batches": self._active_batches,
                    "queue_depth": self._n_backlog,
                    "stage_timings": self.timings.snapshot(),
                    # the active model version (full lifecycle detail
                    # at GET /version): the fleet view aggregates this
                    # into its coherent-version-set check
                    "model_version": self.versions.active.version,
                    # the active version's quantized-wire config (None
                    # = the f32 plane): wire dtype + dequant constants
                    # — what serving_wire_bytes_total{dtype} is
                    # evidence OF
                    "quantization": (
                        self.versions.active.quantization.to_dict()
                        if self.versions.active.quantization is not None
                        else None),
                    # per-device placement of the active model (tensor-
                    # parallel dispatch mode): mesh axes, device list,
                    # sharded/replicated leaf split, bytes per device —
                    # None for models that don't report placement
                    "placement": self._model_placement(),
                    # pipeline-parallel dispatch (when the active
                    # model stages itself over mesh slices): stages,
                    # per-stage placement + probe-measured service
                    # times, bubble ratio, in-flight micro-batches.
                    # None = not pipelined. (The "pipeline" key above
                    # is the serving DATA plane's staged-thread flag —
                    # an older, unrelated surface.)
                    "pipeline_parallel": self._model_pipeline(),
                    # the LIVE tail-capture threshold (adaptive
                    # refreshes move it; fixed config pins it)
                    "slow_trace_ms":
                        self.tracer.threshold(self.api_path),
                    "adaptive_slow_trace": self.adaptive is not None,
                    # the dispatch-wait policy: "fixed" = the constant
                    # max_latency_ms knob; "adaptive" learns the wait
                    # per batch (rate + per-bucket latency — A/B
                    # selectable, docs/serving.md "Adaptive batching")
                    "batch_policy": self.batch_policy,
                    "adaptive_batch": (self.adaptive_batcher.status()
                                       if self.adaptive_batcher
                                       is not None else None),
                    # the socket edge: keep-alive reuse rate, open
                    # connections, accept-loop saturation (eventloop);
                    # the threaded plane reports only its kind
                    "frontend": (self._frontend.stats()
                                 if self._frontend is not None
                                 else {"kind": "threaded"}),
                    # traffic capture (when opted in): journal rows,
                    # drop counts, live segment inventory
                    "capture": (self.capture.status()
                                if self.capture is not None else None),
                    # process vitals: chaos drills diff these across
                    # kill/restart cycles — uptime proves the restart,
                    # RSS spots the leak
                    "uptime_s": round(process_uptime_s(), 3),
                    "rss_bytes": process_rss_bytes(),
                    # per-tenant admission ledger: quotas, in-flight,
                    # shed counts by reason, tokens — None when the
                    # server runs without a tenant registry
                    "tenancy": (self.tenancy.stats()
                                if self.tenancy is not None else None),
                    # build identity (echoes serving_build_info's
                    # labels): version, jax/jaxlib, device kind,
                    # frontend — what a fleet diff pins a worker to
                    "build": self.build,
                    # SLO engine surface WITHOUT forcing an evaluation
                    # (GET /slo runs one); None when disabled
                    "slo": (self.slo.status()
                            if self.slo is not None else None),
                    # the retrospective plane: recorder cadence/budget,
                    # store size per tier, anomaly detector state; None
                    # when the TSDB is disabled
                    "tsdb": (self.recorder.status()
                             if self.recorder is not None else None),
                    # device observability: profiler window state, the
                    # bounded compile-event ledger, per-bucket MFU,
                    # and HBM live/peak/limit bytes
                    "profiling": {
                        "profiler": self.profiler.status(),
                        "compile_events": self.compile_ledger.snapshot(),
                        "mfu": self.mfu.snapshot(),
                        "hbm": device_memory_stats(),
                    },
                    # the postmortem plane: sampling-profiler ring
                    # health, incident-capture counters, log-ring
                    # fill — docs/observability.md "The postmortem
                    # plane"
                    "postmortem": {
                        "cpu_profiler": (self.cpu_profiler.status()
                                         if self.cpu_profiler
                                         is not None else None),
                        "incidents": (self.incidents.status()
                                      if self.incidents is not None
                                      else None),
                        "log_ring": self.log_ring.status(),
                    },
                }
            return 200, json.dumps(stats).encode(), "application/json", ()
        if base == "/traces":
            # the tail-capture store: every retained trace was slow or
            # ended non-ok; ?slow=1 keeps only the threshold-retained
            # ones. Slowest first (root duration descending), so the
            # capture an operator wants tops the list without fetching
            # every tree
            items = self.tracer.traces(slow_only="slow=1" in path)
            items.sort(key=lambda t: -t["duration_ms"])
            return 200, json.dumps(items).encode(), "application/json", ()
        if path.startswith("/trace/"):
            tid, _, query = path[len("/trace/"):].partition("?")
            tr = self.tracer.get_trace(tid)
            if tr is None:
                return (404, json.dumps(
                    {"error": "trace not retained (fast + ok traces "
                              "are tail-dropped)",
                     "trace_id": tid}).encode(), "application/json", ())
            if "format=raw" in query:
                # the stored capture verbatim (flat span list +
                # origin_unix anchor): what the coordinator's
                # distributed merge consumes
                body = json.dumps(tr).encode()
            elif "format=perfetto" in query:
                # Chrome trace_event JSON: load the body in
                # chrome://tracing or ui.perfetto.dev (see
                # tools/trace_dump.py)
                body = json.dumps(to_perfetto(tr)).encode()
            else:
                out = {k: tr[k] for k in
                       ("trace_id", "root", "route", "duration_ms",
                        "status", "reason", "captured_at", "n_spans")}
                out["tree"] = span_tree(tr)
                body = json.dumps(out).encode()
            return 200, body, "application/json", ()
        if path == "/version":
            # the rollout state machine: active/staged/previous version
            # lifecycle, shadow-traffic stats, flip/rollback counters
            return (200, json.dumps(self.versions.status()).encode(),
                    "application/json", ())
        if path == "/decode/stats":
            # the continuous-batching plane: slot occupancy, waiting
            # depth, step/token counters, compile count (flat after
            # warmup = zero retraces), and per-slot in-flight progress
            # (the incremental token emission, observable mid-decode)
            if self.decoder is None:
                return (404, b'{"error": "no decode plane configured"}',
                        "application/json", ())
            return (200, json.dumps(self.decoder.stats()).encode(),
                    "application/json", ())
        if path == "/alerts":
            # the SLO engine's compact alert view (state machine +
            # violating window pairs); the GET itself drives an
            # evaluation pass — pull-based, nothing on the hot path.
            # Anomaly-watch states ride along under "anomalies" (their
            # firing count adds into "firing"), so one endpoint answers
            # "is anything wrong" for both alert sources.
            if self.slo is None:
                return (404, b'{"error": "slo engine disabled"}',
                        "application/json", ())
            self.slo.evaluate()
            body = self.slo.alerts()
            if self.anomalies is not None:
                an = self.anomalies.alerts()
                body["anomalies"] = an["alerts"]
                body["firing"] = body.get("firing", 0) + an["firing"]
            return (200, json.dumps(body).encode(),
                    "application/json", ())
        if path == "/slo":
            # the full burn-rate report: every policy's long/short
            # window burns, measured quantiles, and attribution
            if self.slo is None:
                return (404, b'{"error": "slo engine disabled"}',
                        "application/json", ())
            return (200, json.dumps(self.slo.evaluate()).encode(),
                    "application/json", ())
        if base in ("/query", "/query_range"):
            # the retrospective plane's query surface (core/tsdb.py):
            # ?expr=<selector | rate(sel[w]) | increase(sel[w]) |
            # quantile(q, hist[w])> — /query takes ?at=, /query_range
            # takes ?start=&end=&step= (timestamps on the worker's
            # monotonic clock, defaulting to the newest recorded data).
            # Malformed expressions are a 400, never a 500.
            if self.tsdb is None:
                return (404, b'{"error": "tsdb disabled"}',
                        "application/json", ())
            params = _urlparse.parse_qs(
                path.partition("?")[2], keep_blank_values=True)
            expr = (params.get("expr") or [""])[0]
            try:
                if base == "/query":
                    at = params.get("at")
                    body = self.tsdb.query(
                        expr, at=float(at[0]) if at else None)
                else:
                    start = params.get("start")
                    end = params.get("end")
                    step = (params.get("step") or ["10"])[0]
                    body = self.tsdb.query_range(
                        expr,
                        start=float(start[0]) if start else None,
                        end=float(end[0]) if end else None,
                        step=float(step))
            except (QueryError, ValueError) as e:
                return (400, json.dumps({"error": str(e),
                                         "expr": expr}).encode(),
                        "application/json", ())
            return (200, json.dumps(body).encode(),
                    "application/json", ())
        if base == "/profile/cpu":
            # the always-on sampling profiler (core/profiler.py):
            # ?window_s=N aggregates the last N seconds (JSON
            # top-table by default; &format=collapsed for folded
            # flamegraph text, &format=trace for Chrome trace_event
            # JSON); &baseline_s=M returns the differential profile —
            # the last window_s vs the baseline_s before it, frames
            # ranked by how much hotter they got
            if self.cpu_profiler is None:
                return (404, b'{"error": "cpu profiler disabled"}',
                        "application/json", ())
            params = _urlparse.parse_qs(
                path.partition("?")[2], keep_blank_values=True)
            try:
                window_s = float((params.get("window_s") or ["30"])[0])
                baseline = params.get("baseline_s")
                fmt = (params.get("format") or ["json"])[0]
                if baseline:
                    body = self.cpu_profiler.diff(
                        window_s, float(baseline[0]))
                elif fmt == "collapsed":
                    text = self.cpu_profiler.render_collapsed(window_s)
                    return (200, text.encode(),
                            "text/plain; charset=utf-8", ())
                elif fmt == "trace":
                    body = self.cpu_profiler.chrome_trace(window_s)
                else:
                    body = self.cpu_profiler.profile(window_s)
            except ValueError as e:
                return (400, json.dumps({"error": str(e)}).encode(),
                        "application/json", ())
            return (200, json.dumps(body).encode(),
                    "application/json", ())
        if base == "/logs":
            # the bounded in-memory log ring (core/logs.py):
            # ?trace=<id> filters to one request's records (the
            # injected trace field), ?level=<name> floors severity,
            # ?n= keeps the newest N. Same ring the incident bundle
            # snapshots.
            params = _urlparse.parse_qs(
                path.partition("?")[2], keep_blank_values=True)
            trace = (params.get("trace") or [None])[0]
            level = (params.get("level") or [None])[0]
            n = (params.get("n") or [None])[0]
            try:
                limit = int(n) if n else None
            except ValueError:
                return (400, b'{"error": "n must be an integer"}',
                        "application/json", ())
            body = {"status": self.log_ring.status(),
                    "records": self.log_ring.records(
                        trace=trace, level=level, limit=limit)}
            return (200, json.dumps(body).encode(),
                    "application/json", ())
        if base == "/incidents" or base.startswith("/incidents/"):
            # the postmortem bundles (serving/incident.py): list,
            # per-bundle manifest + inventory, and raw artifacts
            # (/incidents/<id>/<file>, whitelisted names only)
            if self.incidents is None:
                return (404, b'{"error": "incident capture disabled '
                        b'(configure incidents=<dir>)"}',
                        "application/json", ())
            if base == "/incidents":
                body = {"incidents": self.incidents.list(),
                        "status": self.incidents.status()}
                return (200, json.dumps(body).encode(),
                        "application/json", ())
            rest = base[len("/incidents/"):]
            inc_id, _, artifact = rest.partition("/")
            if artifact:
                art = self.incidents.artifact(inc_id, artifact)
                if art is None:
                    return (404, json.dumps(
                        {"error": "no such incident artifact",
                         "id": inc_id,
                         "artifact": artifact}).encode(),
                        "application/json", ())
                return 200, art["body"], art["content_type"], ()
            info = self.incidents.get(inc_id)
            if info is None:
                return (404, json.dumps(
                    {"error": "no such incident",
                     "id": inc_id}).encode(), "application/json", ())
            return (200, json.dumps(info).encode(),
                    "application/json", ())
        if path == "/profile":
            # profiler status (busy flag, last capture window); the
            # capture itself is POST /profile
            return (200, json.dumps(self.profiler.status()).encode(),
                    "application/json", ())
        if path != "/status":
            return None
        with self._commit_lock:
            status = {
                "n_requests": self.n_requests,
                "n_batches": self.n_batches,
                "n_errors": self.n_errors,
                "model_version": self.versions.active.version,
                "n_replayed": self.n_replayed,
                "n_journal_evicted": self.n_journal_evicted,
                "n_window_missed": self.n_window_missed,
                "n_shed": self.n_shed,
                "n_deadline_expired": self.n_deadline_expired,
                "queue_depth": self.backlog(),
                "max_queue": self.max_queue,
                "draining": self._draining.is_set(),
                "journal_entries": len(self._journal),
                "journal_size": self.journal_size,
                "journal_ttl": self.journal_ttl,
                "journal_path": self.journal_path,
                "journal_recovered": self.n_journal_recovered,
            }
        return 200, json.dumps(status).encode(), "application/json", ()

    def _incident_stats(self) -> dict:
        """The worker-state snapshot an incident bundle embeds:
        ``/stats`` + ``/decode/stats`` + placement, captured through
        the same route table the frontends serve (one codepath, no
        drift). Runs on the capture thread — never the hot path."""
        out: Dict[str, Any] = {}
        for key, route in (("stats", "/stats"),
                           ("decode_stats", "/decode/stats"),
                           ("status", "/status")):
            try:
                r = self._get_route(route, None)
                if r is not None and r[0] == 200:
                    out[key] = json.loads(r[1])
            except Exception as exc:  # noqa: BLE001 — capture survives
                out[key] = {"error": str(exc)}
        out["placement"] = self._model_placement()
        return out

    def _model_placement(self) -> Optional[dict]:
        """The active model's device placement, when it reports one
        (NNModel.placement / TransformerDecoder.placement) — scrapes
        must never fail on a model without the surface."""
        fn = getattr(self.versions.active.model, "placement", None)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:  # noqa: BLE001 — stats never 500 on a model
            return None

    def _model_pipeline(self) -> Optional[dict]:
        """The active model's pipeline-parallel report (stage
        placement, bubble ratio, in-flight micro-batches) when it has
        one — the ``/stats`` "pipeline_parallel" block."""
        fn = getattr(self.versions.active.model, "pipeline_report", None)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:  # noqa: BLE001 — stats never 500 on a model
            return None

    def _post_route(self, path: str, body: bytes
                    ) -> Optional[Tuple[int, bytes, str]]:
        """The worker's control-plane POST routes (rollout admin),
        shared by both frontends exactly like ``_get_route`` — only
        ``api_path`` itself takes the data-plane admission path.
        Returns ``(status, body, content_type)`` or None for 404."""
        if path == "/profile":
            # on-demand device profiling: open ONE jax.profiler trace
            # window (duration_ms, clamped) on a background thread and
            # 202 immediately with the on-disk log_dir; a second POST
            # while a window runs gets an honest 409, a runtime that
            # cannot profile (no backend support) a 503
            try:
                args = json.loads(body or b"{}")
                if not isinstance(args, dict):
                    raise ValueError("body must be a JSON object")
            except ValueError as e:
                return (400, json.dumps({"error": f"invalid JSON: {e}"}
                                        ).encode(), "application/json")
            duration_ms = args.get("duration_ms", 1000)
            try:
                duration_ms = min(max(float(duration_ms), 50.0),
                                  30000.0)
            except (TypeError, ValueError):
                return (400, b'{"error": "duration_ms must be a '
                             b'number"}', "application/json")
            try:
                info = self.profiler.start_window(
                    duration_s=duration_ms / 1000.0,
                    log_dir=args.get("log_dir"))
            except ProfilerBusy as e:
                return (409, json.dumps(
                    {"error": str(e),
                     "status": self.profiler.status()}).encode(),
                    "application/json")
            except Exception as e:  # noqa: BLE001 — backend can't
                return (503, json.dumps(
                    {"error": f"profiler unavailable: {e}"}).encode(),
                    "application/json")
            return 202, json.dumps(info).encode(), "application/json"
        if not path.startswith("/rollout/"):
            return None
        try:
            args = json.loads(body or b"{}")
            if not isinstance(args, dict):
                raise ValueError("body must be a JSON object")
        except ValueError as e:
            return (400, json.dumps({"error": f"invalid JSON: {e}"}
                                    ).encode(), "application/json")
        try:
            if path == "/rollout/stage":
                if not args.get("path"):
                    return (400, b'{"error": "stage needs a checkpoint '
                                 b'path"}', "application/json")
                if args.get("sync"):
                    # sync staging is Python-API-only: this handler
                    # runs ON the event-loop thread, and inline
                    # digest-hashing + every-bucket warmup of a big
                    # checkpoint would stall every connection on the
                    # loop — the rollout endpoint causing downtime
                    return (400, b'{"error": "staging is asynchronous '
                                 b'over HTTP; poll GET /version until '
                                 b'the staged state settles"}',
                            "application/json")
                try:
                    out = self.versions.stage(
                        source=args["path"],
                        version=args.get("version"),
                        warmup_payload=args.get("warmup_payload"),
                        shadow_fraction=args.get("shadow_fraction"),
                        quantization=args.get("quantization"))
                except ValueError as e:
                    # a malformed quantization config (zero scale,
                    # non-finite zero-point, unknown wire dtype) is a
                    # client error caught at the door — never a staged
                    # version that dispatches garbage
                    return (400, json.dumps(
                        {"error": str(e)}).encode(), "application/json")
                # 202: staging continues in the background — poll
                # GET /version until the staged state settles
                return (202, json.dumps(out).encode(),
                        "application/json")
            if path == "/rollout/flip":
                out = self.versions.flip(version=args.get("version"))
                return 200, json.dumps(out).encode(), "application/json"
            if path == "/rollout/rollback":
                out = self.versions.rollback()
                return 200, json.dumps(out).encode(), "application/json"
            if path == "/rollout/abort":
                out = self.versions.abort()
                return 200, json.dumps(out).encode(), "application/json"
        except RolloutError as e:
            # an illegal transition is a conflict with current state,
            # not a server fault: 409 + the state that refused it
            return (409, json.dumps(
                {"error": str(e),
                 "rollout": self.versions.status()}).encode(),
                "application/json")
        return None

    #: sentinel: the API key was missing/unknown under the "reject"
    #: policy — the frontends answer 401 without touching _admit
    _TENANT_REJECTED = object()
    _UNKNOWN_KEY_BODY = b'{"error": "unknown or missing API key"}'

    def _resolve_tenant(self, headers):
        """Identity at the edge: API key (``X-Api-Key`` /
        ``Authorization: Bearer``) → tenant. ``None`` when tenancy is
        off; :data:`_TENANT_REJECTED` when the registry's policy
        refuses the credential (the caller 401s)."""
        if self.tenancy is None:
            return None
        tenant = self.tenancy.resolve(extract_api_key(headers))
        return tenant if tenant is not None else self._TENANT_REJECTED

    def _decode_retry_after(self) -> float:
        """Honest decode-plane Retry-After: the scheduler's
        slot-release EWMA scaled by the waiting depth, falling back to
        the configured constant while cold/stale."""
        hint = (self.decoder.retry_after_hint()
                if self.decoder is not None else None)
        return hint if hint is not None else self.shed_retry_after

    def _shed_info(self, reason: str, decode: bool,
                   retry_after: Optional[float] = None) -> dict:
        """The 429 detail a shed decision carries back to the
        frontends: reason-specific body plus the most honest
        ``Retry-After`` available — the bucket's refill math for rate
        sheds, the decode slot-release EWMA for decode-plane pressure,
        the configured constant otherwise."""
        if retry_after is None or retry_after <= 0:
            retry_after = (self._decode_retry_after() if decode
                           else self.shed_retry_after)
        body = (b'{"error": "overloaded"}' if reason == "overload"
                else json.dumps({"error": "tenant quota exceeded",
                                 "reason": reason}).encode())
        return {"reason": reason, "body": body,
                "retry_after": round(max(float(retry_after), 1e-3), 3)}

    def _overload_shed(self, tenant, decode: bool) -> bool:
        """The overload verdict for NEW work: the plain full-queue
        check without tenancy; priority-aware (background sheds at the
        high-water mark, batch midway, interactive only when full)
        with it."""
        if tenant is None or self.tenancy is None:
            return (self.decoder.overloaded() if decode
                    else self._overloaded())
        if decode:
            depth, cap = self.decoder.queue_pressure()
        else:
            depth, cap = self.backlog(), self.max_queue
        return self.tenancy.should_shed(tenant, depth, cap)

    def _admit(self, payload: Any, rid: Optional[str],
               deadline: Optional[Deadline], tid: str,
               decode: bool = False, tenant=None
               ) -> Tuple[str, Optional[_PendingRequest],
                          Optional[tuple], bool, Optional[dict]]:
        """Ingress admission, shared by both frontends AND both data
        planes (``decode=True`` sheds on the decode scheduler's
        waiting-queue depth instead of the frame backlog; everything
        else — replay, join, doa — is identical). Returns ``(kind,
        pending, committed_entry, window_missed, shed)`` with kind one
        of:

        * ``"replay"`` — the rid's reply is already committed
          (``committed_entry`` is the journal tuple);
        * ``"join"``   — the rid is in flight: wait on / watch
          ``pending`` without enqueuing a second compute;
        * ``"shed"``   — refused with 429; ``shed`` carries the
          reason-specific body and honest Retry-After;
        * ``"doa"``    — the deadline was spent before admission:
          ``pending`` is already resolved with its 504;
        * ``"enqueue"`` — ``pending`` is fresh; the caller enqueues it
          (:meth:`_enqueue`) and awaits resolution.

        With ``tenant`` set, quota checks run AFTER the replay/join
        short-circuits (a replay returns the journaled reply without
        re-charging the tenant's bucket or in-flight cap — retries of
        answered work are free) and BEFORE the pending is created, so
        every charged admission has exactly one release in the
        resolution funnel."""
        window_missed = False
        if rid:
            with self._commit_lock:
                self._reap_expired_locked()
                committed = self._journal.get(rid)
                pending = (self._inflight.get(rid)
                           if committed is None else None)
                if committed is not None:
                    self.n_replayed += 1
                    if self.tenancy is not None:
                        # replay attribution follows the JOURNALED
                        # owner when the entry carries one (a replay
                        # through a different key still bills the
                        # tenant that paid for the compute)
                        owner = (committed[4] if len(committed) > 4
                                 and committed[4] else
                                 tenant.id if tenant is not None
                                 else None)
                        if owner:
                            self.tenancy.note_replay(owner)
                    return "replay", None, committed, False, None
                if pending is not None:
                    return "join", pending, None, False, None
                if self._overload_shed(tenant, decode):
                    # shedding applies to NEW work only: replays and
                    # in-flight joins above cost no inference and
                    # always succeed
                    self.n_shed += 1
                    if tenant is not None:
                        self.tenancy.note_shed_overload(tenant.id)
                    return ("shed", None, None, False,
                            self._shed_info("overload", decode))
                # request ids are unique per logical request, so a rid
                # in the evicted ring can only be a retry that outlived
                # the replay window — detected, warned, and re-executed
                # (the documented past-window semantics)
                window_missed = rid in self._evicted
                if window_missed:
                    self.n_window_missed += 1
                if tenant is not None:
                    quota = self.tenancy.admit(tenant)
                    if quota is not None:
                        self.n_shed += 1
                        return ("shed", None, None, False,
                                self._shed_info(quota[0], decode,
                                                quota[1]))
                pending = _PendingRequest(payload, rid, deadline,
                                          trace=tid)
                if tenant is not None:
                    pending.tenant = tenant.id
                self._inflight[rid] = pending
            if window_missed:
                logger.warning(
                    "request id %s retried after its journal entry was "
                    "evicted (journal_size=%d, journal_ttl=%s); "
                    "re-executing", rid, self.journal_size,
                    self.journal_ttl)
        else:
            if self._overload_shed(tenant, decode):
                with self._commit_lock:
                    self.n_shed += 1
                if tenant is not None:
                    self.tenancy.note_shed_overload(tenant.id)
                return ("shed", None, None, False,
                        self._shed_info("overload", decode))
            if tenant is not None:
                quota = self.tenancy.admit(tenant)
                if quota is not None:
                    with self._commit_lock:
                        self.n_shed += 1
                    return ("shed", None, None, False,
                            self._shed_info(quota[0], decode,
                                            quota[1]))
            pending = _PendingRequest(payload, deadline=deadline,
                                      trace=tid)
            if tenant is not None:
                pending.tenant = tenant.id
        if deadline is not None and deadline.expired:
            # dead on arrival: the client's budget is already spent —
            # never enqueue work nobody will read. The pending is
            # resolved (status + event) BEFORE it leaves _inflight, so
            # a duplicate that joined it in the window between the two
            # locked sections is released immediately instead of
            # blocking until request_timeout
            pending.status = 504
            pending.reply = b'{"error": "deadline exceeded"}'
            with self._stats_lock:
                self.n_deadline_expired += 1
            with self._commit_lock:
                self._inflight.pop(pending.rid, None)
            self._release(pending)
            return "doa", pending, None, window_missed, None
        return "enqueue", pending, None, window_missed, None

    def _enqueue(self, pending: _PendingRequest, root) -> None:
        """Hand an admitted request to the data plane. The root span
        rides the work item across the stage threads (exactly as the
        trace id does); ``t_enqueue`` anchors the queue_wait child
        span."""
        pending.span = root
        pending.t_enqueue = self.tracer.clock.now()
        if self.adaptive_batcher is not None:
            # one clock read + two float ops: the arrival-rate EWMA
            # the adaptive batch policy decides wait windows from
            self.adaptive_batcher.note_arrival()
        with self._stats_lock:
            self._n_backlog += 1
        self._queue.put(pending)

    def _enqueue_decode(self, pending: _PendingRequest, root,
                        parsed=None) -> Optional[Tuple[int, bytes]]:
        """Hand an admitted request to the decode scheduler. Returns
        ``None`` on success or ``(status, body)`` for a synchronous
        reject (bad payload -> 400, waiting queue full -> 429) — the
        reject path removes the in-flight entry so a retried rid
        re-admits instead of joining a dead pending. ``parsed``
        forwards a streaming pre-check's parse result so the payload
        is validated once."""
        pending.span = root
        pending.t_enqueue = self.tracer.clock.now()
        try:
            self.decoder.submit(pending, parsed=parsed)
            return None
        except DecodeOverloaded:
            with self._commit_lock:
                self._inflight.pop(pending.rid, None)
                self.n_shed += 1
            self._release_tenant(pending)
            return 429, b'{"error": "overloaded"}'
        except ValueError as e:
            with self._commit_lock:
                self._inflight.pop(pending.rid, None)
            self._release_tenant(pending)
            return 400, json.dumps({"error": str(e)}).encode()

    def _release_tenant(self, p: _PendingRequest) -> None:
        """Return ``p``'s tenant in-flight slot (idempotent: the slot
        id is cleared first, so every resolution path may call this
        and the slot still comes back exactly once)."""
        owner, p.tenant = p.tenant, None
        if owner is None or self.tenancy is None:
            return
        self.tenancy.release(owner)
        if self._m_tenant_latency is not None \
                and p.t_enqueue is not None:
            self._m_tenant_latency.labels(
                self.tenancy.label_of(owner)).observe(
                (self.tracer.clock.now() - p.t_enqueue) * 1000.0)

    def _release(self, p: _PendingRequest) -> None:
        """Resolve a pending request: wake any threaded-frontend
        handler blocked on the event AND fire any event-loop completion
        callbacks. A callback registered concurrently with release may
        fire twice (see :meth:`_add_waiter`); the event-loop frontend
        drops the duplicate reply by connection generation."""
        self._release_tenant(p)
        p.event.set()
        for cb in p.callbacks:
            try:
                cb(p)
            except Exception:  # noqa: BLE001 — one bad reply callback
                logger.warning("reply callback failed",  # must never
                               exc_info=True)            # strand others

    def _add_waiter(self, p: _PendingRequest, cb) -> None:
        """Watch a pending request from the event-loop frontend. Append
        -then-check: if release already ran (or runs concurrently and
        misses the append), the is_set check fires the callback here —
        at worst both sides fire it, which the frontend's generation
        guard absorbs."""
        p.callbacks.append(cb)
        if p.event.is_set():
            try:
                cb(p)
            except Exception:  # noqa: BLE001
                logger.warning("reply callback failed", exc_info=True)

    # -- event-loop frontend protocol ----------------------------------------

    def handle_request(self, method: str, path: str, headers,
                       body: bytes, reply) -> bool:
        """The :class:`EventLoopFrontend` application protocol (see
        serving/frontend.py): route one framed request. GET routes
        answer synchronously on the loop thread (they are in-memory
        reads); POST predict replies later, from whichever stage thread
        commits the request — ``reply`` is thread-safe and
        duplicate-proof by design."""
        if method == "GET":
            route = self._get_route(path, headers)
            if route is None:
                return False
            status, rbody, ctype, extra = route
            reply(status, rbody, ctype=ctype, extra=extra)
            return True
        if method != "POST":
            return False
        # decode matches on the BASE path (the ?stream=1 opt-in rides
        # the query string); the frame plane stays an exact match
        is_decode = (self.decoder is not None
                     and path.partition("?")[0] == self.decode_path)
        if path != self.api_path and not is_decode:
            routed = self._post_route(path, body)
            if routed is None:
                return False
            status, rbody, ctype = routed
            reply(status, rbody, ctype=ctype)
            return True
        tid, parent_sid = extract_span_context(headers)
        with trace_context(tid):
            root = self.tracer.start("request", trace_id=tid,
                                     remote_parent=parent_sid,
                                     route=(self.decode_path if is_decode
                                            else self.api_path))
            if capture_hint(headers):
                root.force = True
            status = "error"
            try:
                status = self._predict_eventloop(headers, body, tid,
                                                 root, reply,
                                                 decode=is_decode,
                                                 path=path)
            finally:
                if status is not None:
                    # sync reject paths; async completions finish the
                    # root in their on_done callback instead
                    self.tracer.finish(root, status=status)
        return True

    def _predict_eventloop(self, headers, body: bytes, tid: str,
                           root, reply, decode: bool = False,
                           path: str = ""
                           ) -> Optional[str]:
        """Admission for the event-loop frontend: same decisions as the
        threaded ``_do_predict`` (one ``_admit`` serves both), but the
        enqueue/join paths return None and deliver via callback — no
        thread ever blocks on a pending request."""
        if self._draining.is_set():
            # graceful drain: accepted work finishes, new work is
            # refused so the orchestrator's retry lands on a live worker
            reply(503, b'{"error": "draining"}',
                  extra=((TRACE_HEADER, tid),
                         ("Retry-After", str(self.shed_retry_after))))
            return "shed"
        try:
            payload = json.loads(body or b"{}")
        except ValueError:
            # even a rejected request must echo its trace id, or the
            # client cannot correlate the failure with worker logs
            reply(400, b'{"error": "invalid JSON"}',
                  extra=((TRACE_HEADER, tid),))
            return "error"
        deadline = Deadline.from_headers(headers, clock=self.clock)
        rid = headers.get("X-Request-Id")
        tenant = self._resolve_tenant(headers)
        if tenant is self._TENANT_REJECTED:
            reply(401, self._UNKNOWN_KEY_BODY,
                  extra=((TRACE_HEADER, tid),))
            return "error"
        if tenant is not None:
            root.set_attr("tenant", tenant.id)
        kind, pending, committed, window_missed, shed = \
            self._admit(payload, rid, deadline, tid, decode=decode,
                        tenant=tenant)
        if rid:
            root.set_attr("rid", rid)
        if kind == "replay":
            root.set_attr("replayed", True)
            reply(committed[0], committed[1],
                  extra=((TRACE_HEADER, tid), ("X-Replayed", "1")))
            return "ok"
        if kind == "shed":
            reply(429, shed["body"],
                  extra=((TRACE_HEADER, tid),
                         ("Retry-After", str(shed["retry_after"]))))
            return "shed"
        if kind == "doa":
            reply(504, pending.reply, extra=((TRACE_HEADER, tid),))
            return "deadline"

        tracer = self.tracer
        joined = kind == "join"

        def on_done(p: _PendingRequest) -> None:
            extra = [(TRACE_HEADER, tid)]
            # a joined duplicate is only "replayed" if the reply was
            # actually committed — errors are never journaled, so they
            # must not carry the committed-replay marker
            if joined and p.status == 200:
                extra.append(("X-Replayed", "1"))
            if window_missed:
                extra.append(("X-Replay-Window-Missed", "1"))
            reply(p.status, p.reply or b"{}", extra=tuple(extra))
            # the root finishes HERE, with the commit-time status: if
            # the frontend's request-timeout sweep already 504ed the
            # connection, this reply is dropped by generation but the
            # trace still records what actually happened. (A request
            # whose reply never comes at all leaves its root
            # unfinished — the threaded frontend remains the plane
            # that tail-captures true stuck-batch timeouts.)
            tracer.finish(root, status="ok" if p.status == 200 else
                          "deadline" if p.status == 504 else "error")

        if joined:
            self._add_waiter(pending, on_done)
        elif decode:
            stream = parsed = None
            want_stream = _stream_requested(path, payload)
            if want_stream:
                # pre-validate so sync rejects (400/429) stay plain
                # replies — once the chunked 200 head is on the wire
                # there is no taking it back; the parse result is
                # forwarded to submit so the payload is checked once
                try:
                    parsed = self.decoder.parse(payload)
                except ValueError as e:
                    with self._commit_lock:
                        self._inflight.pop(pending.rid, None)
                    self._release_tenant(pending)
                    reply(400, json.dumps({"error": str(e)}).encode(),
                          extra=((TRACE_HEADER, tid),))
                    return "error"
                stream = reply.begin_stream(
                    extra=((TRACE_HEADER, tid),))
                # the stream is attached BEFORE submit so the very
                # first token already flows through it; None means
                # the connection died between framing and now
                pending.stream = stream
            err = self._enqueue_decode(pending, root, parsed=parsed)
            if err is not None:
                pending.stream = None
                e_status, e_body = err
                if stream is not None:
                    # headers are out: deliver the reject as the one
                    # and only SSE event (racy overload/parse change)
                    stream.finish(b"data: " + e_body + b"\n\n")
                    return "shed" if e_status == 429 else "error"
                extra = [(TRACE_HEADER, tid)]
                if e_status == 429:
                    extra.append(("Retry-After",
                                  str(self._decode_retry_after())))
                reply(e_status, e_body, extra=tuple(extra))
                return "shed" if e_status == 429 else "error"
            if stream is not None:
                # the stream delivers the body; the waiter only
                # finishes the root span at commit
                tracer2 = self.tracer

                def on_stream_done(p: _PendingRequest) -> None:
                    tracer2.finish(
                        root, status="ok" if p.status == 200 else
                        "deadline" if p.status == 504 else "error")

                self._add_waiter(pending, on_stream_done)
                return None
            self._add_waiter(pending, on_done)
        else:
            self._enqueue(pending, root)
            self._add_waiter(pending, on_done)
        return None

    # -- batching loop -------------------------------------------------------

    def backlog(self) -> int:
        """Requests accepted but not yet dispatched into the model."""
        with self._stats_lock:
            return self._n_backlog

    def _overloaded(self) -> bool:
        return self.max_queue > 0 and self.backlog() >= self.max_queue

    def _collect_batch(self) -> List[_PendingRequest]:
        if self.tenancy is not None and self.tenancy.fair_share:
            return self._collect_batch_fair()
        try:
            first = self._queue.get(timeout=0.05)
        except Empty:
            return []
        # the "collect" span starts at the FIRST request, so /stats
        # reports the batch-mate gathering window (real latency cost),
        # not the idle 0.05s polls of an unloaded server
        with self.timings.span("collect"):
            return self._collect_rest(first)

    # -- fair-share batch assembly (tenancy + fair_share on) ----------------
    #
    # The ingress SimpleQueue stays the handoff (frontend threads only
    # ever put); the collector drains it into per-tenant FIFO deques
    # and pops in deficit-weighted round-robin order, so one tenant's
    # burst can reorder only its OWN requests — a 10:1 flood fills at
    # most its fair share of every batch once another tenant is
    # waiting. All of this is collector-thread-local state: no lock,
    # no hot-path cost for the ingress threads, and the batch still
    # pads to the same shape buckets (fairness reorders rows, never
    # reshapes the dispatch).

    def _fair_push(self, p: _PendingRequest) -> None:
        tid_t = p.tenant or ANONYMOUS_ID
        self._fair_q.setdefault(tid_t, deque()).append(p)
        self._fair_total += 1

    def _fair_drain_ingress(self) -> None:
        try:
            while True:
                self._fair_push(self._queue.get_nowait())
        except Empty:
            pass

    def _fair_pop(self) -> Optional[_PendingRequest]:
        present = {t: self.tenancy.weight_of(t)
                   for t, dq in self._fair_q.items() if dq}
        if not present:
            return None
        t = self._fair_cycle.choose(present)
        dq = self._fair_q[t]
        p = dq.popleft()
        if not dq:
            del self._fair_q[t]
        self._fair_total -= 1
        return p

    def _collect_batch_fair(self) -> List[_PendingRequest]:
        self._fair_drain_ingress()
        if self._fair_total == 0:
            try:
                self._fair_push(self._queue.get(timeout=0.05))
            except Empty:
                return []
            self._fair_drain_ingress()
        with self.timings.span("collect"):
            return self._collect_rest_fair()

    def _collect_rest_fair(self) -> List[_PendingRequest]:
        batch = [self._fair_pop()]
        limit = min(self.max_batch_size, self._bucket_sizes()[-1])
        window_ms = self.max_latency_ms
        if self.adaptive_batcher is not None:
            decided = self.adaptive_batcher.decide_wait_ms(
                1 + self._fair_total + self._queue.qsize())
            if decided is not None:
                window_ms = decided
        deadline = time.monotonic() + max(window_ms, 0.0) / 1000.0
        while len(batch) < limit:
            p = self._fair_pop()
            if p is not None:
                batch.append(p)
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                self._fair_push(self._queue.get(timeout=remaining))
            except Empty:
                break
            self._fair_drain_ingress()
        return batch

    def _collect_rest(self, first: _PendingRequest
                      ) -> List[_PendingRequest]:
        batch = [first]
        # the collection ceiling is the LADDER's top bucket, not the
        # raw max_batch_size: with a batch multiple that does not
        # divide the cap (100-row budget over 8 shards -> top bucket
        # 96), collecting past the top would force a bucket beyond the
        # operator's ceiling
        limit = min(self.max_batch_size, self._bucket_sizes()[-1])
        window_ms = self.max_latency_ms
        if self.adaptive_batcher is not None:
            # the adaptive policy picks THIS batch's wait from the
            # live arrival rate + per-bucket dispatch latencies (None
            # while warming up -> the fixed knob keeps ruling; the
            # fixed knob is also the policy's hard ceiling)
            decided = self.adaptive_batcher.decide_wait_ms(
                1 + self._queue.qsize())
            if decided is not None:
                window_ms = decided
        if window_ms <= 0:
            # latency-first mode: take whatever is already queued and
            # serve immediately — no added wait for batch-mates
            while len(batch) < limit:
                try:
                    batch.append(self._queue.get_nowait())
                except Empty:
                    break
            return batch
        deadline = time.monotonic() + window_ms / 1000.0
        while len(batch) < limit:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                batch.append(self._queue.get(timeout=remaining))
            except Empty:
                break
        return batch

    def _expire(self, p: _PendingRequest, where: str) -> None:
        """504 a request whose deadline passed; never journaled (status
        != 200), so a fresh-budget retry re-executes for real."""
        p.status = 504
        p.reply = json.dumps(
            {"error": f"deadline exceeded {where}"}).encode()
        # under the stats lock: _expire now runs concurrently from the
        # collector, executor, AND encoder-pool threads
        with self._stats_lock:
            self.n_deadline_expired += 1
        self._commit(p)

    # -- data plane stages ---------------------------------------------------
    #
    # Each batch travels through three stage functions; the pipelined
    # plane runs them on separate threads (collector -> executor ->
    # encoder pool), the serial plane (pipeline=False) runs them inline.
    # A batch is a "job" dict: {"batch_n": total collected, "live":
    # not-yet-expired requests, "df": the (bucket-padded) frame,
    # "n_live": true row count, "out": model output, "error": the
    # failure that 500s the batch}.

    def _filter_expired(self, requests: List[_PendingRequest]
                        ) -> List[_PendingRequest]:
        """Deadline check #1: 504 the expired, return the survivors."""
        live = []
        for p in requests:
            if p.deadline is not None and p.deadline.expired:
                self._expire(p, "before dispatch")
            else:
                live.append(p)
        return live

    def _add_spans(self, requests: List[_PendingRequest], name: str,
                   t0: float, t1: float, status: str = "ok",
                   **attrs) -> None:
        """Record one batch-level measurement as a child span of every
        traced request's root: the batch does the work once, but each
        request's trace must show its own full timeline. Synthetic
        warmup requests carry no root span and record nothing."""
        for p in requests:
            if p.span is not None:
                self.tracer.add(name, t0, t1, parent=p.span,
                                status=status, **attrs)

    def _refresh_live(self, job: dict,
                      requests: List[_PendingRequest]) -> dict:
        """Deadline check #1 over ``requests`` + (re)assembly of the
        job's frame — the shared body of _stage_prepare and the
        dispatch-time re-check."""
        live = self._filter_expired(requests)
        job["live"], job["n_live"] = live, len(live)
        job["df"] = None
        if live:
            t0 = self.tracer.clock.now()
            try:
                # remember which wire config assembled this frame: the
                # dispatch stage compares it against ITS version
                # snapshot and re-assembles on mismatch (a flip landing
                # in the assemble->dispatch window)
                job["wire_qc"] = self.versions.active.quantization
                with self.timings.span("assemble"):
                    job["df"] = self._assemble_frame(
                        live, qc=job["wire_qc"])
            except Exception as e:  # noqa: BLE001 — bad payloads -> 500s
                job["error"] = e
            self._add_spans(live, "assemble", t0, self.tracer.clock.now(),
                            status="ok" if job["error"] is None
                            else "error")
        return job

    def _stage_prepare(self, batch: List[_PendingRequest]) -> dict:
        """Stage 1 (collector): deadline check #1 — before dispatch: a
        request whose budget expired while queued must not occupy a
        batch slot or run through the model at all — then columnar
        frame assembly + shape-bucket padding."""
        # queue_wait: enqueue -> the moment the collector owns the
        # batch; recorded for EVERY collected request (the expired ones
        # below waited too — that wait is usually why they expired)
        now = self.tracer.clock.now()
        for p in batch:
            if p.span is not None and p.t_enqueue is not None:
                self.tracer.add("queue_wait", p.t_enqueue, now,
                                parent=p.span)
        job = {"batch_n": len(batch), "live": [], "n_live": 0,
               "df": None, "out": None, "error": None, "version": None,
               "wire_qc": None}
        return self._refresh_live(job, batch)

    #: sentinel: "use the active version's quantization config"
    _ACTIVE_QC = object()

    def _assemble_frame(self, live: List[_PendingRequest],
                        qc=_ACTIVE_QC) -> DataFrame:
        """Payloads -> columnar frame, padded up to the shared bucket.

        ``DataFrame.from_rows`` builds one list per column straight off
        the payload dicts (heterogeneous key sets raise -> batch 500,
        the framework-wide row-assembly policy). With ``bucket_batches`` every
        column is edge-padded (repeat last row: valid for object/string
        columns) to the power-of-two bucket, so any live batch size maps
        onto a bounded set of dispatch shapes.

        ``qc`` is the wire config the frame is cast for (default: the
        active version's): the quantized wire starts HERE — columns
        drop to the wire dtype before bucket padding (edge-padding
        1-byte rows, not the 8-byte int64 ``from_rows`` produced) and
        before the device upload. Staged-version warmup passes its own
        config, and the dispatch stage re-assembles from the RAW
        payloads when a flip changed the config mid-window (casting is
        lossy, so a cast frame cannot be re-cast for a different
        plane).
        """
        payloads = [p.payload if isinstance(p.payload, dict)
                    else {"value": p.payload} for p in live]
        df = DataFrame.from_rows(payloads)
        if qc is self._ACTIVE_QC:
            qc = self.versions.active.quantization
        if qc is not None and df.columns:
            df = qc.quantize_frame(df)
        if self.bucket_batches and df.columns:
            # TP-aware ladder: buckets are rounded up to the model's
            # batch multiple HERE, once, so data/tensor-sharded
            # dispatch (dist.put_batch / batch_sharding) never re-pads
            mult = self._batch_multiple()
            df = DataFrame({
                n: padded_device_batch(df[n], self.max_batch_size,
                                       bucket=True, pad_mode="edge",
                                       multiple=mult)[0]
                for n in df.columns})
        return df

    @staticmethod
    def _shape_key(df: DataFrame):
        """The dispatch-shape identity: row count + column schema —
        exactly what forces a retrace in any jitted model."""
        return (df.num_rows, tuple(sorted(df.schema().items())))

    def _batch_multiple(self, model=None) -> int:
        """A model's batch divisibility constraint (the mesh data-axis
        size for TP/data-sharded models; 1 for everything else) — the
        ACTIVE model's by default, read per call so a flip to a
        differently-sharded version moves the ladder with it."""
        if model is None:
            model = self.versions.active.model
        return max(int(getattr(model, "batch_multiple", 1) or 1), 1)

    def _bucket_sizes(self, model=None) -> List[int]:
        """Every reachable shape bucket: the pow2 ladder clamped at
        max_batch_size, rounded up to the model's batch multiple
        (the active model's by default; staged-version warmup passes
        the STAGED model, whose sharding may differ — it must warm the
        ladder live traffic will dispatch AFTER the flip, or the flip
        retraces)."""
        return bucket_ladder(self.max_batch_size,
                             multiple=self._batch_multiple(model))

    def _warmup_frame(self, payload: Any, n: int,
                      qc=_ACTIVE_QC) -> DataFrame:
        """One synthetic bucket-shaped frame, built exactly like live
        traffic's (payload -> rows -> wire cast -> bucket padding), so
        a model warmed on it compiles the very executables live
        dispatch uses. ``qc`` overrides the wire config (staged-
        version warmup: the STAGED plane's dtypes, not the active
        one's)."""
        return self._assemble_frame(
            [_PendingRequest(payload) for _ in range(n)], qc=qc)

    def _stage_dispatch(self, job: dict) -> dict:
        """Stage 2 (executor): push the bucketed frame through the
        model. New dispatch shapes are counted as recompiles (any jitted
        model retraces exactly when the input shape set grows)."""
        with self._stats_lock:
            self._n_backlog -= job["batch_n"]
        # deadline check #1 runs twice on the pipelined plane: once at
        # collection (cheap early filter, saves the assembly) and again
        # HERE, at true dispatch time — a request can expire while its
        # batch waits behind a slow model, and it must still never reach
        # the model. Only the (rare) expiry case pays a re-assembly.
        if job["error"] is None and any(
                p.deadline is not None and p.deadline.expired
                for p in job["live"]):
            self._refresh_live(job, job["live"])
        df = job["df"]
        if job["error"] is None and df is not None:
            # ONE snapshot of the active version per batch: the rollout
            # flip is a reference assignment, so this batch dispatches,
            # labels, and counts wholly on the version it read here —
            # a flip landing mid-batch affects only the NEXT batch
            mv = self.versions.active
            job["version"] = mv.version
            t0 = self.tracer.clock.now()
            qc = mv.quantization
            new_shape = False
            try:
                if job.get("wire_qc", qc) != qc:
                    # a flip changed the wire contract between assemble
                    # and dispatch (rare — the window is one pipeline
                    # handoff): the cast is lossy, so re-assemble from
                    # the RAW payloads for THIS version's plane rather
                    # than mis-feeding frames cast for the old one
                    df = self._assemble_frame(job["live"], qc=qc)
                    job["df"], job["wire_qc"] = df, qc
                key = self._shape_key(df)
                # bytes-on-wire evidence, by column dtype: what this
                # dispatch actually moves host->device (u8 rows are 4x
                # smaller than the f32 plane's)
                wire: Dict[str, int] = {}
                for c in df.columns:
                    a = df[c]
                    if a.dtype != np.dtype("O"):
                        name = a.dtype.name
                        wire[name] = wire.get(name, 0) + int(a.nbytes)
                for name, nb in wire.items():
                    self._m_wire_bytes.labels(name).inc(nb)
                with self._stats_lock:
                    new_shape = key not in self._shapes_seen
                    if new_shape:
                        self.n_recompiles += 1
                        # bounded: adversarial/heterogeneous schemas
                        # (a new field name per request) must not grow
                        # a long-lived worker's memory without limit —
                        # past the cap, new shapes still count as
                        # recompiles but are no longer remembered
                        if len(self._shapes_seen) < _MAX_SHAPES_TRACKED:
                            self._shapes_seen.add(key)
                # per-version shape bookkeeping: a shape first reaching
                # the live path after this version flipped is a
                # post-flip recompile (/version, model_swap_v1 gate)
                mv.record_shape(key)
                # batch-representative trace AND span (the first live
                # request's): contextvars do not follow the thread
                # handoff, so the executor re-binds here — model-
                # internal logs, pipeline-stage spans, and any io/http
                # egress the model performs nest under that request's
                # root (and the dispatch histogram's exemplar picks up
                # its trace id). Per-request exact ids ride the journal
                # lines; per-request dispatch child spans are recorded
                # for every live root below.
                t_d0 = self.tracer.clock.now()
                with trace_context(job["live"][0].trace), \
                        self.tracer.bind(job["live"][0].span), \
                        self.timings.span("dispatch"), \
                        self._m_dispatch.labels(df.num_rows).time():
                    out = mv.model.transform(df)
                seconds = self.tracer.clock.now() - t_d0
                if new_shape:
                    # a retrace happened inside that dispatch: ledger
                    # it (bounded ring — /stats "compile_events" and
                    # the span's compiled=true attribute)
                    self.compile_ledger.note(
                        "dispatch", shape=str(key),
                        duration_ms=seconds * 1000.0,
                        bucket=df.num_rows, model_version=mv.version)
                # always-on compute accounting: wall-clock per bucket,
                # MFU when the model reports flops for the shape
                self.mfu.note(df.num_rows, seconds,
                              flops=self._flops_for(mv, df, key))
                self._charge_tenant_device(job["live"],
                                           seconds * 1000.0)
                # df.num_rows < n_live only for degenerate frames (e.g.
                # empty-object payloads -> a zero-column frame): still a
                # row-count error, never a silent short batch
                if out.num_rows != df.num_rows \
                        or df.num_rows < job["n_live"]:
                    raise RuntimeError(
                        f"model returned {out.num_rows} rows for a "
                        f"{df.num_rows}-row dispatch ({job['n_live']} live "
                        f"requests); serving models must preserve row "
                        f"count")
                job["out"] = out
                # shadow traffic: mirror this batch to the staged
                # version (sampled, queued, never blocking) — outputs
                # are compared off the client path
                self.versions.maybe_shadow(df, out)
            except Exception as e:  # noqa: BLE001 — model failure -> 500s
                job["error"] = e
            span_attrs = {"bucket": df.num_rows,
                          "model_version": mv.version}
            if new_shape:
                # a captured slow dispatch that compiled says so —
                # first-shape latency is expected, not a regression
                span_attrs["compiled"] = True
            if qc is not None:
                # a captured slow dispatch says which wire it rode
                span_attrs["wire_dtype"] = qc.wire_dtype
            # tensor-parallel dispatch carries its placement on the
            # span (a cheap precomputed label like "data=4,model=2"),
            # so a captured slow dispatch says where it ran
            pl = getattr(mv.model, "placement_label", None)
            if pl:
                span_attrs["placement"] = pl
            self._add_spans(
                job["live"], "dispatch", t0, self.tracer.clock.now(),
                status="ok" if job["error"] is None else "error",
                **span_attrs)
        return job

    def _flops_for(self, mv, df, key) -> Optional[float]:
        """Per-shape flops for the MFU meter, memoized per (version,
        shape key): a model may expose ``dispatch_flops(df)`` (exact
        count) or ``cost_analysis(df)`` (XLA's compiled estimate, a
        dict with "flops"). Models with neither cost one attribute
        probe per shape and meter wall-clock only."""
        ck = (mv.version, key)
        if ck in self._flops_cache:
            return self._flops_cache[ck]
        flops = None
        for attr in ("dispatch_flops", "cost_analysis"):
            fn = getattr(mv.model, attr, None)
            if fn is None:
                continue
            try:
                val = fn(df)
                if attr == "cost_analysis":
                    val = (val or {}).get("flops")
                if val:
                    flops = float(val)
                    break
            except Exception:  # noqa: BLE001 — accounting is optional
                pass
        # bounded exactly like _shapes_seen: adversarial schemas must
        # not grow the memo without limit
        if len(self._flops_cache) < _MAX_SHAPES_TRACKED:
            self._flops_cache[ck] = flops
        return flops

    def _encode_replies(self, out: DataFrame, in_cols: List[str],
                        n_live: int) -> List[bytes]:
        """Unpad, select reply columns, JSON-encode. Scalar (1-D
        numeric/bool) reply columns take the columnar fast path: one
        ``tolist`` per column, plain-python dict per row — no per-row
        numpy-scalar round trip."""
        cols = self.reply_cols or \
            [c for c in out.columns if c not in in_cols]
        sub = out.select(cols)       # raises on missing reply_cols
        if not cols:
            return [b"{}"] * n_live
        arrays = [sub[c] for c in cols]
        if all(a.ndim == 1 and a.dtype.kind in "fiub" for a in arrays):
            lists = [a[:n_live].tolist() for a in arrays]
            return [json.dumps(dict(zip(cols, vals))).encode()
                    for vals in zip(*lists)]
        replies = []
        for i in range(n_live):
            row = {c: a[i] for c, a in zip(cols, arrays)}
            replies.append(json.dumps(_jsonify(row)).encode())
        return replies

    def _stage_finish(self, job: dict) -> None:
        """Stage 3 (encoder): encode replies, deadline check #2, commit."""
        live = job["live"]
        with self._stats_lock:
            self.n_batches += 1
            self.n_requests += job["batch_n"]
        # adaptive-threshold upkeep rides the encoder stage — off the
        # request path; one int bump per batch, a histogram walk every
        # refresh_every-th batch (same cadence for the batch policy's
        # service-time table)
        if self.adaptive is not None:
            self.adaptive.tick()
        if self.adaptive_batcher is not None:
            self.adaptive_batcher.tick()
        if not live:
            return
        replies = None
        if job["error"] is None:
            t0 = self.tracer.clock.now()
            try:
                with trace_context(live[0].trace), \
                        self.tracer.bind(live[0].span), \
                        self.timings.span("encode"):
                    replies = self._encode_replies(
                        job["out"], job["df"].columns, job["n_live"])
            except Exception as e:  # noqa: BLE001 — encode failure -> 500s
                job["error"] = e
            self._add_spans(live, "encode", t0, self.tracer.clock.now(),
                            status="ok" if job["error"] is None
                            else "error")
        version = job["version"] or self.versions.active.version
        if job["error"] is not None:
            err = json.dumps({"error": str(job["error"])}).encode()
            with self._stats_lock:
                self.n_errors += len(live)
            for p in live:
                p.status = 500
                p.reply = err
            self.versions.count_committed(version, len(live))
            self._commit_many(live)
            return
        to_commit = []
        for p, r in zip(live, replies):
            # deadline check #2 — before commit: the client is already
            # gone, so the reply must not be journaled as a committed
            # (replayable) result
            if p.deadline is not None and p.deadline.expired:
                self._expire(p, "before commit")
                continue
            p.reply = r
            to_commit.append(p)
        self.versions.count_committed(version, len(to_commit))
        self._commit_many(to_commit)
        # capture AFTER commit: only committed (journal-visible)
        # request/reply rows feed the retrain loop; offer never blocks.
        # Synthetic warmup batches are excluded — "nothing is
        # journaled" for them (see warmup()) covers the capture
        # journal too, or every worker restart/rollout would feed one
        # ladder of fabricated operator-payload rows into retraining
        if self.capture is not None and to_commit \
                and not self._in_warmup:
            self.capture.offer(version, to_commit)

    def _serve_batch(self, batch: List[_PendingRequest]) -> None:
        """The serial plane: all three stages inline (pipeline=False;
        also the semantic reference the pipelined plane must match)."""
        self._stage_finish(self._stage_dispatch(self._stage_prepare(batch)))

    def warmup(self, payload: Any,
               sizes: Optional[List[int]] = None) -> List[int]:
        """Dispatch one synthetic batch per shape bucket, serially, in
        the calling thread — after this, steady-state traffic with the
        same payload schema never grows the compiled-shape set (the
        ``n_recompiles`` counter in ``GET /stats`` stays flat).

        Call it before exposing the worker to traffic — ideally before
        ``start()`` (the listen socket is bound at construction, so
        early connections just queue in the accept backlog): every jit
        executable then exists before the first real request pays a
        compile, and the model never runs concurrently with a live
        dispatch. Synthetic requests carry no client request id, so
        nothing is journaled; they do count in
        ``n_batches``/``n_requests`` (they really ran the model).
        Returns the dispatched batch sizes.
        """
        # remember the payload: staged rollout versions warm every
        # bucket with the same schema before they become flip-eligible
        self.warmup_payload = payload
        if sizes is None:
            # one batch per reachable bucket: the pow2 ladder clamped at
            # max_batch_size (buckets never exceed the cap)
            sizes = self._bucket_sizes()
        self._in_warmup = True
        try:
            for n in sizes:
                batch = [_PendingRequest(payload) for _ in range(n)]
                # the dispatch stage debits the backlog; synthetic
                # requests never passed the ingress credit, so balance
                # it here
                with self._stats_lock:
                    self._n_backlog += len(batch)
                self._serve_batch(batch)
        finally:
            self._in_warmup = False
        return list(sizes)

    def _evict_locked(self, rid: str) -> None:
        # remember the id (not the reply) so a past-window retry is
        # detectable; ids are ~64 bytes vs whole reply bodies, so the
        # ring can be much deeper than the journal. pop-then-insert so a
        # re-evicted id restarts its ring lifetime at the tail
        self._evicted.pop(rid, None)
        self._evicted[rid] = None
        self.n_journal_evicted += 1
        while len(self._evicted) > 16 * self.journal_size:
            self._evicted.popitem(last=False)

    def _reap_expired_locked(self) -> None:
        if self.journal_ttl is None:
            return
        horizon = time.monotonic() - self.journal_ttl
        while self._journal:
            rid, entry = next(iter(self._journal.items()))
            if entry[2] >= horizon:
                break
            self._journal.popitem(last=False)
            self._evict_locked(rid)

    def _recover_journal(self) -> None:
        """Replay the durable journal file into the in-memory window,
        then compact it (rewrite only the surviving entries)."""
        from mmlspark_tpu.io import fs as _fs
        now_wall, now_mono = time.time(), time.monotonic()
        if _fs.exists(self.journal_path):
            for line in _fs.read_text(self.journal_path).splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    rid, status = rec["rid"], int(rec["status"])
                    reply, t_wall = rec["reply"].encode(), float(rec["t"])
                except (ValueError, KeyError):
                    continue                      # torn tail write
                age = max(now_wall - t_wall, 0.0)
                if self.journal_ttl is not None and age > self.journal_ttl:
                    continue
                self._journal.pop(rid, None)      # newest record wins
                self._journal[rid] = (status, reply, now_mono - age,
                                      str(rec.get("trace", "")),
                                      str(rec.get("tenant", "")))
            while len(self._journal) > self.journal_size:
                self._journal.popitem(last=False)
            self.n_journal_recovered = len(self._journal)
        parent = os.path.dirname(self.journal_path)
        if parent:
            _fs.makedirs(parent)
        self._compact_journal()

    @staticmethod
    def _journal_line(rid, entry, t_wall) -> str:
        # the trace id rides every journal line, so a committed reply
        # correlates with its ingress/dispatch/egress log records even
        # after a restart replays the file; the tenant id rides along
        # so a replay across a restart still bills the owner
        return json.dumps({"rid": rid, "status": entry[0],
                           "reply": entry[1].decode(),
                           "t": round(t_wall, 3),
                           "trace": entry[3] if len(entry) > 3 else "",
                           "tenant": entry[4] if len(entry) > 4 else ""
                           }) + "\n"

    def _compact_journal(self) -> None:
        """Rewrite the file to exactly the live in-memory window and
        reopen the append handle. Runs at construction and (from the
        writer thread) whenever the append-only file outgrows the window
        by 4x — the file stays O(journal_size) however long the worker
        lives, and the next restart's replay stays O(window), not
        O(requests-ever). Only the in-memory snapshot is taken under the
        commit lock; the file rewrite happens outside it.

        The queue is DISCARDED under the same lock that snapshots the
        window (r5 advisor): commits enqueue their line while holding
        the commit lock *after* inserting into ``_journal``, so at
        snapshot time every queued line's rid is already in the
        snapshot (or evicted from it) — the rewrite supersedes them
        all. Without the drain those lines would be re-appended after
        the rewrite (duplicate lines; ``_journal_file_lines``
        over-counting, compacting early)."""
        from mmlspark_tpu.io import fs as _fs
        with self._commit_lock:
            items = list(self._journal.items())
            try:
                while True:
                    self._journal_queue.get_nowait()
            except Empty:
                pass
        if self._journal_fh is not None:
            try:
                self._journal_fh.close()
            except Exception:  # noqa: BLE001
                pass
        now_wall, now_mono = time.time(), time.monotonic()
        _fs.write_text(self.journal_path, "".join(
            self._journal_line(rid, e, now_wall - (now_mono - e[2]))
            for rid, e in items))
        self._journal_fh = _fs.open_file(self.journal_path, "ab")
        self._journal_file_lines = len(items)

    def _drain_journal_queue(self) -> None:
        """Write every queued line in one append+flush (writer thread /
        final drain in stop()); compact when the file outgrows the
        window."""
        lines = []
        try:
            while True:
                lines.append(self._journal_queue.get_nowait())
        except Empty:
            pass
        if not lines or self._journal_fh is None:
            return
        try:
            self._journal_fh.write(b"".join(lines))
            self._journal_fh.flush()
            self._journal_file_lines += len(lines)
            if self._journal_file_lines > 4 * self.journal_size:
                self._compact_journal()
        except Exception:  # noqa: BLE001 — durability is best-effort;
            logger.warning("journal append to %s failed",
                           self.journal_path, exc_info=True)

    def _journal_loop(self):
        while not self._stop.is_set():
            try:
                first = self._journal_queue.get(timeout=0.2)
            except Empty:
                continue
            # put the head back conceptually: write it plus whatever
            # else queued while we slept, in one append+flush
            buf = [first]
            try:
                while True:
                    buf.append(self._journal_queue.get_nowait())
            except Empty:
                pass
            try:
                self._journal_fh.write(b"".join(buf))
                self._journal_fh.flush()
                self._journal_file_lines += len(buf)
                if self._journal_file_lines > 4 * self.journal_size:
                    self._compact_journal()
            except Exception:  # noqa: BLE001
                logger.warning("journal append to %s failed",
                               self.journal_path, exc_info=True)

    def _commit_locked(self, p: _PendingRequest) -> None:
        if self._inflight.pop(p.rid, None) is not None \
                and p.status == 200:
            entry = (p.status, p.reply or b"{}", time.monotonic(),
                     p.trace, p.tenant or "")
            self._journal[p.rid] = entry
            if self._journal_fh is not None:
                # enqueue only: the writer thread does the file I/O
                self._journal_queue.put(self._journal_line(
                    p.rid, entry, time.time()).encode())
            while len(self._journal) > self.journal_size:
                old_rid, _ = self._journal.popitem(last=False)
                self._evict_locked(old_rid)

    def _commit(self, p: _PendingRequest) -> None:
        """Commit a reply, then release waiters. Successful replies are
        journaled under the client request id (exactly-once); errors are
        not journaled, so a client may retry them."""
        t0 = self.tracer.clock.now()
        with self._commit_lock:
            self._commit_locked(p)
            self._reap_expired_locked()
        # the commit child span must hit the recorder BEFORE the
        # release — waiters finish the ROOT on wake (threaded handler
        # thread or event-loop callback), and capture only gathers
        # spans already recorded
        self._add_spans([p], "commit", t0, self.tracer.clock.now())
        self._release(p)

    def _commit_many(self, ps: List[_PendingRequest]) -> None:
        """Batch commit: one lock acquisition and one TTL reap for the
        whole micro-batch (the per-request lock churn was measurable at
        128-row batches), preserving in-batch journal order; waiters are
        released outside the lock, in batch order."""
        if not ps:
            return
        t0 = self.tracer.clock.now()
        with self._commit_lock:
            for p in ps:
                self._commit_locked(p)
            self._reap_expired_locked()
        # record commit children before ANY release fires (see _commit)
        self._add_spans(ps, "commit", t0, self.tracer.clock.now())
        # batched reply flushing: event-loop completion callbacks fired
        # by these releases post their replies into one per-loop batch,
        # flushed with ONE deque extend + ONE wake per loop when the
        # scope exits — a 64-row commit wakes each loop once, not up to
        # 64 times (threaded-frontend waiters are Event.set, unaffected)
        with batched_replies():
            for p in ps:
                self._release(p)

    # -- pipeline loops ------------------------------------------------------

    def _track_batch(self, n: int) -> None:
        with self._stats_lock:
            self._active_batches += n

    def _handoff(self, q: "Queue[dict]", job: dict, on_stop) -> None:
        """Put a job to the next stage. Once ``_stop`` is set the
        consumer may already have exited, so a queued job could strand
        its clients until request_timeout — resolve it via ``on_stop``
        (in this thread) instead; stop()'s flush catches anything that
        races past this check."""
        while True:
            if self._stop.is_set():
                try:
                    on_stop(job)
                finally:
                    self._track_batch(-1)
                return
            try:
                q.put(job, timeout=0.1)
                return
            except Full:
                continue

    def _fail_undispatched(self, job: dict) -> None:
        """Stop-path resolution for a job that never reached the model:
        never dispatch from the collector thread — the executor may be
        mid-``model.transform``, and a second concurrent call through a
        non-thread-safe transformer could commit corrupt (journaled!)
        replies. Fail the stragglers instead; ``_flush_pipeline``
        dispatches the queued ones for real once every stage thread is
        dead."""
        if job["error"] is None:
            job["error"] = RuntimeError("server stopping before dispatch")
        # _stage_dispatch (skipped) is where the backlog debit lives
        with self._stats_lock:
            self._n_backlog -= job["batch_n"]
        self._stage_finish(job)

    def _batch_loop(self):
        """Collector thread: collect + assemble, then either run the
        batch inline (serial plane) or hand it to the executor stage.
        ``_active_batches`` counts a batch from collection until its
        replies are committed, so drain (stop()) covers the whole
        pipeline, not just this thread."""
        while not self._stop.is_set():
            batch = self._collect_batch()
            if not batch:
                continue
            self._track_batch(+1)
            if not self.pipeline:
                try:
                    self._serve_batch(batch)
                finally:
                    self._track_batch(-1)
                continue
            self._handoff(self._dispatch_q, self._stage_prepare(batch),
                          self._fail_undispatched)

    def _executor_loop(self):
        """Executor thread: model dispatch only — it hands the output to
        the encoder pool and immediately returns to the next batch, so
        encode/commit for batch N overlaps model execution for N+1."""
        while True:
            try:
                job = self._dispatch_q.get(timeout=0.05)
            except Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                job = self._stage_dispatch(job)
            except Exception as e:  # noqa: BLE001 — never kill the stage
                job["error"] = job["error"] or e
            # on stop, encoding inline is safe (no model call)
            self._handoff(self._encode_q, job, self._stage_finish)

    def _encoder_loop(self):
        """Encoder-pool thread: unpad + encode + deadline check #2 +
        commit. Pool size ``encoder_threads``: JSON encoding is the
        dominant pure-python cost at high request rates, so it gets the
        parallelism."""
        while True:
            try:
                job = self._encode_q.get(timeout=0.05)
            except Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                self._stage_finish(job)
            except Exception:  # noqa: BLE001 — never kill the stage
                logger.warning("encoder stage failed", exc_info=True)
            finally:
                self._track_batch(-1)

    def _flush_pipeline(self) -> None:
        """Finish any job still sitting in a stage queue after the
        pipeline threads exited (a handoff can race the consumers'
        shutdown): every accepted request gets its reply — or at worst
        a 500 — instead of hanging to request_timeout. Runs in the
        stop() thread after the joins, so nothing else is pulling from
        these queues (and Queue.get is atomic regardless)."""
        while True:
            try:
                job = self._dispatch_q.get_nowait()
            except Empty:
                break
            try:
                self._stage_finish(self._stage_dispatch(job))
            finally:
                self._track_batch(-1)
        while True:
            try:
                job = self._encode_q.get_nowait()
            except Empty:
                break
            try:
                self._stage_finish(job)
            finally:
                self._track_batch(-1)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingServer":
        self._threads = []
        if self._frontend is not None:
            self._frontend.start()
        else:
            t_http = threading.Thread(target=self._server.serve_forever,
                                      daemon=True)
            t_http.start()
            self._threads.append(t_http)
        # stage threads are NAMED: the sampling profiler attributes
        # samples to pipeline stages by thread name (core/profiler.py
        # STAGE_PREFIXES), so a profile reads collector/dispatch/
        # encoder, not Thread-7
        t_batch = threading.Thread(target=self._batch_loop, daemon=True,
                                   name="serving-collector")
        t_batch.start()
        self._threads.append(t_batch)
        self._stage_threads = [t_batch]
        if self.pipeline:
            t_exec = threading.Thread(target=self._executor_loop,
                                      daemon=True,
                                      name="serving-executor")
            t_exec.start()
            self._threads.append(t_exec)
            self._stage_threads.append(t_exec)
            for i in range(self.encoder_threads):
                t_enc = threading.Thread(target=self._encoder_loop,
                                         daemon=True,
                                         name=f"serving-encoder-{i}")
                t_enc.start()
                self._threads.append(t_enc)
                self._stage_threads.append(t_enc)
        self._journal_thread = None
        if self._journal_fh is not None:
            self._journal_thread = threading.Thread(
                target=self._journal_loop, daemon=True,
                name="serving-journal")
            self._journal_thread.start()
            self._threads.append(self._journal_thread)
        if self.decoder is not None:
            self.decoder.start()
        if self.recorder is not None:
            # the retrospective plane's pump: one scrape per interval
            # feeding the TSDB, the SLO history, recording rules, the
            # anomaly detector, and (when configured) the .prom dumper
            self.recorder.start()
        if self.cpu_profiler is not None:
            # always-on: the CPU history must already be in the ring
            # when a detector fires — see docs/observability.md
            self.cpu_profiler.start()
        if self.incidents is not None:
            self.incidents.start()
        return self

    def stop(self, drain: bool = True, drain_timeout: float = 5.0):
        """Stop serving. With ``drain`` (the default), new requests are
        refused first (503 + Retry-After; ``/readyz`` flips to 503) and
        already-accepted work is given ``drain_timeout`` seconds to
        batch, commit, and reply before the listener goes down — a
        rolling restart loses no accepted request."""
        self._draining.set()
        if drain:
            # backlog(), not the ingress queue: a request the collector
            # has already popped but not yet dispatched is still
            # accepted work (it is only debited at dispatch), and the
            # pipelined plane keeps work in stage queues the ingress
            # queue never sees
            t_end = time.monotonic() + float(drain_timeout)
            while time.monotonic() < t_end and \
                    (self.backlog() > 0 or self._active_batches > 0):
                time.sleep(0.005)
        if self.decoder is not None:
            # the decode plane drains itself: in-slot requests would
            # take seconds to finish naturally, so the scheduler stops
            # its loop and resolves stragglers with 503s (a retry
            # lands on a live worker) — accepted-and-journaled replies
            # are already committed and replayable
            self.decoder.stop()
        self._stop.set()
        if self._frontend is None:
            self._server.shutdown()
            self._server.server_close()
        else:
            # stop taking NEW connections now (established keep-alive
            # connections keep being served so in-flight replies land);
            # the loops themselves stop below, after the pipeline flush
            # has posted every reply that will ever exist
            self._frontend.pause_accept()
        for t in self._threads:
            t.join(timeout=5)
        if any(t.is_alive() for t in getattr(self, "_stage_threads", [])):
            # a stage thread is stuck (hung model / slow device): the
            # flush's no-concurrent-consumer invariant doesn't hold, and
            # running the model from this thread too could interleave
            # two batches through a non-thread-safe transformer — leave
            # the queues to the daemon threads instead
            logger.warning(
                "pipeline threads did not stop in 5s; skipping the "
                "final stage-queue flush (stranded requests will 504 "
                "at request_timeout)")
        else:
            self._flush_pipeline()
        if self._frontend is not None:
            # everything that will ever call reply() has run: the loops
            # deliver what's queued, flush pending writes, close fds
            self._frontend.stop()
        # stop mirroring shadow traffic (the staged version, if any,
        # stays staged — a restart-less stop/start keeps it resident)
        self.versions.close()
        if self.capture is not None:
            # flush queued capture rows so a clean stop loses nothing
            self.capture.stop()
        if self.recorder is not None:
            # final tick: the terminal counters land in the store (and
            # on disk when dumping) before the process exits
            self.recorder.stop()
        if self.incidents is not None:
            # before the profiler: an in-flight capture still gets its
            # profile window from the (stopped but readable) ring
            self.incidents.stop()
        if self.cpu_profiler is not None:
            self.cpu_profiler.stop()
        if self._journal_fh is not None:
            jt = getattr(self, "_journal_thread", None)
            if jt is not None and jt.is_alive():
                # the writer is stuck mid-append (slow remote fs):
                # closing/draining here would interleave two writers on
                # one handle and corrupt journal lines — leak the handle
                # instead (the daemon thread dies with the process)
                logger.warning(
                    "journal writer did not stop in 5s; leaving the "
                    "journal handle to it (lines queued after this "
                    "point are dropped)")
                return
            self._drain_journal_queue()   # flush lines queued at stop
            try:
                self._journal_fh.close()
            except Exception:  # noqa: BLE001
                pass
            self._journal_fh = None

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class ServingCoordinator:
    """Driver-side service registry for multi-host serving.

    Parity: the coordination HttpServer in `HTTPSourceV2.scala:111-167` —
    workers POST ``{"host": ..., "port": ...}`` to ``/register``; clients
    GET ``/services`` for the worker list and round-robin between them.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 stale_after: Optional[float] = None,
                 tracer=None, frontend: str = "eventloop",
                 acceptors: int = 1, reuse_port: bool = False,
                 rollout_history: int = 32,
                 slo=None):
        # stale_after: drop workers not re-registered within this many
        # seconds — workers heartbeat (`python -m mmlspark_tpu.serving
        # worker` re-registers every REGISTER_INTERVAL), so dead pods
        # age out instead of accumulating forever. None = never expire.
        self._services: List[Dict[str, Any]] = []
        self._seen: Dict[Tuple[Any, Any], float] = {}
        self.stale_after = (float(stale_after)
                            if stale_after and stale_after > 0 else None)
        # the coordinator usually runs next to the driver/client, whose
        # OWN tracer holds the client side of a distributed trace (the
        # predict root + per-attempt egress spans); fleet_trace() folds
        # that store in as the "client" part, so merged trees include
        # the failover schedule, not just the worker fragments
        self.tracer = tracer if tracer is not None else TRACER
        self._lock = threading.Lock()
        # the current (or last) fleet rollout: POST /rollout starts
        # one RolloutOrchestrator at a time; GET /rollout reports it
        self._rollout: Optional[RolloutOrchestrator] = None
        self._rollout_lock = threading.Lock()
        # bounded ring of rollout runs (current included): GET
        # /rollouts lists every remembered run's state machine + phase
        # decisions, newest first — the audit trail an operator reads
        # after an auto-rollback they did not witness
        from collections import deque as _deque
        self._rollout_runs: "_deque[RolloutOrchestrator]" = _deque(
            maxlen=max(int(rollout_history), 1))
        # previous poll's merged counters: GET /fleet reports
        # rate()-style deltas alongside the lifetime totals (trend
        # needs two scrapes — the ROADMAP fleet-rate item)
        self._prev_totals: Optional[Tuple[float, Dict[str, int]]] = None
        # -- fleet SLO plane (on by default; ``slo=False`` disables):
        # the coordinator keeps a PRIVATE registry with per-worker
        # scrape/scrape-failure counters — every /fleet/alerts and
        # /fleet/slo request polls the workers, feeds the counters,
        # and evaluates one fleet_availability burn-rate policy over
        # them, so a dead worker burns error budget with per-worker
        # attribution until it ages out of stale_after AND the
        # windows. ``slo`` takes {"objective", "windows", "for_s",
        # "resolve_after_s", "webhook"} overrides.
        cfg = dict(slo) if isinstance(slo, dict) else {}
        self.registry = MetricsRegistry()
        self._m_polls = self.registry.counter(
            "fleet_worker_polls_total",
            "Worker scrape attempts by the coordinator's SLO plane.",
            labels=("worker",))
        self._m_poll_failures = self.registry.counter(
            "fleet_worker_poll_failures_total",
            "Worker scrapes that failed (dead/unreachable worker) — "
            "the fleet availability burn's bad-event counter.",
            labels=("worker",))
        self.slo: Optional[SLOEngine] = None
        if slo is not False:
            policy = SLOPolicy(
                name="fleet_availability", kind="availability",
                objective=float(cfg.get("objective", 0.999)),
                total_metric="fleet_worker_polls_total",
                bad_metric="fleet_worker_poll_failures_total",
                windows=(tuple(tuple(w) for w in cfg["windows"])
                         if "windows" in cfg else DEFAULT_WINDOWS),
                for_s=float(cfg.get("for_s", 0.0)),
                resolve_after_s=float(cfg.get("resolve_after_s",
                                              60.0)))
            self.slo = SLOEngine(
                self.registry, [policy],
                notifier=(AlertNotifier(cfg["webhook"])
                          if cfg.get("webhook") else None))
            self.slo.register_metrics(self.registry)
        coordinator = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                routed = coordinator._post_route(
                    self.path, self.rfile.read(length))
                if routed is None:
                    self.send_error(404)
                    return
                self._send(*routed)

            def do_GET(self):
                routed = coordinator._route(self.path)
                if routed is None:
                    self.send_error(404)
                    return
                self._send(*routed)

            def _send(self, status, body, ctype):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        # the coordinator rides the same socket edge as the workers:
        # fleet dashboards poll /fleet every few seconds, and with the
        # event-loop frontend (the default) those pollers hold ONE
        # keep-alive connection instead of a fresh handshake per scrape.
        # ``frontend="threaded"`` keeps the http.server plane selectable,
        # mirroring ServingServer's A/B switch.
        self.frontend = str(frontend)
        self._thread: Optional[threading.Thread] = None
        if self.frontend == "eventloop":
            self._server = None
            self._frontend: Optional[EventLoopFrontend] = \
                EventLoopFrontend(self, host, port,
                                  acceptors=acceptors,
                                  reuse_port=reuse_port,
                                  name="coordinator")
            self.host, self.port = (self._frontend.host,
                                    self._frontend.port)
        elif self.frontend == "threaded":
            self._frontend = None
            self._server = _Server((host, port), Handler)
            self.host, self.port = self._server.server_address[:2]
        else:
            raise ValueError(
                f"unknown frontend {frontend!r} "
                "(expected 'eventloop' or 'threaded')")

    # -- route table (both frontends serve exactly this) ---------------------

    def _post_route(self, path: str, body: bytes
                    ) -> Optional[Tuple[int, bytes, str]]:
        if path == "/rollout":
            # fleet rollout: stage everywhere -> (shadow) -> canary ->
            # flip or auto-rollback, orchestrated in the background;
            # poll GET /rollout for the state machine
            try:
                args = json.loads(body or b"{}")
                if not isinstance(args, dict) or not args.get("version"):
                    raise ValueError('need a JSON object with "version"')
            except ValueError as e:
                return (400, json.dumps({"error": str(e)}).encode(),
                        "application/json")
            try:
                run = self.rollout(**args)
            except (TypeError, ValueError) as e:
                # TypeError: unknown parameter; ValueError: a malformed
                # value (e.g. a zero-scale quantization config) — both
                # are client errors, refused before any worker is asked
                # to stage anything
                return (400, json.dumps(
                    {"error": f"bad rollout parameter: {e}"}).encode(),
                    "application/json")
            except RolloutError as e:
                return (409, json.dumps(
                    {"error": str(e),
                     "rollout": self.rollout_status()}).encode(),
                    "application/json")
            return (202, json.dumps(run.status()).encode(),
                    "application/json")
        if path not in ("/register", "/deregister"):
            return None
        try:
            info = json.loads(body)
        except ValueError:
            return 400, b'{"error": "invalid JSON"}', "application/json"
        key = (info.get("host"), info.get("port"))
        with self._lock:
            if path == "/register":
                # idempotent: a re-registering worker (periodic
                # heartbeat, or after a coordinator restart) replaces
                # its old entry instead of duplicating
                self._services = [
                    s for s in self._services
                    if (s.get("host"), s.get("port")) != key]
                self._services.append(info)
                self._seen[key] = time.monotonic()
            else:
                self._services = [
                    s for s in self._services
                    if (s.get("host"), s.get("port")) != key]
                self._seen.pop(key, None)
        return 200, b"{}", "application/json"

    def _route(self, path: str) -> Optional[Tuple[int, bytes, str]]:
        if path == "/fleet":
            # one-stop fleet observability: polls every live worker's
            # /stats + /metrics and serves the merged view (slowest
            # stage, widest bucket, totals)
            return (200, json.dumps(self.fleet_stats()).encode(),
                    "application/json")
        if path == "/fleet/metrics":
            return (200, self.fleet_metrics().encode(),
                    _METRICS_CONTENT_TYPE)
        if path == "/fleet/alerts":
            # the fleet alert roll-up: the coordinator's own
            # fleet_availability evaluation (dead workers burn with
            # per-worker attribution) plus every live worker's compact
            # alert view, worker-attributed
            return (200, json.dumps(self.fleet_alerts()).encode(),
                    "application/json")
        if path == "/fleet/slo":
            return (200, json.dumps(self.fleet_slo()).encode(),
                    "application/json")
        if path == "/fleet/traces":
            # every worker's retained slow/error captures in one
            # listing (concurrent polls; a dead worker degrades to an
            # error entry, never a 5xx here)
            return (200, json.dumps(self.fleet_traces()).encode(),
                    "application/json")
        if path == "/fleet/incidents":
            # the fleet postmortem inventory: every worker's captured
            # incident bundles, worker-attributed, newest first — one
            # fleet-wide regression reads as one correlated evidence
            # set (fetch a bundle from its worker via
            # /incidents/<id>/<artifact>; tools/trace_dump.py
            # --incidents --fetch does this)
            return (200, json.dumps(self.fleet_incidents()).encode(),
                    "application/json")
        if path.startswith("/fleet/trace/"):
            raw, _, query = path[len("/fleet/trace/"):].partition("?")
            # same charset as trace ids: the id is spliced into
            # per-worker URLs and must not smuggle a path/query
            tid = "".join(ch for ch in raw[:128]
                          if ch.isalnum() or ch in "._-")
            merged, errors = self.fleet_trace(tid)
            if merged is None:
                body = json.dumps(
                    {"error": "trace not retained by any worker "
                              "(fast + ok traces are tail-dropped)",
                     "trace_id": tid,
                     "workers_failed": errors}).encode()
                return 404, body, "application/json"
            if "format=perfetto" in query:
                # per-worker lanes: each process renders as its own
                # pid with named process_name metadata
                body = json.dumps(to_perfetto(merged)).encode()
            else:
                out = {k: merged[k] for k in
                       ("trace_id", "root", "route", "duration_ms",
                        "status", "reason", "captured_at", "n_spans",
                        "workers")}
                out["tree"] = span_tree(merged)
                out["workers_failed"] = errors
                body = json.dumps(out).encode()
            return 200, body, "application/json"
        if path.startswith("/fleet/query"):
            # the one-stop fleet view over the retrospective plane:
            # /fleet/query and /fleet/query_range fan the expression
            # out to every worker's TSDB and merge the answers under
            # worker=host:port labels (same query grammar; dead
            # workers degrade to error entries, never a 5xx)
            sub = path[len("/fleet"):]
            base = sub.split("?", 1)[0]
            if base not in ("/query", "/query_range"):
                return None
            return (200, json.dumps(self.fleet_query(sub)).encode(),
                    "application/json")
        if path == "/rollout":
            return (200, json.dumps(self.rollout_status()).encode(),
                    "application/json")
        if path == "/rollouts":
            # the bounded history ring: past runs + the current one,
            # newest first, each with its phase decisions (canary
            # verdict, failure detail, per-worker staging states)
            return (200, json.dumps(self.rollout_history()).encode(),
                    "application/json")
        if path == "/services":
            with self._lock:
                self._prune_stale_locked()
                body = json.dumps(self._services).encode()
            return 200, body, "application/json"
        return None

    # -- event-loop frontend protocol ----------------------------------------

    def handle_request(self, method: str, path: str, headers,
                       body: bytes, reply) -> bool:
        """The :class:`EventLoopFrontend` application protocol. Every
        coordinator route answers synchronously — registry mutations
        are in-memory, and the fleet polls run on the loop thread (the
        coordinator is a control-plane process; a multi-second fleet
        poll stalling its own accept loop is the same behavior the
        single-threaded pollers already observe)."""
        if method == "POST":
            routed = self._post_route(path, body)
        elif method == "GET":
            routed = self._route(path)
        else:
            return False
        if routed is None:
            return False
        status, rbody, ctype = routed
        reply(status, rbody, ctype=ctype)
        return True

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingCoordinator":
        if self._frontend is not None:
            self._frontend.start()
            return self
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._frontend is not None:
            self._frontend.stop()
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def _prune_stale_locked(self) -> None:
        if self.stale_after is None:
            return
        horizon = time.monotonic() - self.stale_after
        self._services = [
            s for s in self._services
            if self._seen.get((s.get("host"), s.get("port")), 0) >= horizon]
        # drop the timestamps too: months of rolling pod redeploys must
        # not accumulate one _seen entry per worker IP ever seen
        self._seen = {k: t for k, t in self._seen.items() if t >= horizon}

    def services(self) -> List[Dict[str, Any]]:
        with self._lock:
            self._prune_stale_locked()
            return list(self._services)

    # -- fleet rollout orchestration -----------------------------------------

    def rollout(self, version: str, **kwargs) -> RolloutOrchestrator:
        """Start one fleet rollout (see
        :class:`~mmlspark_tpu.serving.rollout.RolloutOrchestrator` for
        the phases and knobs). One at a time: a second call while one
        is running raises :class:`RolloutError` (HTTP callers get a
        409)."""
        with self._rollout_lock:
            if self._rollout is not None and self._rollout.running:
                raise RolloutError(
                    f"a rollout to {self._rollout.version!r} is "
                    f"already {self._rollout.state}")
            run = RolloutOrchestrator(self, version, **kwargs)
            self._rollout = run
            # remembered from the start: a run that dies mid-phase is
            # exactly the one the history must still show
            self._rollout_runs.append(run)
            run.start()
            return run

    def rollout_status(self) -> Dict[str, Any]:
        with self._rollout_lock:
            if self._rollout is None:
                return {"state": "idle"}
            return self._rollout.status()

    def rollout_history(self) -> Dict[str, Any]:
        """Every remembered rollout run (bounded ring, newest first):
        final state, phase decision, failure detail, per-worker
        staging/flip bookkeeping — ``RolloutOrchestrator.status()``
        verbatim per run. Live runs report their current phase."""
        with self._rollout_lock:
            runs = [r.status() for r in reversed(self._rollout_runs)]
        return {"capacity": self._rollout_runs.maxlen,
                "n_runs": len(runs), "rollouts": runs}

    # -- fleet-level stats aggregation ---------------------------------------

    def _poll_workers(self, path: str, timeout: float
                      ) -> List[Tuple[str, Any, Optional[str]]]:
        """``(worker_key, parsed_or_text, error)`` per registered
        worker; a dead worker contributes its error instead of failing
        the whole fleet view. Polls run CONCURRENTLY so k unreachable
        pods cost one connect timeout, not k of them — a fleet view
        must stay fast exactly when workers are failing."""
        import requests
        from concurrent.futures import ThreadPoolExecutor

        def poll(s):
            wk = f"{s.get('host')}:{s.get('port')}"
            try:
                r = requests.get(f"http://{wk}{path}", timeout=timeout)
                r.raise_for_status()
                json_paths = ("/stats", "/traces", "/trace/",
                              "/alerts", "/slo", "/query",
                              "/incidents")
                return (wk, r.json() if path.startswith(json_paths)
                        else r.text, None)
            except Exception as e:  # noqa: BLE001 — worker down/old
                return (wk, None, str(e))

        services = self.services()
        if not services:
            return []
        with ThreadPoolExecutor(
                max_workers=min(len(services), 16)) as pool:
            return list(pool.map(poll, services))

    def fleet_stats(self, timeout: float = 5.0) -> Dict[str, Any]:
        """Poll every worker's ``/stats`` and merge them into one fleet
        view — the single place a fleet's slowest stage is visible
        (closing the ROADMAP item): per-stage timings are combined
        (counts and totals sum, maxes max), and ``slowest_stage`` names
        the stage with the highest merged mean AND the worker whose
        per-worker mean for it is worst. ``widest_bucket`` is the
        largest dispatch shape any worker compiled.
        """
        per_worker: Dict[str, Any] = {}
        merged: Dict[str, Dict[str, float]] = {}
        totals = {k: 0 for k in (
            "n_requests", "n_batches", "n_recompiles", "queue_depth",
            "inflight_batches")}
        widest = 0
        worst: Dict[str, Tuple[float, str]] = {}   # stage -> (mean, worker)
        n_live = 0
        for wk, stats, err in self._poll_workers("/stats", timeout):
            if err is not None:
                per_worker[wk] = {"error": err}
                continue
            n_live += 1
            per_worker[wk] = stats
            for k in totals:
                totals[k] += int(stats.get(k) or 0)
            sizes = stats.get("dispatch_sizes") or []
            widest = max(widest, max(sizes, default=0))
            for stage, t in (stats.get("stage_timings") or {}).items():
                m = merged.setdefault(stage, {"count": 0, "total_ms": 0.0,
                                              "max_ms": 0.0})
                m["count"] += t.get("count", 0)
                m["total_ms"] += t.get("total_ms", 0.0)
                m["max_ms"] = max(m["max_ms"],
                                  t.get("max_ms", t.get("last_ms", 0.0)))
                mean = t.get("mean_ms", 0.0)
                if mean > worst.get(stage, (-1.0, ""))[0]:
                    worst[stage] = (mean, wk)
        for m in merged.values():
            m["mean_ms"] = round(m["total_ms"] / m["count"], 4) \
                if m["count"] else 0.0
            m["total_ms"] = round(m["total_ms"], 3)
        slowest = None
        if merged:
            stage = max(merged, key=lambda s: merged[s]["mean_ms"])
            slowest = {"stage": stage,
                       "mean_ms": merged[stage]["mean_ms"],
                       "max_ms": merged[stage]["max_ms"],
                       "worker": worst[stage][1],
                       "worker_mean_ms": round(worst[stage][0], 4)}
        # rate()-style deltas between this poll and the previous one:
        # the merged counters are lifetime totals, so trend needs two
        # scrapes — held here so ANY /fleet consumer gets rates for
        # free. Counters only (queue_depth/inflight are gauges, a delta
        # of those is noise); clamped at 0 so a worker restart's
        # counter reset reads as "no traffic", not negative traffic.
        # The baseline advances at most once per second: a second
        # consumer (an operator's curl next to the dashboard's poll)
        # must not shrink everyone's window to near-zero, where the
        # quantized counter deltas read as spikes. Rates stay correct
        # over whatever interval is reported — rate_interval_s says
        # which.
        now = time.monotonic()
        with self._lock:
            prev = self._prev_totals
            if prev is None or now - prev[0] >= 1.0:
                self._prev_totals = (now, dict(totals))
        rates: Optional[Dict[str, float]] = None
        interval = None
        if prev is not None and now > prev[0]:
            interval = round(now - prev[0], 3)
            rates = {k: round(max(totals[k] - prev[1].get(k, 0), 0)
                              / (now - prev[0]), 3)
                     for k in ("n_requests", "n_batches", "n_recompiles")}
        # the fleet's model-version set (RESPONDING workers only): a
        # completed rollout reads as one coherent version fleet-wide —
        # the kill-mid-rollout drill's acceptance signal
        versions = sorted({str(s["model_version"])
                           for s in per_worker.values()
                           if isinstance(s, dict)
                           and s.get("model_version")})
        # per-tenant ledgers merged fleet-wide: counters sum, in-flight
        # sums (a gauge, but per-tenant concurrency IS additive across
        # workers), priority/quota config taken from the first worker
        # that names the tenant. None when no responding worker runs a
        # tenant registry.
        tenants: Dict[str, Dict[str, Any]] = {}
        for s in per_worker.values():
            if not isinstance(s, dict):
                continue
            ten = (s.get("tenancy") or {}).get("tenants") or []
            for row in ten:
                tid = str(row.get("id", ""))
                if not tid:
                    continue
                agg = tenants.get(tid)
                if agg is None:
                    tenants[tid] = dict(row)
                    continue
                for k, v in row.items():
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool) \
                            and k not in ("rate_per_s", "burst",
                                          "max_inflight",
                                          "max_cache_pages", "weight"):
                        agg[k] = agg.get(k, 0) + v
        return {"n_workers": len(per_worker), "n_responding": n_live,
                "totals": totals, "rates_per_s": rates,
                "rate_interval_s": interval, "stage_timings": merged,
                "slowest_stage": slowest, "widest_bucket": widest,
                "model_versions": versions,
                "version_coherent": len(versions) <= 1,
                "tenants": (sorted(tenants.values(),
                                   key=lambda r: str(r.get("id", "")))
                            if tenants else None),
                "workers": per_worker}

    def fleet_metrics(self, timeout: float = 5.0) -> str:
        """Poll every worker's ``/metrics`` and serve ONE merged
        exposition: sample values summed per (name, labels) — exact for
        counters and histogram buckets, fleet totals for gauges (see
        :func:`mmlspark_tpu.core.telemetry.merge_prometheus`). Scraping
        the coordinator thus covers the fleet with one target.

        Scrapes ``?scope=server`` (each worker's own registry): the
        process-wide REGISTRY would be summed once per worker when
        several workers share a process, double-counting its families —
        process-level metrics stay on the individual workers'
        unscoped ``/metrics``.

        Every registered worker contributes a
        ``serving_worker_up{worker=...}`` sample (1 scraped, 0 failed):
        when a worker drops out, the merged counters dip (Prometheus
        reads that as a counter reset), and this is the signal that the
        dip means "incomplete sum", not "restarted fleet"."""
        polls = self._poll_workers("/metrics?scope=server", timeout)
        merged = merge_prometheus(
            body for _, body, err in polls if err is None)
        for wk, _, err in polls:
            merged[("serving_worker_up", (("worker", wk),))] = \
                0.0 if err is not None else 1.0
        # the coordinator stamps its OWN build identity into the fleet
        # exposition (frontend="coordinator"), so a scrape of the one
        # fleet target also answers "what is the control plane running"
        from mmlspark_tpu.core.telemetry import build_info
        info = dict(build_info())
        info["frontend"] = "coordinator"
        merged[("serving_build_info",
                tuple(sorted(info.items())))] = 1.0
        return render_samples(merged)

    # -- fleet SLO roll-up ---------------------------------------------------

    def fleet_alerts(self, timeout: float = 5.0) -> Dict[str, Any]:
        """The fleet alert view: poll every worker's ``GET /alerts``
        (each poll feeds the coordinator's per-worker scrape counters
        — the fleet_availability policy's total/bad events), evaluate
        the coordinator's own engine, and report both. ``firing``
        totals the fleet policy and every responding worker's count;
        a dead worker appears as an ``{"error": ...}`` entry AND as
        availability burn with its ``worker=host:port`` attribution."""
        polls = self._poll_slo("alerts", timeout)
        fleet_view = None
        firing = 0
        if self.slo is not None:
            self.slo.evaluate()
            fleet_view = self.slo.alerts()
            firing += int(fleet_view.get("firing", 0))
        workers: Dict[str, Any] = {}
        for wk, body, err in polls:
            if err is not None:
                workers[wk] = {"error": err}
                continue
            workers[wk] = body
            if isinstance(body, dict):
                firing += int(body.get("firing", 0))
        return {"firing": firing, "fleet": fleet_view,
                "workers": workers}

    def fleet_slo(self, timeout: float = 5.0) -> Dict[str, Any]:
        """The full fleet burn-rate report: the coordinator policy's
        evaluation plus every worker's ``GET /slo`` report verbatim,
        worker-attributed."""
        polls = self._poll_slo("slo", timeout)
        fleet_view = self.slo.evaluate() if self.slo is not None \
            else None
        workers = {wk: (body if err is None else {"error": err})
                   for wk, body, err in polls}
        firing = 0
        if self.slo is not None:
            firing += len(self.slo.firing())
        return {"firing": firing, "fleet": fleet_view,
                "workers": workers}

    def _poll_slo(self, mode: str, timeout: float
                  ) -> List[Tuple[str, Any, Optional[str]]]:
        """Poll every worker's ``/alerts`` or ``/slo``, charging the
        per-worker scrape counters the fleet availability policy
        evaluates (success AND failure both count a poll; only
        failures count bad events)."""
        polls = self._poll_workers(f"/{mode}", timeout)
        for wk, _, err in polls:
            self._m_polls.labels(wk).inc()
            if err is not None:
                self._m_poll_failures.labels(wk).inc()
        return polls

    def fleet_query(self, path_with_query: str, timeout: float = 5.0
                    ) -> Dict[str, Any]:
        """Fan one ``/query`` or ``/query_range`` (path WITH its query
        string) out to every worker's TSDB and merge the per-worker
        answers: every result/series gains a ``worker: host:port``
        label, so a fleet-wide ``rate(serving_requests_total[60s])``
        comes back as one list with per-worker attribution. A dead
        worker (or a worker-side 400) contributes an ``errors`` entry
        instead of failing the view; the query echo (expr/at or
        start/end/step) is taken from the first responding worker."""
        merged: List[Dict[str, Any]] = []
        errors: Dict[str, str] = {}
        echo: Dict[str, Any] = {}
        key = None
        polls = self._poll_workers(path_with_query, timeout)
        for wk, body, err in polls:
            if err is not None or not isinstance(body, dict):
                errors[wk] = err or "malformed worker response"
                continue
            if key is None:
                key = "series" if "series" in body else "results"
                echo = {k: body[k] for k in
                        ("expr", "at", "start", "end", "step")
                        if k in body}
            for row in body.get(key) or []:
                entry = dict(row)
                entry["labels"] = dict(entry.get("labels") or {})
                entry["labels"]["worker"] = wk
                merged.append(entry)
        out = dict(echo)
        out.update({"n_workers": len(polls),
                    "n_responding": len(polls) - len(errors),
                    "errors": errors,
                    (key or "results"): merged})
        return out

    # -- fleet-level trace aggregation ---------------------------------------

    def fleet_traces(self, timeout: float = 5.0) -> Dict[str, Any]:
        """Every worker's retained-trace listing in one place: polls
        each worker's ``GET /traces`` concurrently and flattens the
        summaries with per-worker attribution (``worker: host:port``
        on every entry), slowest first. A dead worker contributes an
        entry in ``errors`` instead of failing the view — exactly when
        workers are dying is when an operator reads this."""
        traces: List[Dict[str, Any]] = []
        errors: Dict[str, str] = {}
        polls = self._poll_workers("/traces", timeout)
        for wk, items, err in polls:
            if err is not None:
                errors[wk] = err
                continue
            for t in items:
                entry = dict(t)
                entry["worker"] = wk
                traces.append(entry)
        traces.sort(key=lambda t: -t.get("duration_ms", 0.0))
        return {"n_workers": len(polls),
                "n_responding": len(polls) - len(errors),
                "traces": traces, "errors": errors}

    def fleet_incidents(self, timeout: float = 5.0) -> Dict[str, Any]:
        """Every worker's incident-bundle inventory in one place:
        polls each worker's ``GET /incidents`` concurrently and
        flattens the listings with per-worker attribution, newest
        first. A worker with incident capture disabled (its 404) or a
        dead worker contributes an ``errors`` entry instead of failing
        the view."""
        incidents: List[Dict[str, Any]] = []
        errors: Dict[str, str] = {}
        polls = self._poll_workers("/incidents", timeout)
        for wk, payload, err in polls:
            if err is not None:
                errors[wk] = err
                continue
            for inc in payload.get("incidents", []):
                entry = dict(inc)
                entry["worker"] = wk
                incidents.append(entry)
        incidents.sort(key=lambda i: -(i.get("at_unix") or 0.0))
        return {"n_workers": len(polls),
                "n_responding": len(polls) - len(errors),
                "incidents": incidents, "errors": errors}

    def fleet_trace(self, trace_id: str, timeout: float = 5.0
                    ) -> Tuple[Optional[Dict[str, Any]], Dict[str, str]]:
        """Fetch-and-merge one distributed trace: every worker's
        retained capture of ``trace_id`` (``GET /trace/<id>?format=raw``,
        polled concurrently) plus this process's own tracer store (the
        ``client`` part — the driver-side predict root and failover
        egress spans), stitched by
        :func:`mmlspark_tpu.core.tracing.merge_traces` so worker roots
        nest under the caller's egress spans. Returns ``(merged,
        errors)``; merged is None when no part retained the trace. A
        404 from a worker means "not retained there" — normal
        tail-capture behavior, not an error."""
        import requests
        from concurrent.futures import ThreadPoolExecutor

        def poll(s):
            wk = f"{s.get('host')}:{s.get('port')}"
            try:
                r = requests.get(
                    f"http://{wk}/trace/{trace_id}?format=raw",
                    timeout=timeout)
                if r.status_code == 404:
                    return (wk, None, None)
                r.raise_for_status()
                return (wk, r.json(), None)
            except Exception as e:  # noqa: BLE001 — worker down/old
                return (wk, None, str(e))

        services = self.services()
        polls: List[Tuple[str, Any, Optional[str]]] = []
        if services:
            with ThreadPoolExecutor(
                    max_workers=min(len(services), 16)) as pool:
                polls = list(pool.map(poll, services))
        parts: List[Tuple[str, Dict[str, Any]]] = []
        local = self.tracer.get_trace(trace_id) \
            if self.tracer is not None else None
        if local is not None:
            parts.append(("client", local))
        parts.extend((wk, tr) for wk, tr, err in polls
                     if tr is not None)
        errors = {wk: err for wk, _, err in polls if err is not None}
        if not parts:
            return None, errors
        return merge_traces(parts), errors

    @staticmethod
    def register_worker(coordinator_url: str, host: str, port: int):
        import requests
        requests.post(f"{coordinator_url}/register",
                      json={"host": host, "port": port}, timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class ServingClient:
    """Round-robin client over a coordinator's worker list, with
    breaker-guarded failover and budgeted idempotent retries.

    Every logical request carries a generated ``X-Request-Id``; a retry
    (after a dropped connection, a 5xx, or worker death) reuses the id,
    so a worker that already computed the reply returns its journaled
    copy instead of re-running inference (see :class:`ServingServer`).
    Parity: the reference's clients round-robin the `/services` list of
    `DriverServiceUtils` (`HTTPSourceV2.scala:111`).

    Resilience wiring:

    * a :class:`CircuitBreaker` per worker (``breakers``): a worker that
      keeps failing is skipped without a connect attempt until its
      reset timeout (on the injected clock) elapses;
    * a :class:`RetryPolicy` bounds the TOTAL failover/retry schedule
      per logical request (attempts + elapsed-time budget, jittered
      backoff, 429 ``Retry-After`` honored);
    * ``timeout_budget`` puts a :class:`Deadline` on the whole call,
      propagated to workers via ``X-Deadline-Ms`` so the server also
      stops spending on it (dropped before dispatch / commit).

    Dedup scope: the reply journal lives in each worker, so replay
    dedup is **per worker** — a retry that lands on a *different* worker
    re-runs inference there. To keep the common slow-worker case
    exactly-once, a ``requests.Timeout`` is retried once on the SAME
    worker (whose journal can replay the reply) before failing over;
    only connection failures (worker dead) fail over immediately, where
    re-execution on a new worker is the intended at-least-once fallback.
    """

    def __init__(self, coordinator_url: str, api_path: str = "/predict",
                 timeout: float = 15.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 breakers: Optional[BreakerBoard] = None,
                 tracer=None,
                 api_key: Optional[str] = None,
                 clock: Clock = SYSTEM_CLOCK):
        self.coordinator_url = coordinator_url.rstrip("/")
        self.api_path = api_path
        self.timeout = timeout
        # tenant identity: sent as X-Api-Key on every attempt so a
        # tenancy-enabled fleet (docs/serving.md "Tenancy & overload
        # control") bills the whole failover schedule to one tenant
        self.api_key = api_key
        # spans record through this tracer (None = the ambient one at
        # call time, falling back to the process TRACER): one "predict"
        # root per logical request with an egress child per attempt,
        # whose id travels as X-Parent-Span-Id so every worker-side
        # tree stitches under the failover schedule
        self.tracer = tracer
        self.clock = clock
        self.policy = retry_policy or RetryPolicy(
            max_attempts=6, base=0.02, cap=0.5, clock=clock)
        self.breakers = breakers or BreakerBoard(
            clock=clock, failure_threshold=3, reset_timeout=5.0)
        self.n_failovers = 0
        self._workers: List[str] = []
        self._dead: set = set()
        self._rr = 0
        # one pooled session: every attempt rides a kept-alive
        # connection to its worker (urllib3's pool is thread-safe, so
        # concurrent predict() calls share it) — against an event-loop
        # worker each burst costs one handshake, not one per request
        import requests as _requests
        self._http = _requests.Session()
        self.refresh()

    def refresh(self) -> List[str]:
        import requests
        services = requests.get(self.coordinator_url + "/services",
                                timeout=self.timeout).json()
        self._workers = [f"http://{s['host']}:{s['port']}{self.api_path}"
                         for s in services]
        self._dead.clear()
        return list(self._workers)

    def _pick(self) -> str:
        """Next worker: alive, breaker-admitted, round-robin. Falls back
        to breaker-refused workers rather than failing a request that
        still has budget (availability over protection — the breakers
        exist to stop *hammering*, not to refuse the only option)."""
        alive = [w for w in self._workers if w not in self._dead] \
            or self.refresh()
        if not alive:
            raise RuntimeError("no serving workers registered")
        for _ in range(len(alive)):
            url = alive[self._rr % len(alive)]
            self._rr += 1
            if self.breakers.get(url).allow():
                return url
        url = alive[self._rr % len(alive)]
        self._rr += 1
        return url

    def predict(self, payload: Any, request_id: Optional[str] = None,
                timeout_budget: Optional[float] = None) -> Any:
        rid = request_id or uuid.uuid4().hex
        # one trace id per LOGICAL request (adopting the ambient one
        # when the caller is already inside a trace): every failover/
        # retry attempt carries the same id, so the whole schedule is
        # one line-set in worker logs
        trace = current_trace_id() or new_trace_id()
        tracer = self.tracer if self.tracer is not None \
            else ambient_tracer()
        # one client-side ROOT span over the whole failover schedule:
        # each wire attempt nests under it, and every worker-side tree
        # parents under those attempts in the merged distributed trace
        # (GET /fleet/trace/<id>). Tail capture follows the tracer's
        # "serving_client" route threshold.
        root = tracer.start("predict", trace_id=trace,
                            route="serving_client", rid=rid)
        status = "error"
        try:
            out = self._predict_attempts(payload, rid, trace,
                                         timeout_budget, tracer, root)
            status = "ok"
            return out
        except DeadlineExceeded:
            status = "deadline"
            raise
        finally:
            tracer.finish(root, status=status)

    def _predict_attempts(self, payload: Any, rid: str, trace: str,
                          timeout_budget: Optional[float],
                          tracer, root) -> Any:
        import requests
        deadline = (Deadline(timeout_budget, clock=self.clock)
                    if timeout_budget is not None else None)
        sched = self.policy.schedule(deadline)
        last_err: Optional[Exception] = None
        url: Optional[str] = None
        while True:
            if deadline is not None and deadline.expired:
                raise DeadlineExceeded(
                    f"request {rid} ran out of budget") from last_err
            prev, url = url, self._pick()
            if prev is not None and url != prev:
                self.n_failovers += 1
            breaker = self.breakers.get(url)
            retry_after = None
            headers = {"X-Request-Id": rid, TRACE_HEADER: trace}
            if self.api_key is not None:
                headers["X-Api-Key"] = self.api_key
            if deadline is not None:
                headers[Deadline.HEADER] = deadline.to_header()
            # attempt 0, plus one same-worker retry after a timeout: the
            # worker may be alive-but-slow, and only ITS journal can
            # replay the reply without re-running inference
            for attempt in range(2):
                # one egress span per wire attempt; its id travels as
                # X-Parent-Span-Id so the worker's root "request" span
                # parents under THIS attempt, not just the same trace
                att = tracer.start("http_egress", parent=root,
                                   host=url)
                headers[PARENT_SPAN_HEADER] = \
                    format_span_id(att.span_id)
                try:
                    r = self._http.post(url, json=payload,
                                        timeout=self.timeout,
                                        headers=headers)
                except requests.ConnectionError as e:
                    tracer.finish(att, status="error")
                    last_err = e
                    breaker.record_failure()
                    self._dead.add(url)  # dead: fail over immediately
                    break
                except requests.Timeout as e:
                    tracer.finish(att, status="timeout")
                    last_err = e
                    continue
                except BaseException:
                    # anything else (mid-body resets, redirect loops,
                    # bad URLs) propagates to the caller — but the
                    # attempt span must still land in the recorder, or
                    # the captured trace would omit the one attempt
                    # that explains the failure
                    tracer.finish(att, status="error")
                    raise
                tracer.finish(
                    att,
                    status="shed" if r.status_code == 429 else
                    "error" if r.status_code >= 400 else "ok",
                    status_code=r.status_code)
                if r.status_code == 429 or r.status_code >= 500:
                    # shed/erroring worker: not dead, but this request
                    # should back off and go elsewhere. 504 is excluded
                    # from breaker health: a deadline-expired reply
                    # says the REQUEST's budget was too tight, not that
                    # the worker is sick — tight-budget clients must
                    # not open circuits against healthy workers
                    if r.status_code >= 500 and r.status_code != 504:
                        breaker.record_failure()
                    retry_after = r.headers.get("Retry-After")
                    last_err = requests.HTTPError(
                        f"{r.status_code} from {url}", response=r)
                    break
                breaker.record_success()
                r.raise_for_status()    # other 4xx: caller's error
                return r.json()
            else:
                # both same-worker attempts timed out
                breaker.record_failure()
                self._dead.add(url)
            if sched.give_up(retry_after):
                raise RuntimeError(
                    f"serving workers unreachable after "
                    f"{sched.attempt} attempts") from last_err
