"""Zero-downtime model rollout: versioned hot-swap, canary + shadow.

The worker-side primitive is :class:`ModelVersionManager` — every
:class:`~mmlspark_tpu.serving.server.ServingServer` owns one. A model
version moves through a state machine::

    load -> verify -> warmup -> staged -> (flip) -> active
                                   \\-> aborted        \\-> previous
    any step may end in: error                          \\-> (rollback)

* **load** — the next version is constructed in the background from a
  checkpoint directory (any persisted stage, ``PipelineStage.load``)
  or handed in as an in-memory model (tests, in-process operators);
  live traffic keeps dispatching on the active version throughout.
* **verify** — checkpoint-path versions must pass **strict** digest
  verification (:func:`mmlspark_tpu.io.checkpoint.verify_digest`)
  before anything else touches them: a truncated, bit-rotted, or
  digest-less checkpoint is never flip-eligible.
* **warmup** — every shape bucket the server dispatches is pushed
  through the NEW version's ``transform`` pre-flip (the same synthetic
  frames :meth:`ServingServer.warmup` builds), so a jitted model's
  compiles all land before the flip and steady-state traffic never
  retraces afterwards (``post_flip_recompiles`` stays 0).
* **flip** — one reference assignment under the manager lock. The
  executor snapshots the active version once per batch, so the flip
  lands exactly *between* batches: a batch dispatched on v1 commits on
  v1, the next batch dispatches on v2, and nothing is dropped, errored,
  or recompiled. Journaled replies are version-pinned by construction —
  a request journaled under v1 and retried after the flip returns the
  v1-committed reply verbatim (replay beats re-dispatch).
* **rollback** — the previous version is kept resident (weights and
  compiled executables both), so rolling back is another between-batch
  reference flip, not a reload.

**Shadow traffic**: while a version is staged, a sampled fraction of
live batches is mirrored through it on a dedicated shadow thread — the
client reply always comes from the active version; the staged version's
outputs are compared column-by-column and latency/mismatch counters
exported (``serving_shadow_*``). Backpressure-safe: shadowing drops
batches rather than ever delaying the live pipeline.

The fleet-side orchestration is :class:`RolloutOrchestrator`, driven by
``POST /rollout`` on the :class:`ServingCoordinator`: stage everywhere,
optionally observe shadow traffic, flip ONE canary worker, compare its
error-rate and dispatch-latency p95 deltas against the rest of the
fleet over the same window (from the workers' existing ``/status``
counters and ``/metrics`` histograms), then either flip the remainder
or auto-rollback the canary. Workers that die mid-rollout are skipped —
survivors finish the flip (the chaos drill in
``tools/chaos_serving.py`` proves it) — but a worker that *reports* a
staging error (corrupt checkpoint, failed warmup) fails the whole
rollout before any flip.

Fault points for chaos tests (``testing/faults``): a manager
constructed with a ``fault_plan`` consults the sites ``rollout_load``,
``rollout_verify``, ``rollout_quant_verify`` (the int8-compute parity
gate), ``rollout_warmup``, and ``rollout_flip``.

See docs/serving.md "Zero-downtime rollout".
"""

from __future__ import annotations

import threading
import time
from queue import Empty, Full, Queue
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.logs import get_logger
from mmlspark_tpu.core.telemetry import quantile_from_buckets

logger = get_logger("serving.rollout")

__all__ = ["ModelVersion", "ModelVersionManager", "RolloutError",
           "RolloutOrchestrator"]


class RolloutError(RuntimeError):
    """An illegal rollout transition (flip without a staged version,
    rollback without a previous one, ...)."""


class ModelVersion:
    """One resident model version and its rollout lifecycle state."""

    __slots__ = ("version", "model", "source", "state", "error",
                 "digest_verified", "warmed_buckets", "shapes_seen",
                 "n_post_flip_recompiles", "created_unix", "flipped_unix",
                 "quantization", "quant_parity")

    def __init__(self, version: str, model: Any = None,
                 source: Optional[str] = None, state: str = "loading",
                 quantization=None):
        self.version = version
        self.model = model
        self.source = source
        self.state = state
        self.error: Optional[str] = None
        #: the version's quantized-wire config (serving/quant.py):
        #: the dispatch stage casts assembled frames to its wire dtype
        #: and the model dequantizes on device — carried on the
        #: VERSION so stage -> verify -> warmup -> flip keeps one
        #: coherent wire contract per model (None = the f32 plane)
        self.quantization = quantization
        #: True = strict digest verification passed; None = not
        #: applicable (in-memory model handed in by a trusted caller)
        self.digest_verified: Optional[bool] = None
        self.warmed_buckets: List[int] = []
        #: dispatch-shape keys THIS version has compiled (warmup,
        #: shadow, and live dispatch all record here)
        self.shapes_seen: set = set()
        #: shapes first seen on the live path AFTER this version went
        #: active — the hot-swap contract requires this to stay 0
        self.n_post_flip_recompiles = 0
        self.created_unix = time.time()
        self.flipped_unix: Optional[float] = None
        #: the int8-compute staging gate's evidence (None until a
        #: compute-quantized stage verifies): NNModel.
        #: quant_parity_report's row-wise parity dict
        self.quant_parity: Optional[Dict[str, Any]] = None

    def record_shape(self, key) -> None:
        """Count a dispatch shape against this version (GIL-atomic set
        add; dispatch is single-threaded per plane). A shape not warmed
        pre-flip that shows up on the live path after the flip is a
        post-flip recompile — the number the hot-swap bench gates on."""
        if key not in self.shapes_seen:
            self.shapes_seen.add(key)
            if self.flipped_unix is not None:
                self.n_post_flip_recompiles += 1

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": self.version,
            "state": self.state,
            "source": self.source,
            "quantization": (self.quantization.to_dict()
                             if self.quantization is not None else None),
            "digest_verified": self.digest_verified,
            "warmed_buckets": list(self.warmed_buckets),
            "n_shapes": len(self.shapes_seen),
            "post_flip_recompiles": self.n_post_flip_recompiles,
            "quant_parity": self.quant_parity,
            "created_unix": round(self.created_unix, 3),
            "flipped_unix": (round(self.flipped_unix, 3)
                             if self.flipped_unix is not None else None),
            "error": self.error,
        }


class ModelVersionManager:
    """Versioned hot-swap for one :class:`ServingServer`.

    Owns the ``active`` version the dispatch stage reads (one attribute
    read per batch — the flip is a reference assignment, atomic under
    the GIL and taken under the manager lock), at most one ``staged``
    next version, and the ``previous`` version kept resident for
    instant rollback.
    """

    #: states from which a staged version may be replaced by a new stage
    _REPLACEABLE = ("error", "aborted")

    def __init__(self, server, model: Any, version: str = "v1",
                 verify_checkpoints: bool = True,
                 fault_plan=None,
                 shadow_queue_depth: int = 4,
                 quantization=None):
        self._server = server
        self.verify_checkpoints = bool(verify_checkpoints)
        self.fault_plan = fault_plan
        self._lock = threading.RLock()
        self._active = ModelVersion(version, model=model, state="active",
                                    quantization=quantization)
        self._staged: Optional[ModelVersion] = None
        self._previous: Optional[ModelVersion] = None
        self.n_flips = 0
        self.n_rollbacks = 0
        self.n_rollout_failures = 0
        # -- shadow traffic: a sampled fraction of live batches is
        # mirrored through the staged version on THIS thread, never the
        # pipeline's. The queue is shallow and non-blocking on purpose:
        # when the shadow can't keep up, batches are dropped (counted),
        # and the live path never waits.
        self.shadow_fraction = 0.0
        self._shadow_tick = 0
        self._shadow_q: "Queue[Tuple[ModelVersion, Any, Any]]" = \
            Queue(maxsize=max(int(shadow_queue_depth), 1))
        self._shadow_thread: Optional[threading.Thread] = None
        self._shadow_stop = threading.Event()
        self.n_shadow_batches = 0
        self.n_shadow_rows = 0
        self.n_shadow_mismatched_rows = 0
        self.n_shadow_errors = 0
        self.n_shadow_dropped = 0
        self._register_metrics(server.registry)

    # -- telemetry -----------------------------------------------------------

    def _register_metrics(self, registry) -> None:
        self._m_version = registry.gauge(
            "serving_model_version",
            "1 for the worker's active model version, 0 for any other "
            "version this process has served (flip/rollback history).",
            labels=("version",))
        self._m_version.labels(self._active.version).set(1)
        self._m_requests_by_version = registry.counter(
            "serving_requests_by_version_total",
            "Requests committed per model version (which version's "
            "transform produced each reply).", labels=("version",))
        for name, help_, attr in (
            ("serving_version_flips_total",
             "Model-version flips (staged -> active).", "n_flips"),
            ("serving_version_rollbacks_total",
             "Rollbacks to the previous resident version.",
             "n_rollbacks"),
            ("serving_rollout_failures_total",
             "Version stagings that ended in error (failed digest "
             "verification, load, or warmup).", "n_rollout_failures"),
            ("serving_shadow_batches_total",
             "Live batches mirrored through the staged version.",
             "n_shadow_batches"),
            ("serving_shadow_mismatched_rows_total",
             "Shadow rows whose staged-version output differed from "
             "the active version's.", "n_shadow_mismatched_rows"),
            ("serving_shadow_errors_total",
             "Shadow dispatches that raised (staged-model failures "
             "observed off the client path).", "n_shadow_errors"),
            ("serving_shadow_dropped_total",
             "Sampled batches dropped because the shadow thread was "
             "behind (shadowing never delays live traffic).",
             "n_shadow_dropped"),
        ):
            registry.counter(name, help_).set_function(
                lambda a=attr: getattr(self, a))
        self._m_shadow_latency = registry.histogram(
            "serving_shadow_dispatch_latency_ms",
            "Staged-version transform wall-clock for mirrored batches "
            "(compare against serving_dispatch_latency_ms pre-flip).")

    # -- read side (dispatch path) -------------------------------------------

    @property
    def active(self) -> ModelVersion:
        return self._active

    @property
    def staged(self) -> Optional[ModelVersion]:
        return self._staged

    @property
    def previous(self) -> Optional[ModelVersion]:
        return self._previous

    def count_committed(self, version: str, n: int) -> None:
        if n > 0:
            self._m_requests_by_version.labels(version).inc(n)

    # -- staging -------------------------------------------------------------

    def stage(self, source: Optional[str] = None, model: Any = None,
              version: Optional[str] = None,
              warmup_payload: Any = None,
              shadow_fraction: Optional[float] = None,
              quantization=None,
              sync: bool = False) -> Dict[str, Any]:
        """Begin staging the next version from a checkpoint ``source``
        (or an in-memory ``model``). Runs load -> verify -> warmup in
        the background (``sync=True`` runs it inline — tests and the
        serial callers); live traffic is untouched either way.
        ``quantization`` (a config or dict — see serving/quant.py)
        declares the staged version's wire contract: it is validated
        HERE (malformed -> ValueError -> 400 at the endpoint), rides
        the ModelVersion through verify/warmup/flip, and defaults to
        whatever config the loaded model itself carries. Returns the
        staged version's status snapshot."""
        if source is None and model is None:
            raise RolloutError("stage() needs a checkpoint source or "
                               "an in-memory model")
        from mmlspark_tpu.serving.quant import QuantizationConfig
        quantization = QuantizationConfig.from_value(quantization)
        with self._lock:
            if self._staged is not None and \
                    self._staged.state not in self._REPLACEABLE and \
                    self._staged.state == "staged":
                # restaging over a healthy staged version is allowed
                # (a newer candidate supersedes it) but logged
                logger.info("replacing staged version %s with %s",
                            self._staged.version, version)
            if version is None:
                version = f"v{self.n_flips + 2}"
            if version == self._active.version:
                raise RolloutError(
                    f"version {version!r} is already active")
            mv = ModelVersion(version, model=model, source=source,
                              quantization=quantization)
            self._staged = mv
            if shadow_fraction is not None:
                self.shadow_fraction = max(float(shadow_fraction), 0.0)
        if sync:
            self._prepare(mv, warmup_payload)
        else:
            threading.Thread(target=self._prepare,
                             args=(mv, warmup_payload),
                             daemon=True,
                             name="rollout-stage").start()
        return mv.to_dict()

    def _fault(self, site: str) -> None:
        if self.fault_plan is not None:
            self.fault_plan.raise_at(site)

    def _prepare(self, mv: ModelVersion, warmup_payload: Any) -> None:
        try:
            self._fault("rollout_load")
            if mv.model is None:
                mv.state = "verifying"
                self._fault("rollout_verify")
                if self.verify_checkpoints:
                    from mmlspark_tpu.io.checkpoint import verify_digest
                    ok, detail = verify_digest(mv.source, strict=True)
                    if not ok:
                        raise RolloutError(
                            f"checkpoint {mv.source} is not "
                            f"flip-eligible: {detail}")
                    mv.digest_verified = True
                # already verified strictly above (or verification is
                # explicitly off) — don't hash the tree twice
                from mmlspark_tpu.core.serialize import load_stage
                mv.model = load_stage(mv.source, verify=False)
            if mv.quantization is None:
                # a persisted quantized checkpoint carries its own wire
                # contract (NNModel saves quantization.json) — adopt it
                from mmlspark_tpu.serving.quant import QuantizationConfig
                mv.quantization = QuantizationConfig.from_value(
                    getattr(mv.model, "quantization", None))
            if mv.quantization is not None:
                # the model's on-device dequant must match the wire the
                # dispatch stage will cast to — one config drives both
                mv.quantization.configure_model(mv.model)
            if mv.quantization is not None \
                    and mv.quantization.compute is not None:
                # int8-compute staging gate: the quantized forward must
                # hold row-wise parity with the f32 reference within
                # the config's tolerance BEFORE any warmup work — a
                # broken scale config (or a model the quantization
                # genuinely hurts) dies here, state -> "error", and the
                # active version keeps serving: the automatic rollback
                mv.state = "verifying"
                self._fault("rollout_quant_verify")
                self._verify_compute_quant(mv, warmup_payload)
            mv.state = "warming"
            self._fault("rollout_warmup")
            self._warm(mv, warmup_payload)
            mv.state = "staged"
            logger.info(
                "model version %s staged (source=%s, verified=%s, "
                "warmed buckets %s)", mv.version, mv.source,
                mv.digest_verified, mv.warmed_buckets)
        except Exception as e:  # noqa: BLE001 — any staging failure is
            # terminal for THIS candidate; the active version serves on
            mv.state = "error"
            mv.error = str(e) or type(e).__name__
            self.n_rollout_failures += 1
            logger.warning("staging model version %s failed: %s",
                           mv.version, mv.error)

    def _verify_compute_quant(self, mv: ModelVersion,
                              warmup_payload: Any) -> None:
        """The int8-compute parity gate: score ONE reference frame
        (the warmup payload at the smallest bucket) through the staged
        model's quantized forward and its f32 reference, row-wise
        within the config's tolerance (``NNModel.quant_parity_report``
        — the same dequant math the served executable runs). Models
        without the surface (no ``quant_parity_report``) refuse: a
        compute section on a model that cannot honor it must not stage
        silently."""
        if not hasattr(mv.model, "quant_parity_report"):
            raise RolloutError(
                f"version {mv.version}: quantization.compute needs a "
                f"model with the int8-compute surface "
                f"(NNModel.quant_parity_report); "
                f"{type(mv.model).__name__} has none")
        srv = self._server
        payload = warmup_payload if warmup_payload is not None \
            else srv.warmup_payload
        if payload is None:
            raise RolloutError(
                f"version {mv.version}: quantization.compute needs a "
                "warmup payload to verify parity against (pass "
                "warmup_payload, or warm the server once)")
        if getattr(mv.model, "_compute_quant", None) is None:
            # configure_model should have attached the config — a model
            # that did not adopt it would pass a vacuous 0-row report
            # and then serve f32
            raise RolloutError(
                f"version {mv.version}: model did not adopt the "
                "compute quantization config (quantization.compute is "
                "unset on the model)")
        sizes = srv._bucket_sizes(model=mv.model)
        df = srv._warmup_frame(payload, sizes[0], qc=mv.quantization)
        report = mv.model.quant_parity_report(df)
        mv.quant_parity = report
        if not report["rows"]:
            raise RolloutError(
                f"version {mv.version}: int8-compute parity frame was "
                "empty — nothing verified")
        if not report["passed"]:
            raise RolloutError(
                f"version {mv.version}: int8-compute parity failed — "
                f"{report['bad_rows']}/{report['rows']} rows outside "
                f"rtol={report['rtol']} (max_rel={report['max_rel']:.4g})"
            )
        logger.info(
            "model version %s int8-compute parity verified: %s rows "
            "within rtol=%s (max_rel=%.4g)", mv.version,
            report["rows"], report["rtol"], report["max_rel"])

    def _warm(self, mv: ModelVersion, warmup_payload: Any) -> None:
        """Dispatch one synthetic batch per shape bucket through the
        STAGED version (never the live plane): the same frames
        ``ServingServer.warmup`` builds, so after the flip the live
        shape set is closed under every bucket the server can emit."""
        srv = self._server
        payload = warmup_payload if warmup_payload is not None \
            else srv.warmup_payload
        if payload is None:
            logger.warning(
                "no warmup payload for version %s (pass warmup_payload, "
                "or warm the server once so it remembers one): flipping "
                "without pre-flip warmup risks post-flip recompiles",
                mv.version)
            return
        # the STAGED version's ladder AND wire config, not the active
        # one's: a staged version with different sharding
        # (batch_multiple) or a different quantization contract must
        # warm exactly the bucket shapes + dtypes live traffic will
        # dispatch after ITS flip, or the flip retraces
        for n in srv._bucket_sizes(model=mv.model):
            df = srv._warmup_frame(payload, n, qc=mv.quantization)
            out = mv.model.transform(df)
            if out.num_rows != df.num_rows:
                raise RolloutError(
                    f"version {mv.version} returned {out.num_rows} rows "
                    f"for a {df.num_rows}-row warmup dispatch; serving "
                    f"models must preserve row count")
            mv.record_shape(srv._shape_key(df))
            mv.warmed_buckets.append(df.num_rows)

    # -- transitions ---------------------------------------------------------

    def flip(self, version: Optional[str] = None) -> Dict[str, Any]:
        """Atomically make the staged version active. The dispatch
        stage snapshots ``active`` once per batch, so the swap lands
        between batches: in-flight batches finish on the version that
        dispatched them. Raises :class:`RolloutError` unless a staged
        version (matching ``version``, when given) is fully prepared."""
        with self._lock:
            mv = self._staged
            if mv is None:
                raise RolloutError("no staged version to flip to")
            if version is not None and mv.version != version:
                raise RolloutError(
                    f"staged version is {mv.version!r}, not {version!r}")
            if mv.state != "staged":
                raise RolloutError(
                    f"version {mv.version!r} is not flip-eligible "
                    f"(state={mv.state!r}, error={mv.error!r})")
            self._fault("rollout_flip")
            prev = self._active
            mv.state = "active"
            mv.flipped_unix = time.time()
            # THE flip: one reference assignment — the next batch the
            # executor collects dispatches on the new version
            self._active = mv
            prev.state = "previous"
            self._previous = prev
            self._staged = None
            self.shadow_fraction = 0.0
            self.n_flips += 1
            self._m_version.labels(prev.version).set(0)
            self._m_version.labels(mv.version).set(1)
            logger.info("model version flipped: %s -> %s (warmed "
                        "buckets %s)", prev.version, mv.version,
                        mv.warmed_buckets)
            return self.status()

    def rollback(self) -> Dict[str, Any]:
        """Flip back to the previous resident version — the same
        between-batch swap, no reload, no warmup (its executables are
        still resident). One level deep by design: a rollback of a
        rollback is a no-op error."""
        with self._lock:
            prev = self._previous
            if prev is None:
                raise RolloutError("no previous version to roll back to")
            cur = self._active
            prev.state = "active"
            # re-activation keeps flipped_unix: its shape set is already
            # closed, and any genuinely new shape is still a recompile
            if prev.flipped_unix is None:
                prev.flipped_unix = time.time()
            self._active = prev
            cur.state = "retired"
            self._previous = None
            self.n_rollbacks += 1
            self._m_version.labels(cur.version).set(0)
            self._m_version.labels(prev.version).set(1)
            logger.warning("model version rolled back: %s -> %s",
                           cur.version, prev.version)
            return self.status()

    def abort(self) -> Dict[str, Any]:
        """Discard the staged version (if any) and stop shadowing."""
        with self._lock:
            if self._staged is not None:
                self._staged.state = "aborted"
                logger.info("staged version %s aborted",
                            self._staged.version)
                self._staged = None
            self.shadow_fraction = 0.0
            return self.status()

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active": self._active.to_dict(),
                "staged": (self._staged.to_dict()
                           if self._staged is not None else None),
                "previous": (self._previous.to_dict()
                             if self._previous is not None else None),
                "n_flips": self.n_flips,
                "n_rollbacks": self.n_rollbacks,
                "n_rollout_failures": self.n_rollout_failures,
                "shadow": {
                    "fraction": self.shadow_fraction,
                    "batches": self.n_shadow_batches,
                    "rows": self.n_shadow_rows,
                    "mismatched_rows": self.n_shadow_mismatched_rows,
                    "errors": self.n_shadow_errors,
                    "dropped": self.n_shadow_dropped,
                },
            }

    # -- shadow traffic ------------------------------------------------------

    def maybe_shadow(self, df, out) -> None:
        """Called by the dispatch stage after a successful live
        dispatch: mirror this batch to the staged version if sampling
        selects it. Deterministic counter-based sampling (every
        round(1/fraction)-th batch), non-blocking enqueue — the live
        pipeline never waits on the shadow."""
        frac = self.shadow_fraction
        if frac <= 0.0:
            return
        staged = self._staged
        if staged is None or staged.state != "staged":
            return
        self._shadow_tick += 1
        if self._shadow_tick % max(int(round(1.0 / min(frac, 1.0))), 1):
            return
        if self._shadow_thread is None or \
                not self._shadow_thread.is_alive():
            self._shadow_stop.clear()
            self._shadow_thread = threading.Thread(
                target=self._shadow_loop, daemon=True,
                name="rollout-shadow")
            self._shadow_thread.start()
        try:
            self._shadow_q.put_nowait((staged, df, out))
        except Full:
            self.n_shadow_dropped += 1

    def _shadow_loop(self) -> None:
        while not self._shadow_stop.is_set():
            try:
                staged, df, out = self._shadow_q.get(timeout=0.2)
            except Empty:
                continue
            try:
                if staged.quantization is not None:
                    # mirror what the staged version would REALLY see
                    # post-flip: its own wire cast (a no-op when the
                    # live frame already rode the same wire)
                    df = staged.quantization.quantize_frame(df)
                t0 = time.perf_counter()
                shadow_out = staged.model.transform(df)
                self._m_shadow_latency.observe(
                    (time.perf_counter() - t0) * 1000.0)
                staged.record_shape(self._server._shape_key(df))
                comp = (staged.quantization.compute
                        if staged.quantization is not None else None)
                self._compare(df, out, shadow_out,
                              rtol=(comp.tolerance
                                    if comp is not None else None))
                self.n_shadow_batches += 1
                # shadow-output sampling (the PR 7 follow-up): a
                # bounded slice of each mirrored batch — inputs, live
                # outputs, staged outputs side by side — lands in the
                # traffic-capture journal for offline diffing beyond
                # the in-process mismatch counters. Non-blocking.
                cap = getattr(self._server, "capture", None)
                if cap is not None:
                    cap.offer_shadow(self._active.version,
                                     staged.version, df, out,
                                     shadow_out)
            except Exception as e:  # noqa: BLE001 — a failing staged
                # model is exactly what shadowing exists to observe
                self.n_shadow_errors += 1
                logger.warning("shadow dispatch on version %s failed: "
                               "%s", staged.version, e)

    def _compare(self, df, live_out, shadow_out,
                 rtol: Optional[float] = None) -> None:
        """Row-wise comparison over the columns the live model ADDED
        (the reply surface): numeric columns compare with a small
        tolerance, everything else exactly. ``rtol`` widens the
        numeric tolerance when the STAGED version quantizes compute
        (its config's ``tolerance`` — int8-vs-f32 rows inside it are
        the expected quantization step, not a mismatch; rows outside
        it still count)."""
        cols = [c for c in live_out.columns
                if c not in df.columns and c in shadow_out.columns]
        n = live_out.num_rows
        if not cols or n == 0:
            self.n_shadow_rows += n
            return
        # under a compute-quantized staged version the tolerance bounds
        # BOTH relative and absolute error (int8 weight noise is
        # additive at logit scale — see NNModel.quant_parity_report)
        num_rtol = 1e-5 if rtol is None else float(rtol)
        num_atol = 1e-8 if rtol is None else float(rtol)
        mismatch = np.zeros(n, dtype=bool)
        for c in cols:
            a = np.asarray(live_out[c])
            b = np.asarray(shadow_out[c])
            if b.shape != a.shape:
                mismatch[:] = True
                break
            if a.dtype.kind in "fc" or b.dtype.kind in "fc":
                bad = ~np.isclose(a.astype(np.float64),
                                  b.astype(np.float64),
                                  rtol=num_rtol, atol=num_atol,
                                  equal_nan=True)
            else:
                bad = a != b
            mismatch |= bad.reshape(n, -1).any(axis=1)
        self.n_shadow_rows += n
        self.n_shadow_mismatched_rows += int(mismatch.sum())

    def close(self) -> None:
        self._shadow_stop.set()
        t = self._shadow_thread
        if t is not None and t.is_alive():
            t.join(timeout=2)


# ---------------------------------------------------------------------------
# Coordinator-side orchestration
# ---------------------------------------------------------------------------

class RolloutOrchestrator:
    """One fleet rollout, staged across every registered worker.

    Phases (reported live via :meth:`status` / the coordinator's
    ``GET /rollout``):

    ``staging``  POST ``/rollout/stage`` to every worker, poll each
                 worker's ``GET /version`` until its staged version is
                 ``staged`` or errored. A worker that *reports* an
                 error (failed digest verification, load, warmup) fails
                 the whole rollout — nothing flips anywhere. A worker
                 that is *unreachable* is skipped: survivors roll out
                 (the kill-mid-rollout contract).
    ``shadow``   (optional) observe mirrored-traffic stats for
                 ``shadow_window_s``; an aggregate mismatch rate above
                 ``max_shadow_mismatch_rate`` fails the rollout
                 pre-flip.
    ``canary``   flip ONE worker, wait until it has served
                 ``canary_min_requests`` more requests (or the window
                 expires), then compare its error-rate delta and
                 dispatch-latency p95 against the non-canary fleet over
                 the same window. Regression -> roll the canary back,
                 abort the staged version everywhere, end
                 ``rolled_back``.
    ``flipping`` flip the remaining workers; end ``completed``.

    With ``path=None`` the rollout is flip-only: workers must already
    hold ``version`` staged (in-process staging, pre-distributed
    checkpoints) — the orchestrator verifies and proceeds from the
    shadow/canary phase.
    """

    _RUNNING = ("staging", "shadow", "canary", "flipping",
                "rolling_back")

    def __init__(self, coordinator, version: str,
                 path: Optional[str] = None,
                 warmup_payload: Any = None,
                 canary: bool = True,
                 shadow_fraction: float = 0.0,
                 shadow_window_s: float = 0.0,
                 max_shadow_mismatch_rate: float = 0.01,
                 canary_window_s: float = 5.0,
                 canary_min_requests: int = 20,
                 max_error_rate_delta: float = 0.02,
                 max_p95_ratio: float = 3.0,
                 stage_timeout_s: float = 60.0,
                 poll_interval_s: float = 0.1,
                 http_timeout_s: float = 5.0,
                 quantization: Optional[Dict[str, Any]] = None):
        self.coordinator = coordinator
        self.version = str(version)
        self.path = path
        self.warmup_payload = warmup_payload
        # validated up front (ValueError -> 400 at POST /rollout), then
        # forwarded verbatim to every worker's stage body
        from mmlspark_tpu.serving.quant import QuantizationConfig
        qc = QuantizationConfig.from_value(quantization)
        self.quantization = qc.to_dict() if qc is not None else None
        self.canary = bool(canary)
        self.shadow_fraction = float(shadow_fraction)
        self.shadow_window_s = float(shadow_window_s)
        self.max_shadow_mismatch_rate = float(max_shadow_mismatch_rate)
        self.canary_window_s = float(canary_window_s)
        self.canary_min_requests = int(canary_min_requests)
        self.max_error_rate_delta = float(max_error_rate_delta)
        self.max_p95_ratio = float(max_p95_ratio)
        self.stage_timeout_s = float(stage_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.http_timeout_s = float(http_timeout_s)
        self.state = "pending"
        self.detail: Optional[str] = None
        self.workers: Dict[str, Dict[str, Any]] = {}
        self.canary_worker: Optional[str] = None
        self.decision: Optional[Dict[str, Any]] = None
        self.started_unix = time.time()
        self.finished_unix: Optional[float] = None
        self._thread: Optional[threading.Thread] = None

    # -- tiny HTTP helpers ---------------------------------------------------

    def _get(self, wk: str, path: str):
        import requests
        r = requests.get(f"http://{wk}{path}",
                         timeout=self.http_timeout_s)
        r.raise_for_status()
        return r.json() if "json" in r.headers.get(
            "Content-Type", "application/json") else r.text

    def _post(self, wk: str, path: str, body: Dict[str, Any]):
        import requests
        r = requests.post(f"http://{wk}{path}", json=body,
                          timeout=self.http_timeout_s)
        r.raise_for_status()
        return r.json()

    def _mark_unreachable(self, wk: str, err: Exception) -> None:
        self.workers[wk] = {"state": "unreachable", "error": str(err)}
        logger.warning("rollout: worker %s unreachable (%s); skipping",
                       wk, err)

    # -- lifecycle -----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self.state in self._RUNNING

    def start(self) -> "RolloutOrchestrator":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rollout-orchestrator")
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def status(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "version": self.version,
            "path": self.path,
            "canary": self.canary,
            "canary_worker": self.canary_worker,
            "shadow_fraction": self.shadow_fraction,
            "quantization": self.quantization,
            # dict() copies are C-level, atomic under the GIL: the
            # orchestrator thread populates/mutates self.workers
            # concurrently with /rollout handlers calling this — a
            # comprehension over the live dict could raise "changed
            # size during iteration" mid-population
            "workers": {wk: dict(st)
                        for wk, st in dict(self.workers).items()},
            "decision": self.decision,
            "detail": self.detail,
            "started_unix": round(self.started_unix, 3),
            "finished_unix": (round(self.finished_unix, 3)
                              if self.finished_unix else None),
        }

    def _finish(self, state: str, detail: Optional[str] = None) -> None:
        self.state = state
        self.detail = detail
        self.finished_unix = time.time()
        (logger.warning if state in ("failed", "rolled_back")
         else logger.info)("rollout %s ended %s%s", self.version, state,
                           f": {detail}" if detail else "")

    def _run(self) -> None:
        try:
            self._run_phases()
        except Exception as e:  # noqa: BLE001 — an orchestration bug
            # must surface in /rollout, never kill the coordinator
            logger.error("rollout orchestration crashed", exc_info=True)
            self._finish("failed", f"orchestrator error: {e}")

    # -- phases --------------------------------------------------------------

    def _live_workers(self) -> List[str]:
        return [wk for wk, st in self.workers.items()
                if st.get("state") not in ("unreachable", "error")]

    def _run_phases(self) -> None:
        services = self.coordinator.services()
        targets = [f"{s.get('host')}:{s.get('port')}" for s in services]
        if not targets:
            self._finish("failed", "no workers registered")
            return
        for wk in targets:
            self.workers[wk] = {"state": "pending"}

        # -- phase: staging
        self.state = "staging"
        if not self._stage_all(targets):
            return
        live = self._live_workers()
        if not live:
            self._finish("failed", "no worker finished staging")
            return

        # -- phase: shadow observation (optional, pre-flip)
        if self.shadow_fraction > 0 and self.shadow_window_s > 0:
            self.state = "shadow"
            if not self._observe_shadow(live):
                return

        # -- phase: canary
        to_flip = list(live)
        if self.canary and len(live) >= 2:
            self.state = "canary"
            self.canary_worker = live[0]
            if not self._canary_phase(self.canary_worker, live[1:]):
                return
            self.workers[self.canary_worker]["state"] = "active"
            to_flip = [wk for wk in live if wk != self.canary_worker]

        # -- phase: flip the rest
        self.state = "flipping"
        for wk in to_flip:
            try:
                self._post(wk, "/rollout/flip",
                           {"version": self.version})
                self.workers[wk]["state"] = "active"
            except Exception as e:  # noqa: BLE001 — died mid-rollout:
                self._mark_unreachable(wk, e)   # survivors finish
        if not self._live_workers():
            self._finish("failed", "every worker died before the flip")
            return
        self._finish("completed")

    def _stage_all(self, targets: List[str]) -> bool:
        """Stage (or, path-less, confirm an existing staging) on every
        worker. Returns False (after aborting the healthy stagings)
        when any worker REPORTS a staging error."""
        for wk in targets:
            if self.path is not None:
                body = {"path": self.path, "version": self.version}
                if self.warmup_payload is not None:
                    body["warmup_payload"] = self.warmup_payload
                if self.shadow_fraction > 0:
                    body["shadow_fraction"] = self.shadow_fraction
                if self.quantization is not None:
                    body["quantization"] = self.quantization
                try:
                    self._post(wk, "/rollout/stage", body)
                    self.workers[wk]["state"] = "staging"
                except Exception as e:  # noqa: BLE001
                    self._mark_unreachable(wk, e)
            else:
                self.workers[wk]["state"] = "staging"
        deadline = time.monotonic() + self.stage_timeout_s
        failed: Optional[str] = None
        while time.monotonic() < deadline:
            pending = False
            for wk, st in self.workers.items():
                if st.get("state") != "staging":
                    continue
                try:
                    v = self._get(wk, "/version")
                except Exception as e:  # noqa: BLE001
                    self._mark_unreachable(wk, e)
                    continue
                staged = v.get("staged") or {}
                if staged.get("version") == self.version:
                    if staged.get("state") == "staged":
                        st["state"] = "staged"
                        st["digest_verified"] = \
                            staged.get("digest_verified")
                        continue
                    if staged.get("state") == "error":
                        st["state"] = "error"
                        st["error"] = staged.get("error")
                        failed = f"{wk}: {staged.get('error')}"
                        continue
                elif (v.get("active") or {}).get("version") == \
                        self.version:
                    # already active there (a resumed rollout)
                    st["state"] = "active"
                    continue
                elif self.path is None:
                    # flip-only rollout: the version simply isn't there
                    st["state"] = "error"
                    st["error"] = (f"version {self.version!r} not "
                                   f"staged on this worker")
                    failed = f"{wk}: {st['error']}"
                    continue
                pending = True
            if failed is not None:
                break
            if not pending:
                break
            time.sleep(self.poll_interval_s)
        else:
            failed = "staging timed out"
        for wk, st in self.workers.items():
            if st.get("state") == "staging":
                st["state"] = "error"
                st["error"] = "staging timed out"
                failed = failed or f"{wk}: staging timed out"
        if failed is not None:
            self._abort_staged()
            self._finish("failed", f"staging failed ({failed})")
            return False
        return True

    def _abort_staged(self) -> None:
        for wk, st in self.workers.items():
            if st.get("state") in ("staged", "staging"):
                try:
                    self._post(wk, "/rollout/abort", {})
                    st["state"] = "aborted"
                except Exception:  # noqa: BLE001 — best effort
                    pass

    def _shadow_counts(self, wk: str) -> Tuple[int, int, int]:
        sh = self._get(wk, "/version").get("shadow") or {}
        return (int(sh.get("rows") or 0),
                int(sh.get("mismatched_rows") or 0),
                int(sh.get("errors") or 0))

    def _observe_shadow(self, live: List[str]) -> bool:
        # window DELTAS, like the canary phase: the worker counters are
        # lifetime totals, so a failed shadow rollout's mismatches must
        # not poison every later rollout's gate
        before: Dict[str, Tuple[int, int, int]] = {}
        for wk in list(live):
            try:
                before[wk] = self._shadow_counts(wk)
            except Exception as e:  # noqa: BLE001
                self._mark_unreachable(wk, e)
        time.sleep(self.shadow_window_s)
        rows = mismatched = errors = 0
        for wk in list(live):
            if wk not in before:
                continue
            try:
                after = self._shadow_counts(wk)
            except Exception as e:  # noqa: BLE001
                self._mark_unreachable(wk, e)
                continue
            rows += max(after[0] - before[wk][0], 0)
            mismatched += max(after[1] - before[wk][1], 0)
            errors += max(after[2] - before[wk][2], 0)
        rate = (mismatched / rows) if rows else None
        self.decision = {"phase": "shadow", "shadow_rows": rows,
                         "shadow_mismatched_rows": mismatched,
                         "shadow_errors": errors,
                         "shadow_mismatch_rate": rate}
        if errors > 0 or (rate is not None
                          and rate > self.max_shadow_mismatch_rate):
            self._abort_staged()
            self._finish("failed",
                         f"shadow traffic regressed (mismatch rate "
                         f"{rate}, errors {errors})")
            return False
        return True

    # -- canary telemetry ----------------------------------------------------

    def _worker_counters(self, wk: str) -> Dict[str, Any]:
        """One comparison snapshot: request/error counters from
        ``/status``, cumulative dispatch-latency buckets (summed over
        shape buckets, per ``le`` edge) from the worker's own
        ``/metrics`` registry."""
        from mmlspark_tpu.core.telemetry import parse_prometheus
        status = self._get(wk, "/status")
        text = self._get(wk, "/metrics?scope=server")
        if not isinstance(text, str):
            text = str(text)
        cum: Dict[float, float] = {}
        for name, labels, value in parse_prometheus(text):
            if name != "serving_dispatch_latency_ms_bucket":
                continue
            le = dict(labels).get("le")
            edge = float("inf") if le == "+Inf" else float(le)
            cum[edge] = cum.get(edge, 0.0) + value
        return {"requests": int(status.get("n_requests") or 0),
                "errors": int(status.get("n_errors") or 0),
                "buckets": cum}

    @staticmethod
    def _delta_p95(before: Dict[float, float],
                   after: Dict[float, float]) -> Optional[float]:
        edges = sorted(e for e in after if e != float("inf"))
        if not edges:
            return None
        cum_deltas = [max(after.get(e, 0.0) - before.get(e, 0.0), 0.0)
                      for e in edges]
        inf_delta = max(after.get(float("inf"), 0.0)
                        - before.get(float("inf"), 0.0), 0.0)
        counts = [cum_deltas[0]] + [
            max(b - a, 0.0)
            for a, b in zip(cum_deltas, cum_deltas[1:])]
        counts.append(max(inf_delta - cum_deltas[-1], 0.0))
        return quantile_from_buckets(tuple(edges),
                                     [int(c) for c in counts], 0.95)

    def _canary_phase(self, canary: str, rest: List[str]) -> bool:
        # baseline snapshot tolerates individual worker deaths — only
        # the CANARY's own failure may fail the phase (a non-canary
        # worker dying mid-rollout is exactly the case survivors must
        # roll through)
        before: Dict[str, Dict[str, Any]] = {}
        for wk in [canary] + rest:
            try:
                before[wk] = self._worker_counters(wk)
            except Exception as e:  # noqa: BLE001
                self._mark_unreachable(wk, e)
        rest = [wk for wk in rest if wk in before]
        if canary not in before:
            self._abort_staged()
            self._finish("failed",
                         f"canary {canary} died before the flip")
            return False
        try:
            self._post(canary, "/rollout/flip", {"version": self.version})
        except Exception as e:  # noqa: BLE001 — canary died at flip:
            # nothing new is live anywhere; fail safe
            self._abort_staged()
            self._finish("failed", f"canary {canary} failed to flip: "
                                   f"{e}")
            return False
        self.workers[canary]["state"] = "canary"
        deadline = time.monotonic() + self.canary_window_s
        while time.monotonic() < deadline:
            try:
                st = self._get(canary, "/status")
            except Exception as e:  # noqa: BLE001 — canary died while
                # canarying: roll the fleet's staging back, fail safe
                self._mark_unreachable(canary, e)
                self._abort_staged()
                self._finish("failed",
                             f"canary {canary} died mid-observation")
                return False
            if int(st.get("n_requests") or 0) - \
                    before[canary]["requests"] >= self.canary_min_requests:
                break
            time.sleep(self.poll_interval_s)
        after = {}
        for wk in [canary] + rest:
            try:
                after[wk] = self._worker_counters(wk)
            except Exception as e:  # noqa: BLE001
                self._mark_unreachable(wk, e)
        if canary not in after:
            self._abort_staged()
            self._finish("failed", f"canary {canary} died at evaluation")
            return False

        def rates(wks) -> Tuple[int, int]:
            req = sum(after[w]["requests"] - before[w]["requests"]
                      for w in wks if w in after)
            err = sum(after[w]["errors"] - before[w]["errors"]
                      for w in wks if w in after)
            return max(req, 0), max(err, 0)

        c_req, c_err = rates([canary])
        b_req, b_err = rates([w for w in rest if w in after])
        c_rate = (c_err / c_req) if c_req else 0.0
        b_rate = (b_err / b_req) if b_req else 0.0
        c_p95 = self._delta_p95(before[canary]["buckets"],
                                after[canary]["buckets"])
        b_p95s = [self._delta_p95(before[w]["buckets"],
                                  after[w]["buckets"])
                  for w in rest if w in after]
        b_p95s = [p for p in b_p95s if p is not None]
        b_p95 = max(b_p95s) if b_p95s else None
        err_regressed = (c_req > 0 and
                         c_rate > b_rate + self.max_error_rate_delta)
        lat_regressed = (c_p95 is not None and b_p95 is not None
                         and b_p95 > 0
                         and c_p95 > b_p95 * self.max_p95_ratio)
        self.decision = {
            "phase": "canary", "canary_worker": canary,
            "canary_requests": c_req, "canary_errors": c_err,
            "canary_error_rate": round(c_rate, 4),
            "baseline_requests": b_req, "baseline_errors": b_err,
            "baseline_error_rate": round(b_rate, 4),
            "canary_p95_ms": (round(c_p95, 3)
                              if c_p95 is not None else None),
            "baseline_p95_ms": (round(b_p95, 3)
                                if b_p95 is not None else None),
            "error_regressed": err_regressed,
            "latency_regressed": lat_regressed,
        }
        if err_regressed or lat_regressed:
            self.state = "rolling_back"
            try:
                self._post(canary, "/rollout/rollback", {})
                self.workers[canary]["state"] = "rolled_back"
            except Exception as e:  # noqa: BLE001 — a canary that
                # can't roll back is an operator page, not a silent pass
                self.workers[canary]["state"] = "rollback_failed"
                self.workers[canary]["error"] = str(e)
            self._abort_staged()
            self._finish(
                "rolled_back",
                "canary regressed "
                f"(errors: {c_rate:.3f} vs {b_rate:.3f}, p95: "
                f"{c_p95 if c_p95 is None else round(c_p95, 3)} vs "
                f"{b_p95 if b_p95 is None else round(b_p95, 3)} ms)")
            return False
        return True
