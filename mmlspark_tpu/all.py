"""Import every stage module so the full registry is populated.

Grows as the framework grows; used by persistence resolution and the
generic fuzzing test sweep.
"""

import mmlspark_tpu.core.stage  # noqa: F401
import mmlspark_tpu.core.pipeline  # noqa: F401
import mmlspark_tpu.stages.basic  # noqa: F401
import mmlspark_tpu.stages.prep  # noqa: F401
import mmlspark_tpu.stages.image  # noqa: F401
import mmlspark_tpu.stages.batching  # noqa: F401
import mmlspark_tpu.featurize.assemble  # noqa: F401
import mmlspark_tpu.featurize.text  # noqa: F401
import mmlspark_tpu.models.nn  # noqa: F401
import mmlspark_tpu.models.trainer  # noqa: F401
import mmlspark_tpu.models.featurizer  # noqa: F401
import mmlspark_tpu.gbdt.stages  # noqa: F401
import mmlspark_tpu.automl.train  # noqa: F401
import mmlspark_tpu.automl.metrics  # noqa: F401
import mmlspark_tpu.automl.best  # noqa: F401
import mmlspark_tpu.automl.tune  # noqa: F401
import mmlspark_tpu.recommend.indexer  # noqa: F401
import mmlspark_tpu.recommend.ranking  # noqa: F401
import mmlspark_tpu.recommend.sar  # noqa: F401
import mmlspark_tpu.explain.lime  # noqa: F401
import mmlspark_tpu.explain.superpixel  # noqa: F401
import mmlspark_tpu.io.http  # noqa: F401
import mmlspark_tpu.io.services  # noqa: F401
import mmlspark_tpu.serving.consolidator  # noqa: F401
