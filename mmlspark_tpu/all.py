"""Import every stage module so the full registry is populated.

Grows as the framework grows; used by persistence resolution and the
generic fuzzing test sweep.
"""

import mmlspark_tpu.core.stage  # noqa: F401
import mmlspark_tpu.core.pipeline  # noqa: F401
import mmlspark_tpu.stages.image  # noqa: F401
import mmlspark_tpu.stages.batching  # noqa: F401
import mmlspark_tpu.models.nn  # noqa: F401
import mmlspark_tpu.models.trainer  # noqa: F401
import mmlspark_tpu.models.featurizer  # noqa: F401
import mmlspark_tpu.gbdt.stages  # noqa: F401
