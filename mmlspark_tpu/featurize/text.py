"""Text featurization: tokenize → stopwords → n-grams → TF(-IDF).

Capability parity with `src/text-featurizer`
(`TextFeaturizer.scala:179,386`): a composable pipeline builder producing a
feature-vector column from raw text, plus `MultiNGram` (parallel n-gram
lengths, `MultiNGram.scala:23`) and `PageSplitter` (bounded-length text
paging for HTTP services, `PageSplitter.scala:19`).

String processing is host-side; the produced TF/TF-IDF matrices are dense
float arrays ready for device upload (IDF scaling itself is a trivial
broadcast multiply that XLA fuses into the consumer).
"""

from __future__ import annotations

import hashlib
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core import schema as S
from mmlspark_tpu.core.params import (
    Param, HasInputCol, HasOutputCol, in_range,
)
from mmlspark_tpu.core.stage import Transformer, Estimator, Model
from mmlspark_tpu.core.pipeline import Pipeline, PipelineModel


from mmlspark_tpu.core.dataframe import obj_col as _obj_col  # shared helper


def hash_token(token: str, dims: int) -> int:
    """Stable token -> slot hash (murmur-free: md5 low 8 bytes mod dims)."""
    h = hashlib.md5(token.encode("utf-8", "ignore")).digest()
    return int.from_bytes(h[:8], "little") % dims


# A compact English stopword list (Spark ML's default list, reduced).
ENGLISH_STOP_WORDS = frozenset("""
a about above after again against all am an and any are as at be because
been before being below between both but by could did do does doing down
during each few for from further had has have having he her here hers
herself him himself his how i if in into is it its itself just me more
most my myself no nor not now of off on once only or other our ours
ourselves out over own same she should so some such than that the their
theirs them themselves then there these they this those through to too
under until up very was we were what when where which while who whom why
will with you your yours yourself yourselves
""".split())


class Tokenizer(Transformer, HasInputCol, HasOutputCol):
    """Regex tokenizer (parity: Spark RegexTokenizer inside TextFeaturizer)."""

    pattern = Param(r"\W+", "split pattern (gaps=True semantics)", ptype=str)
    to_lowercase = Param(True, "lowercase before splitting", ptype=bool)
    min_token_length = Param(1, "drop shorter tokens", ptype=int)

    def transform(self, df: DataFrame) -> DataFrame:
        pat = re.compile(self.pattern)
        out: List[List[str]] = []
        for text in df[self.input_col]:
            s = str(text)
            if self.to_lowercase:
                s = s.lower()
            toks = [t for t in pat.split(s) if len(t) >= self.min_token_length]
            out.append(toks)
        return df.with_column(self.output_col, _obj_col(out))


class StopWordsRemover(Transformer, HasInputCol, HasOutputCol):
    stop_words = Param(None, "stopword list (default: English)", ptype=list)

    def transform(self, df: DataFrame) -> DataFrame:
        stops = set(self.stop_words) if self.stop_words is not None \
            else ENGLISH_STOP_WORDS
        out = [[t for t in toks if t not in stops]
               for toks in df[self.input_col]]
        return df.with_column(self.output_col, _obj_col(out))


class NGram(Transformer, HasInputCol, HasOutputCol):
    n = Param(2, "n-gram length", ptype=int, validator=in_range(lo=1))

    def transform(self, df: DataFrame) -> DataFrame:
        n = self.n
        out = [[" ".join(toks[i:i + n]) for i in range(len(toks) - n + 1)]
               for toks in df[self.input_col]]
        return df.with_column(self.output_col, _obj_col(out))


class MultiNGram(Transformer, HasInputCol, HasOutputCol):
    """Concatenate n-grams of several lengths (parity: `MultiNGram.scala:23`)."""

    lengths = Param(None, "n-gram lengths, e.g. [1,2,3]", ptype=list)

    def transform(self, df: DataFrame) -> DataFrame:
        lengths = [int(x) for x in (self.lengths or [1, 2, 3])]
        out: List[List[str]] = []
        for toks in df[self.input_col]:
            grams: List[str] = []
            for n in lengths:
                grams.extend(" ".join(toks[i:i + n])
                             for i in range(len(toks) - n + 1))
            out.append(grams)
        return df.with_column(self.output_col, _obj_col(out))


class HashingTF(Transformer, HasInputCol, HasOutputCol):
    """Token list -> hashed term-frequency vector."""

    num_features = Param(1 << 12, "vector dims", ptype=int,
                         validator=in_range(lo=1))
    binary = Param(False, "presence (1.0) instead of counts", ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        dims = self.num_features
        tf = np.zeros((df.num_rows, dims), dtype=np.float64)
        for i, toks in enumerate(df[self.input_col]):
            for tok in toks:
                j = hash_token(tok, dims)
                tf[i, j] = 1.0 if self.binary else tf[i, j] + 1.0
        meta = S.make_features_meta(
            [f"{self.input_col}#tf{j}" for j in range(dims)])
        return df.with_column(self.output_col, tf, metadata=meta)


class IDF(Estimator, HasInputCol, HasOutputCol):
    """Inverse-document-frequency scaling over a TF vector column."""

    min_doc_freq = Param(0, "ignore terms in fewer docs", ptype=int)

    def fit(self, df: DataFrame) -> "IDFModel":
        tf = np.asarray(df[self.input_col], dtype=np.float64)
        n_docs = len(tf)
        doc_freq = np.sum(tf > 0, axis=0)
        idf = np.log((n_docs + 1.0) / (doc_freq + 1.0))
        if self.min_doc_freq > 0:
            idf = np.where(doc_freq >= self.min_doc_freq, idf, 0.0)
        return IDFModel(input_col=self.input_col,
                        output_col=self.output_col, idf=idf.tolist())


class IDFModel(Model, HasInputCol, HasOutputCol):
    idf = Param(None, "per-slot idf weights", ptype=list)

    def transform(self, df: DataFrame) -> DataFrame:
        tf = np.asarray(df[self.input_col], dtype=np.float64)
        out = tf * np.asarray(self.idf, dtype=np.float64)[None, :]
        return df.with_column(self.output_col, out,
                              metadata=df.get_metadata(self.input_col))


class Word2Vec(Estimator, HasInputCol, HasOutputCol):
    """Skip-gram word embeddings with negative sampling, trained on TPU.

    Parity: the `useWord2Vec` path of the reference's text pipeline
    (`TextFeaturizer.scala:179` wraps Spark ML Word2Vec). The TPU rebuild
    trains the classic SGNS objective as one jitted step over batched
    (center, context, negatives) triples — embedding gathers and the
    logit dot-products map onto MXU/VPU, and the whole corpus pass is a
    `lax`-friendly minibatch loop. Documents are embedded as the mean of
    their token vectors (Spark ML semantics).
    """

    vector_size = Param(32, "embedding dimension", ptype=int)
    window = Param(5, "context window radius", ptype=int)
    min_count = Param(1, "min token frequency", ptype=int)
    negatives = Param(5, "negative samples per pair", ptype=int)
    step_size = Param(0.05, "SGD learning rate", ptype=float)
    max_iter = Param(1, "epochs over the pair set", ptype=int)
    batch_size = Param(4096, "pairs per jitted step", ptype=int)
    seed = Param(0, "random seed", ptype=int)

    def fit(self, df: DataFrame) -> "Word2VecModel":
        import jax
        import jax.numpy as jnp

        docs = [list(d) for d in df[self.input_col]]
        counts: Dict[str, int] = {}
        for doc in docs:
            for tok in doc:
                counts[tok] = counts.get(tok, 0) + 1
        vocab = sorted(t for t, c in counts.items() if c >= self.min_count)
        index = {t: i for i, t in enumerate(vocab)}
        V, D = max(len(vocab), 1), self.vector_size

        rng = np.random.default_rng(self.seed)
        centers: List[int] = []
        contexts: List[int] = []
        for doc in docs:
            ids = [index[t] for t in doc if t in index]
            for i, c in enumerate(ids):
                lo = max(0, i - self.window)
                for j in range(lo, min(len(ids), i + self.window + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        if not centers:  # degenerate corpus: zero vectors
            return Word2VecModel(
                input_col=self.input_col,
                output_col=self.output_col or f"{self.input_col}_w2v",
                vocab=list(vocab), vectors=np.zeros((V, D), np.float32))

        # unigram^(3/4) negative-sampling table (word2vec's choice)
        freq = np.array([counts[t] for t in vocab], np.float64) ** 0.75
        neg_p = freq / freq.sum()

        emb_in = (rng.uniform(-0.5, 0.5, (V, D)) / D).astype(np.float32)
        emb_out = np.zeros((V, D), np.float32)
        params = (jnp.asarray(emb_in), jnp.asarray(emb_out))
        lr, K = self.step_size, self.negatives

        def loss_fn(ps, c_idx, ctx_idx, neg_idx):
            e_in, e_out = ps
            vc = e_in[c_idx]                          # (B, D)
            pos = jnp.einsum("bd,bd->b", vc, e_out[ctx_idx])
            neg = jnp.einsum("bd,bkd->bk", vc, e_out[neg_idx])
            return -(jnp.mean(jax.nn.log_sigmoid(pos))
                     + jnp.mean(jnp.sum(jax.nn.log_sigmoid(-neg), axis=1)))

        @jax.jit
        def step(ps, c_idx, ctx_idx, neg_idx):
            g = jax.grad(loss_fn)(ps, c_idx, ctx_idx, neg_idx)
            return jax.tree.map(lambda p, gg: p - lr * gg, ps, g)

        pairs = np.stack([centers, contexts], axis=1)
        B = max(1, min(self.batch_size, len(pairs)))  # static per fit
        for _ in range(max(self.max_iter, 1)):
            order = rng.permutation(len(pairs))
            for s in range(0, len(pairs), B):
                batch = pairs[order[s:s + B]]
                if len(batch) < B:  # static shapes: wrap the tail around
                    batch = np.concatenate(
                        [batch, pairs[order[:B - len(batch)]]], axis=0)
                negs = rng.choice(V, size=(B, K), p=neg_p)
                params = step(params, jnp.asarray(batch[:, 0]),
                              jnp.asarray(batch[:, 1]), jnp.asarray(negs))

        return Word2VecModel(
            input_col=self.input_col,
            output_col=self.output_col or f"{self.input_col}_w2v",
            vocab=list(vocab), vectors=np.asarray(params[0]))


class Word2VecModel(Model, HasInputCol, HasOutputCol):
    """Token lists -> mean-of-embeddings document vectors."""

    vocab = Param(None, "vocabulary (index-aligned with vectors)",
                  ptype=list)
    vectors = Param(None, "embedding matrix (V, D)", complex=True)

    def find_synonyms(self, word: str, num: int = 5) -> List[Tuple[str, float]]:
        """Nearest vocabulary words by cosine similarity."""
        if word not in self.vocab:
            return []
        M = np.asarray(self.vectors)
        v = M[self.vocab.index(word)]
        sim = M @ v / (np.linalg.norm(M, axis=1) * np.linalg.norm(v) + 1e-12)
        order = np.argsort(-sim)
        return [(self.vocab[i], float(sim[i])) for i in order
                if self.vocab[i] != word][:num]

    def _save_extra(self, path, arrays):
        arrays["w2v_vectors"] = np.asarray(self.vectors)

    def _load_extra(self, path, arrays):
        self.vectors = arrays["w2v_vectors"]

    def transform(self, df: DataFrame) -> DataFrame:
        index = {t: i for i, t in enumerate(self.vocab)}
        M = np.asarray(self.vectors)
        D = M.shape[1]
        out = np.zeros((df.num_rows, D), np.float64)
        for r, doc in enumerate(df[self.input_col]):
            ids = [index[t] for t in doc if t in index]
            if ids:
                out[r] = M[ids].mean(axis=0)
        return df.with_column(self.output_col, out)


class TextFeaturizer(Estimator, HasInputCol, HasOutputCol):
    """Text -> feature-vector pipeline builder.

    Parity: `TextFeaturizer.scala:179` — assembles an internal pipeline of
    tokenizer → stopword remover → n-gram → HashingTF → IDF, each part
    toggleable, and fits it as one unit (the fitted result is a
    :class:`TextFeaturizerModel` wrapping the internal PipelineModel, as
    the reference wraps a Spark PipelineModel at
    `TextFeaturizer.scala:386`).
    """

    use_tokenizer = Param(True, "split text into tokens", ptype=bool)
    tokenizer_pattern = Param(r"\W+", "token split pattern", ptype=str)
    to_lowercase = Param(True, "lowercase text", ptype=bool)
    use_stop_words_remover = Param(False, "remove stopwords", ptype=bool)
    use_n_gram = Param(False, "use n-grams", ptype=bool)
    n_gram_length = Param(2, "n-gram length", ptype=int)
    num_features = Param(1 << 12, "hash dims", ptype=int)
    binary = Param(False, "binary TF", ptype=bool)
    use_idf = Param(True, "apply IDF scaling", ptype=bool)
    min_doc_freq = Param(1, "IDF min document frequency", ptype=int)
    use_word2vec = Param(False, "embed via Word2Vec instead of TF(IDF)",
                         ptype=bool)
    word2vec_size = Param(32, "Word2Vec dimension", ptype=int)

    def fit(self, df: DataFrame) -> "TextFeaturizerModel":
        col = self.input_col
        out = self.output_col or f"{col}_features"
        stages: List[Any] = []
        cur = f"{col}__tokens"
        if self.use_tokenizer:
            stages.append(Tokenizer(
                input_col=col, output_col=cur,
                pattern=self.tokenizer_pattern,
                to_lowercase=self.to_lowercase))
        else:
            cur = col
        if self.use_stop_words_remover:
            nxt = f"{col}__nostop"
            stages.append(StopWordsRemover(input_col=cur, output_col=nxt))
            cur = nxt
        if self.use_n_gram:
            nxt = f"{col}__ngrams"
            stages.append(NGram(input_col=cur, output_col=nxt,
                                n=self.n_gram_length))
            cur = nxt
        if self.use_word2vec:
            stages.append(Word2Vec(input_col=cur, output_col=out,
                                   vector_size=self.word2vec_size))
        else:
            tf_col = out if not self.use_idf else f"{col}__tf"
            stages.append(HashingTF(input_col=cur, output_col=tf_col,
                                    num_features=self.num_features,
                                    binary=self.binary))
            if self.use_idf:
                stages.append(IDF(input_col=tf_col, output_col=out,
                                  min_doc_freq=self.min_doc_freq))
        fitted = Pipeline(stages=stages).fit(df)
        return TextFeaturizerModel(input_col=col, output_col=out,
                                   model=fitted)


class TextFeaturizerModel(Model, HasInputCol, HasOutputCol):
    """Parity: `TextFeaturizer.scala:386` (fitted pipeline wrapper)."""

    model = Param(None, "fitted internal pipeline", complex=True)

    def transform(self, df: DataFrame) -> DataFrame:
        out = self.model.transform(df)
        drop = [c for c in out.columns
                if c.startswith(f"{self.input_col}__")]
        return out.drop(*drop)

    def _save_extra(self, path, arrays):
        import os
        self.model.save(os.path.join(path, "inner"))

    def _load_extra(self, path, arrays):
        import os
        from mmlspark_tpu.core.stage import PipelineStage
        self.model = PipelineStage.load(os.path.join(path, "inner"))


class PageSplitter(Transformer, HasInputCol, HasOutputCol):
    """Split long documents into bounded-length pages.

    Parity: `PageSplitter.scala:19` — pages of at most
    ``maximum_page_length`` characters, preferring to break at whitespace
    after ``minimum_page_length``.
    """

    maximum_page_length = Param(5000, "max page chars", ptype=int)
    minimum_page_length = Param(4500, "min chars before a soft break",
                                ptype=int)
    boundary_regex = Param(r"\s", "soft break pattern", ptype=str)

    def transform(self, df: DataFrame) -> DataFrame:
        lo, hi = self.minimum_page_length, self.maximum_page_length
        boundary = re.compile(self.boundary_regex)
        out: List[List[str]] = []
        for text in df[self.input_col]:
            s = str(text)
            pages: List[str] = []
            while len(s) > hi:
                cut = -1
                for m in boundary.finditer(s, lo, hi):
                    if m.start() > 0:  # a cut at 0 would make an empty page
                        cut = m.start()
                        break
                if cut < 0:
                    cut = hi
                pages.append(s[:cut])
                s = s[cut:]
            if s:
                pages.append(s)
            out.append(pages)
        return df.with_column(self.output_col, _obj_col(out))
