"""AutoML featurization: per-type column handling + vector assembly + text.

Capability parity with `src/featurize` (`Featurize.scala:24`,
`AssembleFeatures.scala:93`) and `src/text-featurizer`
(`TextFeaturizer.scala:179`, `MultiNGram.scala:23`, `PageSplitter.scala:19`).
"""

from mmlspark_tpu.featurize.assemble import (
    VectorAssembler, Featurize, FeaturizeModel,
)
from mmlspark_tpu.featurize.text import (
    Tokenizer, StopWordsRemover, NGram, HashingTF, IDF, IDFModel,
    TextFeaturizer, TextFeaturizerModel, MultiNGram, PageSplitter,
)

__all__ = [
    "VectorAssembler", "Featurize", "FeaturizeModel",
    "Tokenizer", "StopWordsRemover", "NGram", "HashingTF", "IDF", "IDFModel",
    "TextFeaturizer", "TextFeaturizerModel", "MultiNGram", "PageSplitter",
]
