"""Vector assembly + AutoML per-type featurization.

Capability parity with `src/featurize`:
- :class:`VectorAssembler` — assemble numeric/vector columns into one
  feature-vector column, carrying slot names and categorical-slot levels in
  column metadata (parity: `core/spark/FastVectorAssembler.scala:23`, which
  exists precisely to keep categorical metadata cheap and up front).
- :class:`Featurize` — AutoML featurization (parity: `Featurize.scala:24`,
  `AssembleFeatures.scala:93`): per-type column handling — numerics cast
  (with missing-value indicator + mean impute), strings token-hashed
  (`HashingTF` parity), categorical-metadata columns one-hot or indexed,
  datetime expansion, vector passthrough — then assembly.

Everything here is host-side numpy: featurization shapes the columns the
device work consumes; the heavy math downstream (GBDT/NN) is the jitted
part. Output is a dense 2D float array — the TPU-native layout (MXU wants
dense tiles; the reference's SparseVector path exists for JVM memory
reasons that don't apply to a columnar host batch feeding HBM).
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, is_null
from mmlspark_tpu.core import schema as S
from mmlspark_tpu.core.params import Param, HasOutputCol, in_range
from mmlspark_tpu.core.stage import Transformer, Estimator, Model
from mmlspark_tpu.featurize.text import hash_token


class VectorAssembler(Transformer, HasOutputCol):
    """Assemble numeric scalar/vector columns into one 2D features column.

    Parity: `FastVectorAssembler.scala:23` — categorical metadata of input
    columns is preserved as categorical slots in the output metadata (and
    categorical columns are placed first, as the reference does, so slot
    indexes stay stable for tree learners).
    """

    input_cols = Param(None, "columns to assemble", ptype=list)
    cats_first = Param(True, "order categorical columns first", ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        names = list(self.input_cols or [])
        if self.cats_first:
            names.sort(key=lambda n: 0 if S.is_categorical(
                df.get_metadata(n)) else 1)
        parts: List[np.ndarray] = []
        slot_names: List[str] = []
        cat_slots: Dict[str, List[Any]] = {}
        for name in names:
            col = df[name]
            meta = df.get_metadata(name)
            if col.dtype == np.dtype("O"):
                col = np.stack([np.asarray(v, dtype=np.float64) for v in col])
            if col.ndim == 1:
                parts.append(col.astype(np.float64)[:, None])
                slot_names.append(name)
                levels = S.categorical_levels(meta)
                if levels is not None:
                    cat_slots[name] = list(levels)
            else:
                col = col.reshape(len(col), -1).astype(np.float64)
                parts.append(col)
                sub = (meta or {}).get("feature_names")
                if sub and len(sub) == col.shape[1]:
                    slot_names.extend(sub)
                    for s, lv in ((meta or {}).get("categorical_slots")
                                  or {}).items():
                        cat_slots[s] = list(lv)
                else:
                    slot_names.extend(f"{name}_{j}" for j in range(col.shape[1]))
        X = np.concatenate(parts, axis=1) if parts else \
            np.zeros((df.num_rows, 0))
        out_meta = S.make_features_meta(slot_names, cat_slots)
        return df.with_column(self.output_col or "features", X,
                              metadata=out_meta)


_DATE_PARTS = ("year", "month", "day", "weekday", "hour", "minute")


def _expand_datetime(epochs: np.ndarray) -> np.ndarray:
    out = np.zeros((len(epochs), len(_DATE_PARTS)), dtype=np.float64)
    for i, e in enumerate(epochs):
        if is_null(e):
            continue  # null date -> all-zero expansion (imputed downstream)
        d = _dt.datetime.fromtimestamp(int(e), tz=_dt.timezone.utc)
        out[i] = (d.year, d.month, d.day, d.weekday(), d.hour, d.minute)
    return out


class Featurize(Estimator, HasOutputCol):
    """AutoML featurization of heterogeneous columns into one feature vector.

    Parity: `Featurize.scala:24` / `AssembleFeatures.scala:93`. Per-type
    handling decided at fit time:

    - numeric: cast float64; if NaNs seen, mean-impute + append a
      ``<col>_missing`` indicator slot (the reference's missing-value
      double-columns);
    - categorical metadata present: one-hot (``one_hot_encode_categoricals``)
      or keep the index as a single categorical slot;
    - plain strings: treated as categorical below
      ``number_of_features`` distinct values, else token-hashed into
      ``number_of_features`` TF slots (HashingTF parity);
    - datetime columns (``datetime`` metadata from DataConversion): expanded
      to year/month/day/weekday/hour/minute;
    - vector (2D) columns: passthrough.
    """

    feature_columns = Param(None, "columns to featurize", ptype=list)
    number_of_features = Param(256, "hash dims for free-text columns",
                               ptype=int, validator=in_range(lo=1))
    one_hot_encode_categoricals = Param(True, "one-hot categoricals",
                                        ptype=bool)
    allow_images = Param(False, "kept for API parity (images handled by "
                         "ImageFeaturizer)", ptype=bool)

    def fit(self, df: DataFrame) -> "FeaturizeModel":
        plans: List[Dict[str, Any]] = []
        for name in self.feature_columns or []:
            col = df[name]
            meta = df.get_metadata(name)
            levels = S.categorical_levels(meta)
            if levels is not None:
                plans.append({"col": name, "kind": "categorical",
                              "levels": list(levels)})
            elif (meta or {}).get("datetime"):
                plans.append({"col": name, "kind": "datetime"})
            elif col.ndim == 1 and (
                    col.dtype.kind in ("U", "S")   # numpy str columns
                    or (col.dtype == np.dtype("O") and (
                        not len(col)
                        or isinstance(_first_non_null(col), str)))):
                distinct = {v for v in col if v is not None}
                if len(distinct) < min(self.number_of_features, 100):
                    lv = sorted(distinct)
                    plans.append({"col": name, "kind": "string_categorical",
                                  "levels": lv})
                else:
                    plans.append({"col": name, "kind": "text",
                                  "dims": self.number_of_features})
            elif col.ndim > 1 or col.dtype == np.dtype("O"):
                plans.append({"col": name, "kind": "vector"})
            else:
                vals = col.astype(np.float64)
                has_missing = bool(np.any(~np.isfinite(vals)))
                mean = float(np.mean(vals[np.isfinite(vals)])) \
                    if np.any(np.isfinite(vals)) else 0.0
                plans.append({"col": name, "kind": "numeric",
                              "has_missing": has_missing, "mean": mean})
        return FeaturizeModel(
            output_col=self.output_col or "features",
            one_hot=self.one_hot_encode_categoricals,
            plans=plans)


def _first_non_null(col):
    for v in col:
        if v is not None:
            return v
    return None


class FeaturizeModel(Model, HasOutputCol):
    """Fitted featurization (parity: `AssembleFeatures.scala:312`)."""

    plans = Param(None, "per-column featurization plans", ptype=list)
    one_hot = Param(True, "one-hot categoricals", ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        parts: List[np.ndarray] = []
        slot_names: List[str] = []
        cat_slots: Dict[str, List[Any]] = {}
        n = df.num_rows
        for plan in self.plans or []:
            name, kind = plan["col"], plan["kind"]
            col = df[name]
            if kind == "numeric":
                vals = col.astype(np.float64).copy()
                if plan["has_missing"]:
                    miss = ~np.isfinite(vals)
                    vals[miss] = plan["mean"]
                    parts.append(vals[:, None])
                    slot_names.append(name)
                    parts.append(miss.astype(np.float64)[:, None])
                    slot_names.append(f"{name}_missing")
                else:
                    parts.append(np.nan_to_num(vals)[:, None])
                    slot_names.append(name)
            elif kind in ("categorical", "string_categorical"):
                levels = plan["levels"]
                lookup = {lv: i for i, lv in enumerate(levels)}
                if kind == "categorical":
                    idx = col.astype(np.int64)
                else:
                    idx = np.array([lookup.get(v, -1) for v in col],
                                   dtype=np.int64)
                if self.one_hot:
                    oh = np.zeros((n, len(levels)), dtype=np.float64)
                    valid = (idx >= 0) & (idx < len(levels))
                    oh[np.arange(n)[valid], idx[valid]] = 1.0
                    parts.append(oh)
                    slot_names.extend(f"{name}={lv}" for lv in levels)
                else:
                    parts.append(idx.astype(np.float64)[:, None])
                    slot_names.append(name)
                    cat_slots[name] = list(levels)
            elif kind == "datetime":
                parts.append(_expand_datetime(col))
                slot_names.extend(f"{name}.{p}" for p in _DATE_PARTS)
            elif kind == "text":
                dims = plan["dims"]
                tf = np.zeros((n, dims), dtype=np.float64)
                for i, text in enumerate(col):
                    for tok in str(text).lower().split():
                        tf[i, hash_token(tok, dims)] += 1.0
                parts.append(tf)
                slot_names.extend(f"{name}#tf{j}" for j in range(dims))
            else:  # vector
                v = col
                if v.dtype == np.dtype("O"):
                    v = np.stack([np.asarray(x, dtype=np.float64) for x in v])
                parts.append(v.reshape(n, -1).astype(np.float64))
                slot_names.extend(
                    f"{name}_{j}" for j in range(parts[-1].shape[1]))
        X = np.concatenate(parts, axis=1) if parts else np.zeros((n, 0))
        meta = S.make_features_meta(slot_names, cat_slots)
        return df.with_column(self.output_col or "features", X, metadata=meta)
