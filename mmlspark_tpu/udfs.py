"""Column helper functions.

Parity: `src/udf/src/main/scala/udfs.scala:15` — the reference registers
``to_vector`` (array column -> ML vector) and ``get_value_at`` (vector
element extraction) as Spark UDFs. Here they are plain column
transformations usable directly or through :class:`UDFTransformer`.
"""

from __future__ import annotations

import numpy as np


def to_vector(col) -> np.ndarray:
    """List/array-of-numbers column -> stacked (n, d) float64 matrix."""
    return np.stack([np.asarray(v, dtype=np.float64) for v in col])


def get_value_at(col, index: int) -> np.ndarray:
    """Element ``index`` of each row's vector as a float64 column."""
    return np.asarray([float(np.asarray(v)[index]) for v in col])
