"""GBDT pipeline stages: the LightGBMClassifier/Regressor replacements.

Capability parity with `lightgbm/src/main/scala/LightGBMClassifier.scala:
23,72`, `LightGBMRegressor.scala`, `LightGBMParams.scala:13` and the
model classes (`LightGBMBooster.scala`): Estimators over a features
column with the full param surface, fitted models that add raw-score /
probability / prediction columns, native-model-string save/load
(`saveNativeModel` / python `loadNativeModelFromFile` parity), feature
importances, and incremental batch training (`numBatches` +
`LGBM_BoosterMerge`, `LightGBMBase.scala:25-37`).

Categorical features come from column metadata (categorical slot indexes
inside the assembled vector — parity with `getCategoricalIndexes`,
`LightGBMUtils.scala:63`) or an explicit param.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    Param, HasFeaturesCol, HasLabelCol, HasWeightCol, in_range, in_set,
)
from mmlspark_tpu.core.stage import Estimator, Model
from mmlspark_tpu.core import schema
from mmlspark_tpu.gbdt.booster import Booster, BoosterParams

# stage-level parallelism names (reference spelling) -> Booster tree_learner
_TREE_LEARNERS = {"data_parallel": "data", "feature_parallel": "feature",
                  "voting_parallel": "voting", "serial": "data"}


class _GBDTParams(HasFeaturesCol, HasLabelCol, HasWeightCol):
    """Shared LightGBM-parity params (`LightGBMParams.scala:13`)."""

    boosting_type = Param("gbdt", "gbdt | rf | dart | goss",
                          validator=in_set("gbdt", "rf", "dart", "goss"))
    num_iterations = Param(100, "boosting rounds", ptype=int)
    learning_rate = Param(0.1, "shrinkage rate", ptype=float)
    num_leaves = Param(31, "max leaves per tree", ptype=int)
    max_depth = Param(-1, "max tree depth (-1 = unlimited)", ptype=int)
    max_bin = Param(255, "max feature bins", ptype=int)
    min_data_in_leaf = Param(20, "min rows per leaf", ptype=int)
    min_sum_hessian_in_leaf = Param(1e-3, "min hessian per leaf", ptype=float)
    lambda_l1 = Param(0.0, "L1 regularization", ptype=float)
    lambda_l2 = Param(0.0, "L2 regularization", ptype=float)
    min_gain_to_split = Param(0.0, "min split gain", ptype=float)
    bagging_fraction = Param(1.0, "row subsample fraction", ptype=float)
    bagging_freq = Param(0, "bag every k iterations", ptype=int)
    feature_fraction = Param(1.0, "feature subsample fraction", ptype=float)
    drop_rate = Param(0.1, "dart dropout rate", ptype=float)
    max_drop = Param(50, "dart max dropped trees", ptype=int)
    skip_drop = Param(0.5, "dart skip probability", ptype=float)
    top_rate = Param(0.2, "goss large-gradient keep rate", ptype=float)
    other_rate = Param(0.1, "goss small-gradient sample rate", ptype=float)
    early_stopping_round = Param(0, "stop after N rounds w/o improvement",
                                 ptype=int)
    metric = Param("", "validation metric (default from objective)", ptype=str)
    validation_fraction = Param(0.0, "held-out fraction for early stopping",
                                ptype=float)
    categorical_feature_indexes = Param(None, "categorical slot indexes "
                                        "(default: from column metadata)",
                                        ptype=list)
    num_batches = Param(0, "split training into N sequential batches merged "
                        "into one booster (parity: numBatches)", ptype=int)
    parallelism = Param("data_parallel", "tree learner (parity: parallelism "
                        "= tree_learner, `LightGBMParams.scala:13-18`): "
                        "data_parallel | feature_parallel | voting_parallel "
                        "| serial", ptype=str)
    top_k = Param(20, "voting-parallel candidates per worker (parity: "
                  "top_k voting param)", ptype=int)
    histogram_impl = Param("auto", "histogram engine: auto | xla | pallas "
                           "| pallas_interpret", ptype=str)
    seed = Param(0, "random seed", ptype=int)
    verbosity = Param(0, "log every N iterations (0 = silent)", ptype=int)
    init_score_col = Param(None, "unused; API parity", ptype=str)

    def _booster_params(self, objective: str, num_class: int = 2,
                        **extra) -> BoosterParams:
        if self.parallelism not in _TREE_LEARNERS:
            raise ValueError(
                f"unknown parallelism {self.parallelism!r}; expected one of "
                f"{sorted(_TREE_LEARNERS)}")
        return BoosterParams(
            objective=objective, boosting_type=self.boosting_type,
            num_iterations=self.num_iterations,
            learning_rate=self.learning_rate, num_leaves=self.num_leaves,
            max_depth=self.max_depth, max_bin=self.max_bin,
            min_data_in_leaf=self.min_data_in_leaf,
            min_sum_hessian_in_leaf=self.min_sum_hessian_in_leaf,
            lambda_l1=self.lambda_l1, lambda_l2=self.lambda_l2,
            min_gain_to_split=self.min_gain_to_split,
            bagging_fraction=self.bagging_fraction,
            bagging_freq=self.bagging_freq,
            feature_fraction=self.feature_fraction,
            num_class=num_class, drop_rate=self.drop_rate,
            max_drop=self.max_drop, skip_drop=self.skip_drop,
            top_rate=self.top_rate, other_rate=self.other_rate,
            early_stopping_round=self.early_stopping_round,
            metric=self.metric, seed=self.seed,
            tree_learner=_TREE_LEARNERS[self.parallelism],
            top_k=self.top_k, histogram_impl=self.histogram_impl, **extra)

    def _categoricals(self, df: DataFrame) -> List[int]:
        if self.categorical_feature_indexes is not None:
            return [int(i) for i in self.categorical_feature_indexes]
        return schema.categorical_slot_indexes(
            df.get_metadata(self.features_col))

    def _feature_names(self, df: DataFrame, F: int) -> List[str]:
        meta = df.get_metadata(self.features_col)
        names = (meta or {}).get("feature_names")
        return list(names) if names and len(names) == F \
            else [f"f{j}" for j in range(F)]

    def _sharding(self):
        import jax
        from mmlspark_tpu.parallel.topology import in_single_device_scope
        if self.parallelism == "serial" or len(jax.devices()) == 1 \
                or in_single_device_scope():
            return None
        from mmlspark_tpu.parallel import build_mesh, batch_sharding
        return batch_sharding(build_mesh())

    def _train(self, df: DataFrame, objective: str,
               num_class: int = 2, **extra) -> Booster:
        X = np.asarray(np.stack(df[self.features_col])
                       if df[self.features_col].dtype == np.dtype("O")
                       else df[self.features_col], dtype=np.float64)
        y = np.asarray(df[self.label_col])
        w = np.asarray(df[self.weight_col], dtype=np.float32) \
            if self.weight_col else None
        params = self._booster_params(objective, num_class, **extra)
        cats = self._categoricals(df)
        names = self._feature_names(df, X.shape[1])

        valid_sets = ()
        if self.validation_fraction > 0:
            rng = np.random.default_rng(self.seed)
            mask = rng.random(len(X)) < self.validation_fraction
            valid_sets = ((X[mask], y[mask]),)
            X, y = X[~mask], y[~mask]
            if w is not None:
                w = w[~mask]

        sharding = self._sharding()
        n_batches = max(self.num_batches, 1)
        booster: Optional[Booster] = None
        if n_batches == 1:
            booster = Booster.train(params, X, y, weights=w,
                                    categorical_features=cats,
                                    feature_names=names,
                                    valid_sets=valid_sets, sharding=sharding,
                                    log_every=self.verbosity)
        else:
            # incremental batch training: N sequential slices, trees merged
            bounds = np.linspace(0, len(X), n_batches + 1).astype(int)
            for i in range(n_batches):
                s, e = bounds[i], bounds[i + 1]
                booster = Booster.train(
                    params, X[s:e], y[s:e],
                    weights=w[s:e] if w is not None else None,
                    categorical_features=cats, feature_names=names,
                    valid_sets=valid_sets, init_model=booster,
                    sharding=sharding, log_every=self.verbosity)
        return booster


class _GBDTModelBase(Model, HasFeaturesCol):
    booster = Param(None, "trained Booster", complex=True)
    prediction_col = Param("prediction", "prediction column", ptype=str)

    def _features(self, df: DataFrame) -> np.ndarray:
        col = df[self.features_col]
        return np.asarray(np.stack(col) if col.dtype == np.dtype("O") else col,
                          dtype=np.float64)

    def feature_importances(self, importance_type: str = "split") -> np.ndarray:
        return self.booster.feature_importances(importance_type)

    def save_native_model(self, path: str, format: Optional[str] = None) -> None:
        """Parity: LightGBMBooster.saveNativeModel (`LightGBMBooster.scala:104`).

        ``format="lightgbm"`` writes LightGBM's text model format
        (including categorical bitset splits), loadable by LightGBM
        tooling and by :func:`load_native_model`; ``format="json"``
        writes this framework's own model string (also loadable by
        :func:`load_native_model`). By default (``format=None``) the
        LightGBM format is written; the rare tree that format cannot
        represent (a categorical split routing MISSING left — LightGBM
        always sends NaN right) falls back to json with a warning, while
        an explicit ``format="lightgbm"`` request still raises
        ``NotImplementedError``.
        """
        if format not in (None, "lightgbm", "json"):
            raise ValueError(f"unknown format {format!r}")
        from mmlspark_tpu.io import fs as _fs
        if format == "json":
            text = self.booster.model_to_string()
        else:
            try:
                text = self.booster.to_lightgbm_string()
            except NotImplementedError:
                if format == "lightgbm":
                    raise
                import warnings
                warnings.warn(
                    "model has a categorical split routing MISSING left, "
                    "which LightGBM's text format cannot represent; saving "
                    "format='json' instead (loadable by load_native_model)",
                    stacklevel=2)
                text = self.booster.model_to_string()
        _fs.write_text(path, text)

    def _save_extra(self, path, arrays):
        import os
        with open(os.path.join(path, "booster.json"), "w") as f:
            f.write(self.booster.model_to_string())

    def _load_extra(self, path, arrays):
        import os
        with open(os.path.join(path, "booster.json")) as f:
            self.booster = Booster.from_string(f.read())


class GBDTClassifier(Estimator, _GBDTParams):
    """Binary/multiclass GBDT classifier (parity: LightGBMClassifier)."""

    objective = Param("binary", "binary | multiclass",
                      validator=in_set("binary", "multiclass"))
    probability_col = Param("probability", "probability column", ptype=str)
    raw_prediction_col = Param("raw_prediction", "raw score column", ptype=str)
    prediction_col = Param("prediction", "label prediction column", ptype=str)

    def fit(self, df: DataFrame) -> "GBDTClassificationModel":
        y = np.asarray(df[self.label_col])
        classes = np.unique(y)
        num_class = len(classes)
        objective = self.objective
        if objective == "binary" and num_class > 2:
            objective = "multiclass"
        y_idx = np.searchsorted(classes, y)
        work = df.with_column(self.label_col, y_idx)
        booster = self._train(work, objective, num_class=num_class)
        return GBDTClassificationModel(
            booster=booster, features_col=self.features_col,
            probability_col=self.probability_col,
            raw_prediction_col=self.raw_prediction_col,
            prediction_col=self.prediction_col,
            classes=[float(c) for c in classes])


class GBDTClassificationModel(_GBDTModelBase):
    probability_col = Param("probability", "probability column", ptype=str)
    raw_prediction_col = Param("raw_prediction", "raw score column", ptype=str)
    classes = Param(None, "original class labels", ptype=list)

    def transform(self, df: DataFrame) -> DataFrame:
        X = self._features(df)
        raw = self.booster.predict_raw(X)
        prob = np.asarray(self.booster.obj.transform(raw))
        if raw.shape[1] == 1:  # binary: expand to 2-class columns
            prob = np.concatenate([1 - prob, prob], axis=1)
            raw = np.concatenate([-raw, raw], axis=1)
        pred_idx = prob.argmax(axis=1)
        classes = np.asarray(self.classes or range(prob.shape[1]))
        out = df.with_column(
            self.raw_prediction_col, raw,
            metadata=schema.make_role_meta(schema.SCORES_KIND, self.uid,
                                           task=schema.CLASSIFICATION))
        out = out.with_column(
            self.probability_col, prob,
            metadata=schema.make_role_meta(schema.SCORED_PROBABILITIES_KIND,
                                           self.uid))
        return out.with_column(
            self.prediction_col, classes[pred_idx],
            metadata=schema.make_role_meta(schema.SCORED_LABELS_KIND,
                                           self.uid))


class GBDTRegressor(Estimator, _GBDTParams):
    """GBDT regressor (parity: LightGBMRegressor + application params)."""

    objective = Param("regression", "regression | regression_l1 | quantile | "
                      "poisson | tweedie",
                      validator=in_set("regression", "regression_l1", "l2",
                                       "l1", "mae", "mse", "quantile",
                                       "poisson", "tweedie"))
    alpha = Param(0.9, "quantile level", ptype=float)
    tweedie_variance_power = Param(1.5, "tweedie variance power",
                                   ptype=float, validator=in_range(1.0, 2.0))

    def fit(self, df: DataFrame) -> "GBDTRegressionModel":
        booster = self._train(df, self.objective, alpha=self.alpha,
                              tweedie_variance_power=self.tweedie_variance_power)
        return GBDTRegressionModel(booster=booster,
                                   features_col=self.features_col)


class GBDTRegressionModel(_GBDTModelBase):
    def transform(self, df: DataFrame) -> DataFrame:
        X = self._features(df)
        pred = self.booster.predict(X)
        return df.with_column(
            self.prediction_col, pred,
            metadata=schema.make_role_meta(schema.SCORES_KIND, self.uid,
                                           task=schema.REGRESSION))


def load_native_model(path: str, is_classifier: bool = True,
                      **stage_params):
    """Parity: python LightGBM*.loadNativeModelFromFile. Accepts local
    paths or remote URLs (the save/load pair both go through io.fs)."""
    from mmlspark_tpu.io import fs as _fs
    booster = Booster.from_string(_fs.read_text(path))
    cls = GBDTClassificationModel if is_classifier else GBDTRegressionModel
    return cls(booster=booster, **stage_params)


# Familiar aliases for users migrating from the reference
LightGBMClassifier = GBDTClassifier
LightGBMRegressor = GBDTRegressor
