"""GBDT objectives: gradients/hessians, init scores, prediction transforms.

Capability parity with the objectives the reference passes through to
LightGBM (`lightgbm/src/main/scala/TrainParams.scala:8-66`: binary,
multiclass, regression, quantile, tweedie; plus poisson/mae used by its
`objective` param). Everything is a pure jittable function of
(predictions, labels, weights).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional, Tuple

import jax.numpy as jnp
import numpy as np

Array = jnp.ndarray


@dataclasses.dataclass(frozen=True)
class Objective:
    name: str
    num_model_outputs: int  # trees trained per boosting round
    grad_hess: Callable  # (pred_raw, y, w, aux) -> (grad, hess) per output
    init_score: Callable  # (y, w) -> scalar or (K,) init raw score
    transform: Callable  # raw scores -> user-facing prediction
    is_classification: bool = False
    # constant-hessian objectives renew each leaf's output to this
    # residual quantile after growth (LightGBM RenewTreeOutput,
    # `regression_objective.hpp`): 0.5 for L1, alpha for quantile
    renew_quantile: Optional[float] = None


def _weighted_mean(y, w):
    return float(np.sum(y * w) / max(np.sum(w), 1e-12))


# -- regression --------------------------------------------------------------

def make_regression(alpha: float = 0.9, tweedie_p: float = 1.5,
                    kind: str = "l2") -> Objective:
    if kind in ("l2", "regression", "mean_squared_error", "mse"):
        def gh(pred, y, w, aux=None):
            return (pred - y) * w, w

        return Objective("regression", 1, gh,
                         lambda y, w: _weighted_mean(y, w),
                         lambda raw: raw)

    if kind in ("l1", "mae", "regression_l1"):
        def gh(pred, y, w, aux=None):
            return jnp.sign(pred - y) * w, w  # constant hessian like LightGBM

        def init(y, w):
            return float(np.median(np.asarray(y)))

        return Objective("regression_l1", 1, gh, init, lambda raw: raw,
                         renew_quantile=0.5)

    if kind == "quantile":
        def gh(pred, y, w, aux=None):
            # pinball loss: grad is -alpha under-prediction, (1-alpha) over
            g = jnp.where(y > pred, -alpha, 1.0 - alpha)
            return g * w, w

        def init(y, w):
            return float(np.quantile(np.asarray(y), alpha))

        return Objective("quantile", 1, gh, init, lambda raw: raw,
                         renew_quantile=alpha)

    if kind == "poisson":
        def gh(pred, y, w, aux=None):
            mu = jnp.exp(pred)
            return (mu - y) * w, mu * w

        def init(y, w):
            return float(np.log(max(_weighted_mean(y, w), 1e-12)))

        return Objective("poisson", 1, gh, init, jnp.exp)

    if kind == "tweedie":
        p = tweedie_p

        def gh(pred, y, w, aux=None):
            # d/df of tweedie deviance with log link (LightGBM's formulation)
            g = -y * jnp.exp((1.0 - p) * pred) + jnp.exp((2.0 - p) * pred)
            h = -y * (1.0 - p) * jnp.exp((1.0 - p) * pred) \
                + (2.0 - p) * jnp.exp((2.0 - p) * pred)
            return g * w, jnp.maximum(h, 1e-12) * w

        def init(y, w):
            return float(np.log(max(_weighted_mean(y, w), 1e-12)))

        return Objective("tweedie", 1, gh, init, jnp.exp)

    raise ValueError(f"unknown regression objective {kind!r}")


# -- binary ------------------------------------------------------------------

def make_binary() -> Objective:
    def gh(pred, y, w, aux=None):
        p = jax_sigmoid(pred)
        return (p - y) * w, jnp.maximum(p * (1.0 - p), 1e-12) * w

    def init(y, w):
        p = min(max(_weighted_mean(y, w), 1e-12), 1 - 1e-12)
        return float(np.log(p / (1 - p)))

    return Objective("binary", 1, gh, init, jax_sigmoid,
                     is_classification=True)


def jax_sigmoid(x):
    return 1.0 / (1.0 + jnp.exp(-x))


# -- multiclass --------------------------------------------------------------

def make_multiclass(num_class: int) -> Objective:
    def gh(pred, y, w, aux=None):
        # pred: (n, K) raw; y: (n,) int labels
        p = jnp.exp(pred - jnp.max(pred, axis=1, keepdims=True))
        p = p / jnp.sum(p, axis=1, keepdims=True)
        onehot = jnp.eye(num_class)[y.astype(jnp.int32)]
        grad = (p - onehot) * w[:, None]
        hess = jnp.maximum(p * (1.0 - p), 1e-12) * w[:, None] * 2.0
        return grad, hess

    def init(y, w):
        counts = np.array([max(float(np.sum((np.asarray(y) == k) * w)), 1e-12)
                           for k in range(num_class)])
        return np.log(counts / counts.sum())

    def transform(raw):
        e = jnp.exp(raw - jnp.max(raw, axis=-1, keepdims=True))
        return e / jnp.sum(e, axis=-1, keepdims=True)

    return Objective("multiclass", num_class, gh, init, transform,
                     is_classification=True)


@functools.lru_cache(maxsize=64)
def get_objective(name: str, num_class: int = 2, alpha: float = 0.9,
                  tweedie_p: float = 1.5) -> Objective:
    """Objectives are frozen and stateless, so instances are cached —
    a stable ``grad_hess`` identity lets repeated fits with the same
    config hit jit caches (the fused device loop keys on it) instead of
    re-tracing the whole boosting program per fit."""
    name = name.lower()
    if name == "binary":
        return make_binary()
    if name in ("multiclass", "softmax"):
        return make_multiclass(num_class)
    return make_regression(alpha=alpha, tweedie_p=tweedie_p, kind=name)
