"""Distributed tree learners: feature-parallel and voting-parallel.

The reference passes ``tree_learner = data | feature | voting`` straight
into LightGBM's C++ socket fabric (`LightGBMParams.scala:13-18`,
`TrainParams.scala:32`); its distributed semantics live behind
`LGBM_NetworkInit` (`TrainUtils.scala:252-267`). Here each mode is a
different *sharding + collective pattern* over the same jitted split
math (`tree.py`):

- **data** (default, `booster.py`): rows sharded over the mesh ``data``
  axis; the histogram reduction becomes an ICI psum via GSPMD.
- **feature**: the bin matrix is sharded over the *feature* axis — each
  device histograms only its feature shard with zero cross-device
  traffic; the only communication is the tiny best-split argmax
  reduction, exactly the trade LightGBM's feature-parallel mode makes
  (its workers exchange just the winning split).
- **voting**: rows sharded as in data-parallel, but instead of psumming
  every feature's histogram, each device *votes* for its locally best
  ``top_k`` features (by real split gain), the vote counts are psummed,
  and only the globally top ``2·top_k`` feature histograms are reduced
  — LightGBM's parallel voting algorithm (Meng et al., NeurIPS'16) with
  the TCP allreduce replaced by ICI collectives inside ``shard_map``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.gbdt.tree import (
    GrowthParams, build_histogram, split_gain_matrix,
)


@partial(jax.jit, static_argnames=("n_bins",))
def build_histogram_per_feature(bins, grad, hess, in_leaf, n_bins: int):
    """Histogram with no cross-feature index flattening.

    Numerically identical to ``tree.build_histogram`` but scatters each
    feature column independently (vmap over features), so when ``bins``
    is sharded over its feature axis GSPMD keeps every scatter local to
    the device owning the shard — the feature-parallel learner.
    """
    mask = in_leaf.astype(jnp.float32)
    vals = jnp.stack([grad * mask, hess * mask, mask], axis=1)  # (n, 3)

    def one_feature(bins_col):
        return jnp.zeros((n_bins, 3), jnp.float32).at[bins_col].add(vals)

    return jax.vmap(one_feature, in_axes=1)(bins)               # (F, B, 3)


def make_voting_hist(mesh, growth: GrowthParams, is_categorical,
                     n_features: int, n_bins: int, top_k: int):
    """Build the voting-parallel histogram function for one fit.

    Returns ``hist_fn(bins, grad, hess, in_leaf) -> (F, B, 3)`` where the
    output is exact for the globally voted top ``min(2*top_k, F)``
    features (plus the count-richest local feature as the parent-stat
    anchor) and zero elsewhere — zeroed features fail the
    ``min_data_in_leaf`` gate in ``split_gain_matrix`` and can never be
    chosen, mirroring how LightGBM's voting learner only ever considers
    globally merged candidates.
    """
    n_sel = min(2 * top_k, n_features)
    axis = "data"
    from mmlspark_tpu.parallel.collectives import shard_map_fn
    import dataclasses
    n_shards = mesh.shape[axis]
    # vote gains are scored on LOCAL (per-shard) histograms, so the
    # min-data/min-hessian gates must be scaled down by the shard count —
    # with the global gates a leaf of ~min_data_in_leaf*n_shards rows has
    # every local gain at -inf and the vote degenerates to low feature ids
    local_growth = dataclasses.replace(
        growth,
        min_data_in_leaf=max(1, growth.min_data_in_leaf // n_shards),
        min_sum_hessian_in_leaf=growth.min_sum_hessian_in_leaf / n_shards)

    def hist_fn(bins, grad, hess, in_leaf, feat_mask):
        local = build_histogram(bins, grad, hess, in_leaf,
                                n_features, n_bins)
        gains, _ = split_gain_matrix(local, is_categorical, local_growth)
        # feature_fraction: vote only over the sampled columns, or the
        # voted set could be disjoint from what find_best_split allows
        gains = jnp.where(feat_mask[None, :, None], gains, -jnp.inf)
        per_feature = jnp.max(gains, axis=(0, 2))            # (F,)
        k = min(top_k, n_features)
        _, voted = jax.lax.top_k(per_feature, k)
        votes = jnp.zeros(n_features, jnp.int32).at[voted].add(1)
        votes = jax.lax.psum(votes, axis)
        # deterministic tie-break by feature index so every device picks
        # the same winners
        rank = votes.astype(jnp.float32) * n_features - jnp.arange(
            n_features, dtype=jnp.float32)
        _, sel = jax.lax.top_k(rank, n_sel)
        # anchor: psum vote for the parent-stat source feature too
        anchor = jnp.argmax(jnp.sum(local[:, :, 2], axis=1))
        anchor = jax.lax.pmax(anchor, axis)                  # consistent
        sel = jnp.concatenate([sel, anchor[None]])
        reduced = jax.lax.psum(local[sel], axis)             # (n_sel+1, B, 3)
        return jnp.zeros_like(local).at[sel].set(reduced)

    # forward-only: the scatter-of-psum output is replicated but the VMA
    # type system cannot infer it, hence check_vma=False (see collectives)
    return jax.jit(shard_map_fn(
        hist_fn, mesh,
        in_specs=(P(axis, None), P(axis), P(axis), P(axis), P()),
        out_specs=P(), check_vma=False))
