"""On-device validation metrics for the fused GBDT boosting loop.

The reference evaluates validation metrics inside its native eval loop
every iteration (`TrainUtils.scala:105-145`: `LGBM_BoosterGetEval` after
each `UpdateOneIter`) — no JVM round-trip per round. The TPU shape of
that idea: the fused fit (`tree.boost_loop_device`) carries the
validation rows' raw scores in the scan and evaluates the metric as a
device scalar each iteration, so an early-stopping fit still touches
the host exactly twice. Host-side :func:`mmlspark_tpu.gbdt.booster.
eval_metric` stays the single source of truth for metric *definitions*;
everything here mirrors it in jnp (f32 — rank sums and means are exact
well past typical validation-set sizes).

AUC uses tie-averaged ranks computed with the same segment trick as
``renew_leaf_values``: sort, group equal predictions via a cumsum of
group starts, scatter-min/max the ranks per group, and average.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax.numpy as jnp

from mmlspark_tpu.gbdt.objectives import Objective

_EPS = 1e-15


def _tie_rank_auc(pred, y):
    m = pred.shape[0]
    order = jnp.argsort(pred)
    sp, sy = pred[order], y[order]
    starts = jnp.concatenate([jnp.ones((1,), bool), sp[1:] != sp[:-1]])
    gid = jnp.cumsum(starts) - 1                       # tie-group per row
    r = jnp.arange(1, m + 1, dtype=jnp.float32)
    gmin = jnp.full(m, jnp.inf, jnp.float32).at[gid].min(r)
    gmax = jnp.full(m, -jnp.inf, jnp.float32).at[gid].max(r)
    avg_rank = (gmin[gid] + gmax[gid]) / 2.0
    pos = (sy == 1).astype(jnp.float32)
    n_pos, n_neg = jnp.sum(pos), jnp.sum((sy == 0).astype(jnp.float32))
    auc = (jnp.sum(avg_rank * pos) - n_pos * (n_pos + 1) / 2.0) \
        / jnp.maximum(n_pos * n_neg, 1e-12)
    return jnp.where((n_pos == 0) | (n_neg == 0), 0.5, auc)


_SUPPORTED = ("auc", "binary_logloss", "binary_error", "multi_logloss",
              "multi_error", "rmse", "l2", "l1", "mae", "quantile",
              "poisson", "tweedie")


def get_device_metric(name: str, obj: Objective, alpha: float,
                      tweedie_p: float
                      ) -> Optional[Tuple[Callable, bool]]:
    """``(metric_fn, higher_is_better)`` or None if the metric has no
    device implementation (the caller falls back to the host loop).

    ``metric_fn(vraw, vy) -> f32 scalar`` where ``vraw`` is the
    validation rows' raw scores ``(m, K)`` and ``vy`` their labels
    ``(m,)``; mirrors :func:`booster.eval_metric` definition-for-
    definition. Cached so the returned closure's identity is stable
    across fits (``metric_fn`` is a static jit arg of the fused boosting
    scan — a fresh identity means a full recompile); the cache key drops
    ``alpha``/``tweedie_p`` for the metrics that ignore them, so e.g.
    binary-AUC fits that differ only in ``alpha`` share one program.
    """
    if name not in _SUPPORTED:
        return None
    if name != "quantile":
        alpha = 0.0
    if name != "tweedie":
        tweedie_p = 0.0
    return _cached_metric(name, obj, alpha, tweedie_p)


@functools.lru_cache(maxsize=None)
def _cached_metric(name: str, obj: Objective, alpha: float,
                   tweedie_p: float) -> Tuple[Callable, bool]:

    def fn(vraw, vy):
        pred = obj.transform(vraw)                     # user-facing (m, K)
        p1 = pred[:, 0]
        if name == "auc":
            return _tie_rank_auc(p1, vy)
        if name == "binary_logloss":
            p = jnp.clip(p1, _EPS, 1 - _EPS)
            return -jnp.mean(vy * jnp.log(p) + (1 - vy) * jnp.log(1 - p))
        if name == "binary_error":
            return jnp.mean(((p1 > 0.5) != (vy > 0.5)).astype(jnp.float32))
        if name == "multi_logloss":
            p = pred[jnp.arange(pred.shape[0]), vy.astype(jnp.int32)]
            return -jnp.mean(jnp.log(jnp.clip(p, _EPS, 1.0)))
        if name == "multi_error":
            return jnp.mean((jnp.argmax(pred, axis=1)
                             != vy.astype(jnp.int32)).astype(jnp.float32))
        if name in ("rmse", "l2"):
            mse = jnp.mean(jnp.square(p1 - vy))
            return jnp.sqrt(mse) if name == "rmse" else mse
        if name in ("l1", "mae"):
            return jnp.mean(jnp.abs(p1 - vy))
        if name == "quantile":
            d = vy - p1
            return jnp.mean(jnp.where(d >= 0, alpha * d, (alpha - 1) * d))
        if name == "poisson":
            mu = jnp.maximum(p1, _EPS)
            return jnp.mean(mu - vy * jnp.log(mu))
        if name == "tweedie":
            mu = jnp.maximum(p1, _EPS)
            return jnp.mean(-vy * jnp.power(mu, 1 - tweedie_p)
                            / (1 - tweedie_p)
                            + jnp.power(mu, 2 - tweedie_p) / (2 - tweedie_p))
        raise AssertionError(name)

    return fn, (name == "auc")
