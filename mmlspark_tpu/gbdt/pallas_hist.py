"""Pallas TPU kernel for the GBDT histogram build — the engine's hot op.

The reference gets its histograms from LightGBM's hand-tuned C++
(`LGBM_BoosterUpdateOneIter`, call site `TrainUtils.scala:95-146`); the
XLA fallback in `tree.py` uses a scatter-add, which lowers to a serial
sort/segment pattern on TPU. This kernel instead turns the histogram
into what the MXU is built for: a one-hot × values **matmul**.

For each row tile we form, per feature, the one-hot matrix
``O[r, b] = (bins[f, r] == b)`` in VMEM and accumulate
``V @ O`` where ``V`` stacks ``[grad·mask, hess·mask, mask]`` — an
(8 × ROWS) @ (ROWS × BINS) MXU contraction per feature. The grid walks
(feature tiles × row tiles) with row tiles innermost, accumulating into
the same output block (revisiting pattern; zeroed on the first visit).

Layout choices (see pallas guide "Tiling Constraints"):
- bins arrive **transposed** (F, N) so a feature row is a sublane slice;
- the value matrix is padded to 8 sublanes (f32 min tile 8×128);
- the bin axis is padded to a multiple of 128 lanes.

The kernel is numerically identical to ``tree.build_histogram`` (tested
against it in interpret mode on CPU); the booster selects it
automatically on TPU backends for the single-chip path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

F_TILE = 8        # features per grid step (sublane-aligned)
ROW_TILE = 1024   # rows per grid step (MXU contraction depth)
_VAL_ROWS = 8     # grad/hess/count padded to the f32 sublane tile


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _hist_kernel(bins_ref, vals_ref, out_ref):
    """One (feature-tile, row-tile) step: out[f] += V @ onehot(bins[f])."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    vals = vals_ref[:]                                    # (8, ROW_TILE)
    n_rows, n_bins = vals.shape[1], out_ref.shape[2]
    lane = jax.lax.broadcasted_iota(jnp.int32, (n_rows, n_bins), 1)
    for f in range(F_TILE):                               # static unroll
        onehot = (bins_ref[f, :][:, None] == lane).astype(jnp.float32)
        # HIGHEST: full-f32 MXU passes — split decisions are tie-sensitive,
        # so histogram sums must match the scatter-add path bit-for-near
        out_ref[f] += jnp.dot(vals, onehot,
                              preferred_element_type=jnp.float32,
                              precision=jax.lax.Precision.HIGHEST)


@functools.partial(jax.jit,
                   static_argnames=("n_bins", "interpret"))
def _hist_pallas(bins_t, vals, n_bins: int, interpret: bool):
    """bins_t (F_pad, N_pad) int32, vals (8, N_pad) f32 -> (F_pad, 8, B_pad)."""
    f_pad, n_pad = bins_t.shape
    b_pad = _round_up(max(n_bins, 128), 128)
    grid = (f_pad // F_TILE, n_pad // ROW_TILE)
    return pl.pallas_call(
        _hist_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((F_TILE, ROW_TILE), lambda i, j: (i, j)),
            pl.BlockSpec((_VAL_ROWS, ROW_TILE), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((F_TILE, _VAL_ROWS, b_pad),
                               lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((f_pad, _VAL_ROWS, b_pad),
                                       jnp.float32),
        interpret=interpret,
    )(bins_t, vals)


def prepare_bins_t(bins) -> jnp.ndarray:
    """Pad + transpose (n, F) bins once per fit for reuse across leaves."""
    n, f = bins.shape
    bins = jnp.asarray(bins, jnp.int32)
    bins_t = jnp.swapaxes(bins, 0, 1)
    f_pad, n_pad = _round_up(f, F_TILE), _round_up(n, ROW_TILE)
    if (f_pad, n_pad) != (f, n):
        bins_t = jnp.pad(bins_t, ((0, f_pad - f), (0, n_pad - n)))
    return bins_t


@functools.partial(jax.jit, static_argnames=("n_features", "n_bins",
                                             "interpret"))
def build_histogram_pallas(bins_t, grad, hess, in_leaf,
                           n_features: int, n_bins: int,
                           interpret: bool = False):
    """Drop-in twin of ``tree.build_histogram`` fed pre-transposed bins.

    bins_t: (F_pad, N_pad) int32 from :func:`prepare_bins_t`;
    grad/hess: (n,) f32; in_leaf: (n,) bool. Returns (F, B, 3) float32
    of [sum_grad, sum_hess, count] per (feature, bin).
    """
    n = grad.shape[0]
    n_pad = bins_t.shape[1]
    mask = in_leaf.astype(jnp.float32)
    vals = jnp.zeros((_VAL_ROWS, n_pad), jnp.float32)
    vals = vals.at[0, :n].set(grad * mask)
    vals = vals.at[1, :n].set(hess * mask)
    vals = vals.at[2, :n].set(mask)
    out = _hist_pallas(bins_t, vals, n_bins, interpret)
    # (F_pad, 8, B_pad) -> (F, B, 3)
    return jnp.swapaxes(out[:n_features, :3, :n_bins], 1, 2)


def pallas_available() -> bool:
    """True when the compiled (non-interpret) kernel can run here."""
    return jax.default_backend() == "tpu"
