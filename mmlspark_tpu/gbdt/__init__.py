from mmlspark_tpu.gbdt.binning import BinMapper
from mmlspark_tpu.gbdt.booster import Booster, BoosterParams
from mmlspark_tpu.gbdt.stages import (
    GBDTClassifier, GBDTClassificationModel,
    GBDTRegressor, GBDTRegressionModel,
    LightGBMClassifier, LightGBMRegressor,
    load_native_model,
)

__all__ = [
    "BinMapper", "Booster", "BoosterParams",
    "GBDTClassifier", "GBDTClassificationModel",
    "GBDTRegressor", "GBDTRegressionModel",
    "LightGBMClassifier", "LightGBMRegressor",
    "load_native_model",
]
