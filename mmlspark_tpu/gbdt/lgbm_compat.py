"""Import genuine LightGBM text-format model files.

Migration path for users of the reference: a model trained there is
saved with ``LightGBMBooster.saveNativeModel``
(`LightGBMBooster.scala:104` → LightGBM's ``SaveModelToString`` text
dump) and loads here unchanged. This parses the documented v2/v3 text
layout — header key=value lines, then per-tree blocks::

    Tree=0
    num_leaves=3
    split_feature=1 0
    threshold=0.5 1.25
    decision_type=2 0
    left_child=1 -1
    right_child=-1 -2
    leaf_value=0.1 -0.2 0.3

Node encoding: internal nodes are 0..num_leaves-2; a negative child
``c`` is leaf ``~c``. ``decision_type`` bit 0 = categorical split,
bit 1 = default-left, bits 2-3 = missing_type (0 = None, 1 = Zero,
2 = NaN). Numerical rule: ``x <= threshold`` goes left. Leaf values
already include shrinkage, and there is no separate init score
(LightGBM bakes boost-from-average into the leaves).

Parity scope: models with missing_type None or NaN (the defaults) and
any ``sigmoid`` coefficient reproduce ``PredictForMat`` outputs on
finite and NaN inputs; missing_type Zero (``zero_as_missing=true``)
routes zeros specially in LightGBM and cannot be represented by this
tree format, so it raises. Categorical (many-vs-many bitset) splits are
not imported yet and raise.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from mmlspark_tpu.gbdt.binning import BinMapper
from mmlspark_tpu.gbdt.tree import Tree

_OBJECTIVE_MAP = {
    "binary": "binary",
    "regression": "regression",
    "regression_l2": "regression",
    "l2": "regression",
    "mean_squared_error": "regression",
    "regression_l1": "regression_l1",
    "l1": "regression_l1",
    "mae": "regression_l1",
    "multiclass": "multiclass",
    "softmax": "multiclass",
    "quantile": "quantile",
    "poisson": "poisson",
    "tweedie": "tweedie",
}


def is_lightgbm_text(s: str) -> bool:
    head = s.lstrip()[:64]
    return head.startswith("tree") and "Tree=" in s


def _parse_blocks(s: str) -> (Dict[str, str], List[Dict[str, str]]):
    header: Dict[str, str] = {}
    trees: List[Dict[str, str]] = []
    current = header
    for line in s.splitlines():
        line = line.strip()
        if not line or line in ("tree", "end of trees") \
                or line.startswith(("pandas_categorical", "parameters",
                                    "feature_importances")):
            continue
        if "=" not in line:
            if line == "average_output":  # rf marker: a bare header line
                header["average_output"] = "1"
            continue
        key, _, value = line.partition("=")
        if key == "Tree":
            current = {}
            trees.append(current)
            continue
        current[key] = value
    return header, trees


def _ints(v: str) -> np.ndarray:
    return np.array([int(x) for x in v.split()], dtype=np.int32)


def _floats(v: str) -> np.ndarray:
    return np.array([float(x) for x in v.split()], dtype=np.float64)


def _convert_tree(blk: Dict[str, str]) -> Tree:
    n_leaves = int(blk["num_leaves"])
    if int(blk.get("num_cat", "0")) > 0:
        raise NotImplementedError(
            "categorical (bitset) splits in LightGBM model files are not "
            "supported by the importer yet")
    leaf_value = _floats(blk["leaf_value"])
    n_internal = n_leaves - 1
    n_nodes = n_internal + n_leaves

    feature = np.full(n_nodes, -1, np.int32)
    threshold = np.zeros(n_nodes, np.float64)
    missing_left = np.zeros(n_nodes, bool)
    left = np.zeros(n_nodes, np.int32)
    right = np.zeros(n_nodes, np.int32)
    value = np.zeros(n_nodes, np.float32)
    value[n_internal:] = leaf_value.astype(np.float32)

    if n_internal:
        split_feature = _ints(blk["split_feature"])
        thr = _floats(blk["threshold"])
        decision = _ints(blk["decision_type"])
        lc = _ints(blk["left_child"])
        rc = _ints(blk["right_child"])

        def node_id(c: int) -> int:
            return c if c >= 0 else n_internal + (~c)

        for i in range(n_internal):
            if decision[i] & 1:
                raise NotImplementedError(
                    "categorical decision_type in LightGBM model file")
            missing_type = (int(decision[i]) >> 2) & 3
            feature[i] = split_feature[i]
            threshold[i] = thr[i]
            if missing_type == 0:
                # None: LightGBM coerces NaN to 0.0 at predict time, then
                # applies the numerical rule — route NaN where 0.0 goes
                missing_left[i] = bool(0.0 <= thr[i])
            elif missing_type == 1:
                raise NotImplementedError(
                    "missing_type=Zero (zero_as_missing=true) routes zeros "
                    "to the default side, which this tree format cannot "
                    "represent")
            else:  # NaN: missing goes to the default-left side
                missing_left[i] = bool(decision[i] & 2)
            left[i] = node_id(int(lc[i]))
            right[i] = node_id(int(rc[i]))

    return Tree(feature=feature, threshold=threshold,
                threshold_bin=np.zeros(n_nodes, np.int32),
                missing_left=missing_left,
                categorical=np.zeros(n_nodes, bool),
                cat_mask=np.zeros((n_nodes, 1), bool),
                left=left, right=right, value=value,
                gain=np.zeros(n_nodes, np.float32), n_nodes=n_nodes)


def from_lightgbm_text(s: str):
    """Parse a LightGBM model dump into a scoring-ready :class:`Booster`."""
    from mmlspark_tpu.gbdt.booster import Booster, BoosterParams
    from mmlspark_tpu.gbdt.objectives import get_objective

    header, blocks = _parse_blocks(s)
    obj_spec = header.get("objective", "regression").split()
    obj_name = _OBJECTIVE_MAP.get(obj_spec[0])
    if obj_name is None:
        raise ValueError(f"unsupported LightGBM objective {obj_spec[0]!r}")
    num_class = int(header.get("num_class", "1"))
    per_iter = int(header.get("num_tree_per_iteration", "1"))
    n_features = int(header["max_feature_idx"]) + 1
    names = header.get("feature_names", "").split() \
        or [f"f{j}" for j in range(n_features)]

    alpha, tweedie_p = 0.9, 1.5
    for tok in obj_spec[1:]:
        if tok.startswith("alpha:"):
            alpha = float(tok.split(":", 1)[1])
        elif tok.startswith("tweedie_variance_power:"):
            tweedie_p = float(tok.split(":", 1)[1])
    params = BoosterParams(objective=obj_name,
                           num_class=max(num_class, 2)
                           if obj_name == "multiclass" else 2,
                           alpha=alpha, tweedie_variance_power=tweedie_p,
                           boosting_type="rf" if "average_output" in header
                           else "gbdt")
    obj = get_objective(obj_name, max(num_class, 2), alpha, tweedie_p)
    sigmoid = 1.0
    if obj_name == "binary":
        # the objective spec line carries the trained sigmoid coefficient,
        # e.g. "objective=binary sigmoid:1"; predict = 1/(1+exp(-k*raw))
        for tok in obj_spec[1:]:
            if tok.startswith("sigmoid:"):
                sigmoid = float(tok.split(":", 1)[1])
        if sigmoid != 1.0:
            import dataclasses
            from mmlspark_tpu.gbdt.objectives import jax_sigmoid
            obj = dataclasses.replace(
                obj, transform=lambda raw, k=sigmoid: jax_sigmoid(k * raw))
    mapper = BinMapper(max_bin=255,
                       upper_bounds=[np.zeros(0)] * n_features,
                       categorical=[False] * n_features, cat_levels={})
    booster = Booster(params, mapper, obj, names)
    booster.init_score = np.zeros(obj.num_model_outputs)
    if obj_name == "binary":
        booster.lgbm_sigmoid = sigmoid  # preserved on re-export

    trees = [_convert_tree(b) for b in blocks]
    booster.trees = [trees[i:i + per_iter]
                     for i in range(0, len(trees), per_iter)]
    booster.best_iteration = len(booster.trees) - 1
    return booster


def _export_tree(tree: Tree, idx: int, init_shift: float) -> str:
    """One ``Tree=`` block in LightGBM's node encoding (internal nodes
    indexed 0.., leaves referenced as ``~leaf_idx``)."""
    if bool(np.any(tree.categorical[:tree.n_nodes])):
        raise NotImplementedError(
            "categorical (bitset) splits cannot be exported to the "
            "LightGBM text format yet; use save_native_model(path, "
            "format='json') for models with categorical splits")
    internal: List[int] = []
    leaves: List[int] = []
    order: List[int] = [0]
    while order:  # preorder: root gets internal index 0
        n = order.pop()
        if tree.feature[n] < 0:
            leaves.append(n)
        else:
            internal.append(n)
            order.append(int(tree.right[n]))
            order.append(int(tree.left[n]))
    int_idx = {n: i for i, n in enumerate(internal)}
    leaf_idx = {n: i for i, n in enumerate(leaves)}

    def child_ref(c: int) -> int:
        return int_idx[c] if tree.feature[c] >= 0 else ~leaf_idx[c]

    lines = [f"Tree={idx}",
             f"num_leaves={len(leaves)}",
             "num_cat=0"]
    if internal:
        # decision_type: bit0=0 numerical, bit1=default-left,
        # bits 2-3 = missing_type NaN (2) — our missing bin holds NaN
        dt = [8 | (2 if tree.missing_left[n] else 0) for n in internal]
        lines += [
            "split_feature=" + " ".join(str(int(tree.feature[n]))
                                        for n in internal),
            "split_gain=" + " ".join(f"{float(tree.gain[n]):.17g}"
                                     for n in internal),
            "threshold=" + " ".join(f"{float(tree.threshold[n]):.17g}"
                                    for n in internal),
            "decision_type=" + " ".join(str(d) for d in dt),
            "left_child=" + " ".join(str(child_ref(int(tree.left[n])))
                                     for n in internal),
            "right_child=" + " ".join(str(child_ref(int(tree.right[n])))
                                      for n in internal),
        ]
    lines += [
        "leaf_value=" + " ".join(f"{float(tree.value[n]) + init_shift:.17g}"
                                 for n in leaves),
        "shrinkage=1",
        "",
    ]
    return "\n".join(lines)


def to_lightgbm_text(booster) -> str:
    """Export a trained :class:`Booster` as a LightGBM text model dump.

    The reverse of :func:`from_lightgbm_text` — the reference's
    ``saveNativeModel`` direction (`LightGBMBooster.scala:104`): a model
    trained here can be loaded by LightGBM tooling (and by this
    importer). LightGBM files carry no separate init score, so the
    booster's init score is folded into the first tree's leaf values,
    exactly how LightGBM bakes boost-from-average into leaves.
    """
    params = booster.params
    obj = booster.obj
    K = obj.num_model_outputs
    sigmoid = getattr(booster, "lgbm_sigmoid", 1.0)
    spec = {
        "binary": f"binary sigmoid:{sigmoid:g}",
        "regression": "regression",
        "regression_l1": "regression_l1",
        "quantile": f"quantile alpha:{params.alpha}",
        "poisson": "poisson",
        "tweedie":
            f"tweedie tweedie_variance_power:{params.tweedie_variance_power}",
        "multiclass": f"multiclass num_class:{K}",
    }.get(obj.name)
    if spec is None:
        raise ValueError(f"objective {obj.name!r} has no LightGBM "
                         f"text-format spelling")
    n_features = len(booster.feature_names)
    head = [
        "tree",
        "version=v3",
        # rf boosters average tree outputs; LightGBM records this so
        # scoring sums become means on reload
        *(["average_output"] if params.boosting_type == "rf" else []),
        f"num_class={K if obj.name == 'multiclass' else 1}",
        f"num_tree_per_iteration={K}",
        "label_index=0",
        f"max_feature_idx={n_features - 1}",
        f"objective={spec}",
        "feature_names=" + " ".join(booster.feature_names),
        "feature_infos=" + " ".join(["none"] * n_features),
        "",
    ]
    init = np.asarray(booster.init_score, dtype=np.float64)
    # export only the trees predict() uses: early-stopped models must
    # reload (here or in LightGBM tooling) with identical predictions
    n_iters = (booster.best_iteration + 1
               if booster.best_iteration >= 0 else len(booster.trees))
    is_rf = params.boosting_type == "rf"
    blocks = []
    for it, iter_trees in enumerate(booster.trees[:n_iters]):
        for k, tree in enumerate(iter_trees):
            # gbdt: fold the init score into the FIRST tree's leaves
            # (how LightGBM bakes boost-from-average); rf: scores are
            # AVERAGED, so the init must ride every tree to survive
            # the division
            shift = 0.0
            if k < len(init) and (is_rf or it == 0):
                shift = float(init[k])
            blocks.append(_export_tree(tree, it * K + k, shift))
    return "\n".join(head) + "\n" + "\n".join(blocks) + "\nend of trees\n"
