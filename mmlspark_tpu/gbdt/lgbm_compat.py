"""Import genuine LightGBM text-format model files.

Migration path for users of the reference: a model trained there is
saved with ``LightGBMBooster.saveNativeModel``
(`LightGBMBooster.scala:104` → LightGBM's ``SaveModelToString`` text
dump) and loads here unchanged. This parses the documented v2/v3 text
layout — header key=value lines, then per-tree blocks::

    Tree=0
    num_leaves=3
    split_feature=1 0
    threshold=0.5 1.25
    decision_type=2 0
    left_child=1 -1
    right_child=-1 -2
    leaf_value=0.1 -0.2 0.3

Node encoding: internal nodes are 0..num_leaves-2; a negative child
``c`` is leaf ``~c``. ``decision_type`` bit 0 = categorical split,
bit 1 = default-left, bits 2-3 = missing_type (0 = None, 1 = Zero,
2 = NaN). Numerical rule: ``x <= threshold`` goes left. Leaf values
already include shrinkage, and there is no separate init score
(LightGBM bakes boost-from-average into the leaves).

Parity scope: models with any missing_type (None / Zero / NaN) and any
``sigmoid`` coefficient reproduce ``PredictForMat`` outputs on finite
and NaN inputs. ``missing_type=Zero`` (``zero_as_missing=true``) is
handled the way LightGBM's predictor handles it — values with
``|x| <= 1e-35`` on those features are treated as missing and routed to
the default side (`Booster.zero_missing_features`). Categorical
(many-vs-many bitset) splits import and export: the bitset maps onto
the framework's per-node ``cat_mask`` with the identity level map
``category value v <-> bin v + 1`` (values beyond the bitset, negative,
or NaN fall to bin 0 and route right, exactly LightGBM's
``CategoricalDecision``).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from mmlspark_tpu.gbdt.binning import BinMapper
from mmlspark_tpu.gbdt.tree import Tree

_OBJECTIVE_MAP = {
    "binary": "binary",
    "regression": "regression",
    "regression_l2": "regression",
    "l2": "regression",
    "mean_squared_error": "regression",
    "regression_l1": "regression_l1",
    "l1": "regression_l1",
    "mae": "regression_l1",
    "multiclass": "multiclass",
    "softmax": "multiclass",
    "quantile": "quantile",
    "poisson": "poisson",
    "tweedie": "tweedie",
}


def is_lightgbm_text(s: str) -> bool:
    head = s.lstrip()[:64]
    return head.startswith("tree") and "Tree=" in s


def _parse_blocks(s: str) -> (Dict[str, str], List[Dict[str, str]]):
    header: Dict[str, str] = {}
    trees: List[Dict[str, str]] = []
    current = header
    for line in s.splitlines():
        line = line.strip()
        if not line or line in ("tree", "end of trees") \
                or line.startswith(("pandas_categorical", "parameters",
                                    "feature_importances")):
            continue
        if "=" not in line:
            if line == "average_output":  # rf marker: a bare header line
                header["average_output"] = "1"
            continue
        key, _, value = line.partition("=")
        if key == "Tree":
            current = {}
            trees.append(current)
            continue
        current[key] = value
    return header, trees


def _ints(v: str) -> np.ndarray:
    return np.array([int(x) for x in v.split()], dtype=np.int32)


def _floats(v: str) -> np.ndarray:
    return np.array([float(x) for x in v.split()], dtype=np.float64)


_BITS_PER_WORD = 32


def _bitset_values(words: np.ndarray) -> List[int]:
    """Category values whose bit is set in a LightGBM uint32 bitset."""
    out = []
    for wi, w in enumerate(words):
        w = int(w) & 0xFFFFFFFF
        for b in range(_BITS_PER_WORD):
            if w >> b & 1:
                out.append(wi * _BITS_PER_WORD + b)
    return out


def _convert_tree(blk: Dict[str, str], cat_width: Dict[int, int],
                  zero_features: set) -> Tree:
    """Build one :class:`Tree`; records per-feature categorical bitset
    widths in ``cat_width`` and Zero-missing features in
    ``zero_features`` (both shared across the file's trees)."""
    n_leaves = int(blk["num_leaves"])
    leaf_value = _floats(blk["leaf_value"])
    n_internal = n_leaves - 1
    n_nodes = n_internal + n_leaves

    feature = np.full(n_nodes, -1, np.int32)
    threshold = np.zeros(n_nodes, np.float64)
    missing_left = np.zeros(n_nodes, bool)
    categorical = np.zeros(n_nodes, bool)
    left = np.zeros(n_nodes, np.int32)
    right = np.zeros(n_nodes, np.int32)
    value = np.zeros(n_nodes, np.float32)
    value[n_internal:] = leaf_value.astype(np.float32)
    cat_left: Dict[int, List[int]] = {}   # node -> category values left

    if n_internal:
        split_feature = _ints(blk["split_feature"])
        thr = _floats(blk["threshold"])
        decision = _ints(blk["decision_type"])
        lc = _ints(blk["left_child"])
        rc = _ints(blk["right_child"])
        n_cat = int(blk.get("num_cat", "0"))
        cat_boundaries = (_ints(blk["cat_boundaries"]) if n_cat
                          else np.zeros(1, np.int32))
        cat_words = (np.array([int(x) for x in
                               blk["cat_threshold"].split()],
                              dtype=np.int64) if n_cat
                     else np.zeros(0, np.int64))

        def node_id(c: int) -> int:
            return c if c >= 0 else n_internal + (~c)

        for i in range(n_internal):
            feature[i] = split_feature[i]
            if decision[i] & 1:
                # categorical: threshold holds the index into
                # cat_boundaries; the bitset lists the values going LEFT.
                # Values beyond the bitset / negative / NaN go right —
                # LightGBM's CategoricalDecision — which the identity
                # level map reproduces via the missing bin (right).
                categorical[i] = True
                ci = int(thr[i])
                words = cat_words[cat_boundaries[ci]:cat_boundaries[ci + 1]]
                vals = _bitset_values(words)
                cat_left[i] = vals
                f = int(split_feature[i])
                width = len(words) * _BITS_PER_WORD
                cat_width[f] = max(cat_width.get(f, 0), width)
            else:
                missing_type = (int(decision[i]) >> 2) & 3
                threshold[i] = thr[i]
                if missing_type == 0:
                    # None: LightGBM coerces NaN to 0.0 at predict time,
                    # then applies the numerical rule — route NaN where
                    # 0.0 goes
                    missing_left[i] = bool(0.0 <= thr[i])
                elif missing_type == 1:
                    # Zero: |x| <= 1e-35 AND NaN are missing, routed to
                    # the default side; the booster pre-maps zeros to
                    # NaN on these features at predict time
                    zero_features.add(int(split_feature[i]))
                    missing_left[i] = bool(decision[i] & 2)
                else:  # NaN: missing goes to the default-left side
                    missing_left[i] = bool(decision[i] & 2)
            left[i] = node_id(int(lc[i]))
            right[i] = node_id(int(rc[i]))

    # cat_mask over bin space with the identity level map: value v is
    # bin v + 1 (bin 0 = missing/unseen, never in a left set => right)
    mask_width = 1 + max(cat_width.values(), default=0)
    cat_mask = np.zeros((n_nodes, max(mask_width, 1)), bool)
    for node, vals in cat_left.items():
        for v in vals:
            cat_mask[node, v + 1] = True

    return Tree(feature=feature, threshold=threshold,
                threshold_bin=np.zeros(n_nodes, np.int32),
                missing_left=missing_left,
                categorical=categorical,
                cat_mask=cat_mask,
                left=left, right=right, value=value,
                gain=np.zeros(n_nodes, np.float32), n_nodes=n_nodes)


def from_lightgbm_text(s: str):
    """Parse a LightGBM model dump into a scoring-ready :class:`Booster`."""
    from mmlspark_tpu.gbdt.booster import Booster, BoosterParams
    from mmlspark_tpu.gbdt.objectives import get_objective

    header, blocks = _parse_blocks(s)
    obj_spec = header.get("objective", "regression").split()
    obj_name = _OBJECTIVE_MAP.get(obj_spec[0])
    if obj_name is None:
        raise ValueError(f"unsupported LightGBM objective {obj_spec[0]!r}")
    num_class = int(header.get("num_class", "1"))
    per_iter = int(header.get("num_tree_per_iteration", "1"))
    n_features = int(header["max_feature_idx"]) + 1
    names = header.get("feature_names", "").split() \
        or [f"f{j}" for j in range(n_features)]

    alpha, tweedie_p = 0.9, 1.5
    for tok in obj_spec[1:]:
        if tok.startswith("alpha:"):
            alpha = float(tok.split(":", 1)[1])
        elif tok.startswith("tweedie_variance_power:"):
            tweedie_p = float(tok.split(":", 1)[1])
    params = BoosterParams(objective=obj_name,
                           num_class=max(num_class, 2)
                           if obj_name == "multiclass" else 2,
                           alpha=alpha, tweedie_variance_power=tweedie_p,
                           boosting_type="rf" if "average_output" in header
                           else "gbdt")
    obj = get_objective(obj_name, max(num_class, 2), alpha, tweedie_p)
    sigmoid = 1.0
    if obj_name == "binary":
        # the objective spec line carries the trained sigmoid coefficient,
        # e.g. "objective=binary sigmoid:1"; predict = 1/(1+exp(-k*raw))
        for tok in obj_spec[1:]:
            if tok.startswith("sigmoid:"):
                sigmoid = float(tok.split(":", 1)[1])
        if sigmoid != 1.0:
            import dataclasses
            from mmlspark_tpu.gbdt.objectives import jax_sigmoid
            obj = dataclasses.replace(
                obj, transform=lambda raw, k=sigmoid: jax_sigmoid(k * raw))
    cat_width: Dict[int, int] = {}
    zero_features: set = set()
    trees = [_convert_tree(b, cat_width, zero_features) for b in blocks]
    # identity level map for imported categorical features: category
    # value v <-> bin v + 1, so the trees' bitset masks index directly
    mapper = BinMapper(
        max_bin=255,
        upper_bounds=[np.zeros(0)] * n_features,
        categorical=[j in cat_width for j in range(n_features)],
        cat_levels={j: np.arange(w, dtype=np.float64)
                    for j, w in cat_width.items()})
    booster = Booster(params, mapper, obj, names)
    booster.init_score = np.zeros(obj.num_model_outputs)
    if obj_name == "binary":
        booster.lgbm_sigmoid = sigmoid  # preserved on re-export
    booster.zero_missing_features = frozenset(zero_features)

    booster.trees = [trees[i:i + per_iter]
                     for i in range(0, len(trees), per_iter)]
    booster.best_iteration = len(booster.trees) - 1
    return booster


def _cat_left_values(tree: Tree, node: int, levels: np.ndarray) -> List[int]:
    """Nonneg-int category values routed left by ``node``'s cat_mask."""
    mask = tree.cat_mask[node]
    if mask.shape[0] > 0 and bool(mask[0]):
        raise NotImplementedError(
            "this categorical split routes MISSING left, which LightGBM's "
            "categorical decision cannot express (NaN always goes right "
            "there); use save_native_model(path, format='json') for "
            "exact persistence of this model")
    vals = []
    for b in np.flatnonzero(mask[1:1 + len(levels)]):
        v = float(levels[int(b)])
        if v < 0 or v != int(v):
            raise ValueError(
                f"categorical level {v!r} is not a nonnegative integer; "
                "LightGBM bitsets index categories by nonneg int value "
                "(the reference passes integer-coded categoricals "
                "straight through, `LightGBMBase.scala:54-58`)")
        vals.append(int(v))
    return vals


def _export_tree(tree: Tree, idx: int, init_shift: float,
                 cat_levels: Optional[Dict[int, np.ndarray]] = None,
                 zero_features: frozenset = frozenset()) -> str:
    """One ``Tree=`` block in LightGBM's node encoding (internal nodes
    indexed 0.., leaves referenced as ``~leaf_idx``)."""
    internal: List[int] = []
    leaves: List[int] = []
    order: List[int] = [0]
    while order:  # preorder: root gets internal index 0
        n = order.pop()
        if tree.feature[n] < 0:
            leaves.append(n)
        else:
            internal.append(n)
            order.append(int(tree.right[n]))
            order.append(int(tree.left[n]))
    int_idx = {n: i for i, n in enumerate(internal)}
    leaf_idx = {n: i for i, n in enumerate(leaves)}

    def child_ref(c: int) -> int:
        return int_idx[c] if tree.feature[c] >= 0 else ~leaf_idx[c]

    # categorical nodes: threshold = index into cat_boundaries; bitsets
    # of the LEFT category values, 32-bit words
    cat_boundaries = [0]
    cat_words: List[int] = []
    thr_str: List[str] = []
    dt: List[int] = []
    n_cat = 0
    for n in internal:
        f = int(tree.feature[n])
        if bool(tree.categorical[n]):
            levels = (cat_levels or {}).get(f, np.zeros(0))
            vals = _cat_left_values(tree, n, levels)
            width_words = (max(vals) // _BITS_PER_WORD + 1) if vals else 1
            words = [0] * width_words
            for v in vals:
                words[v // _BITS_PER_WORD] |= 1 << (v % _BITS_PER_WORD)
            cat_words.extend(words)
            cat_boundaries.append(cat_boundaries[-1] + width_words)
            thr_str.append(str(n_cat))
            n_cat += 1
            dt.append(1)
        else:
            thr_str.append(f"{float(tree.threshold[n]):.17g}")
            if f in zero_features:
                # preserve an imported Zero missing_type on re-export
                dt.append(4 | (2 if tree.missing_left[n] else 0))
            else:
                # bit1=default-left, bits 2-3 = missing_type NaN (2) —
                # our missing bin holds NaN
                dt.append(8 | (2 if tree.missing_left[n] else 0))

    lines = [f"Tree={idx}",
             f"num_leaves={len(leaves)}",
             f"num_cat={n_cat}"]
    if internal:
        lines += [
            "split_feature=" + " ".join(str(int(tree.feature[n]))
                                        for n in internal),
            "split_gain=" + " ".join(f"{float(tree.gain[n]):.17g}"
                                     for n in internal),
            "threshold=" + " ".join(thr_str),
            "decision_type=" + " ".join(str(d) for d in dt),
            "left_child=" + " ".join(str(child_ref(int(tree.left[n])))
                                     for n in internal),
            "right_child=" + " ".join(str(child_ref(int(tree.right[n])))
                                      for n in internal),
        ]
        if n_cat:
            lines += [
                "cat_boundaries=" + " ".join(str(b) for b in cat_boundaries),
                "cat_threshold=" + " ".join(str(w) for w in cat_words),
            ]
    lines += [
        "leaf_value=" + " ".join(f"{float(tree.value[n]) + init_shift:.17g}"
                                 for n in leaves),
        "shrinkage=1",
        "",
    ]
    return "\n".join(lines)


def to_lightgbm_text(booster) -> str:
    """Export a trained :class:`Booster` as a LightGBM text model dump.

    The reverse of :func:`from_lightgbm_text` — the reference's
    ``saveNativeModel`` direction (`LightGBMBooster.scala:104`): a model
    trained here can be loaded by LightGBM tooling (and by this
    importer). LightGBM files carry no separate init score, so the
    booster's init score is folded into the first tree's leaf values,
    exactly how LightGBM bakes boost-from-average into leaves.
    """
    params = booster.params
    obj = booster.obj
    K = obj.num_model_outputs
    sigmoid = getattr(booster, "lgbm_sigmoid", 1.0)
    spec = {
        "binary": f"binary sigmoid:{sigmoid:g}",
        "regression": "regression",
        "regression_l1": "regression_l1",
        "quantile": f"quantile alpha:{params.alpha}",
        "poisson": "poisson",
        "tweedie":
            f"tweedie tweedie_variance_power:{params.tweedie_variance_power}",
        "multiclass": f"multiclass num_class:{K}",
    }.get(obj.name)
    if spec is None:
        raise ValueError(f"objective {obj.name!r} has no LightGBM "
                         f"text-format spelling")
    n_features = len(booster.feature_names)
    head = [
        "tree",
        "version=v3",
        # rf boosters average tree outputs; LightGBM records this so
        # scoring sums become means on reload
        *(["average_output"] if params.boosting_type == "rf" else []),
        f"num_class={K if obj.name == 'multiclass' else 1}",
        f"num_tree_per_iteration={K}",
        "label_index=0",
        f"max_feature_idx={n_features - 1}",
        f"objective={spec}",
        "feature_names=" + " ".join(booster.feature_names),
        "feature_infos=" + " ".join(["none"] * n_features),
        "",
    ]
    init = np.asarray(booster.init_score, dtype=np.float64)
    # export only the trees predict() uses: early-stopped models must
    # reload (here or in LightGBM tooling) with identical predictions
    n_iters = (booster.best_iteration + 1
               if booster.best_iteration >= 0 else len(booster.trees))
    is_rf = params.boosting_type == "rf"
    cat_levels = booster.mapper.cat_levels or {}
    zero_features = frozenset(
        getattr(booster, "zero_missing_features", frozenset()))
    blocks = []
    for it, iter_trees in enumerate(booster.trees[:n_iters]):
        for k, tree in enumerate(iter_trees):
            # gbdt: fold the init score into the FIRST tree's leaves
            # (how LightGBM bakes boost-from-average); rf: scores are
            # AVERAGED, so the init must ride every tree to survive
            # the division
            shift = 0.0
            if k < len(init) and (is_rf or it == 0):
                shift = float(init[k])
            blocks.append(_export_tree(tree, it * K + k, shift,
                                       cat_levels, zero_features))
    return "\n".join(head) + "\n" + "\n".join(blocks) + "\nend of trees\n"
