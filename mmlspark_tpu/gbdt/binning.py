"""Quantile binning: raw features -> small integer bins.

The front door of the GBDT engine, replacing LightGBM's in-C++ dataset
construction (`LGBM_DatasetCreateFromMat`, reference call sites
`lightgbm/src/main/scala/LightGBMUtils.scala:332,367`): features are
discretized once into at most ``max_bin`` quantile bins (uint8-sized),
so tree growth only ever touches small integers — the property that
makes histogram GBDT fast, on TPU as in C++.

NaN handling: missing values get dedicated bin 0; trees learn a default
direction for it like LightGBM's ``use_missing``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

MISSING_BIN = 0  # bin index reserved for NaN in every feature


@dataclasses.dataclass
class BinMapper:
    """Per-feature quantile bin edges + categorical level maps."""

    max_bin: int = 255
    upper_bounds: Optional[List[np.ndarray]] = None  # per feature, ascending
    categorical: Optional[List[bool]] = None
    cat_levels: Optional[Dict[int, np.ndarray]] = None  # feat -> level values

    @property
    def n_features(self) -> int:
        return len(self.upper_bounds or [])

    def n_bins(self, feature: int) -> int:
        if self.categorical[feature]:
            return len(self.cat_levels[feature]) + 1  # + missing bin
        # numeric values land in 1..len(bounds)+1 (searchsorted can return
        # len(bounds)), plus the missing bin 0
        return len(self.upper_bounds[feature]) + 2

    @property
    def max_bins_total(self) -> int:
        return max((self.n_bins(j) for j in range(self.n_features)), default=1)

    # -- fit ----------------------------------------------------------------

    def fit(self, X: np.ndarray,
            categorical_features: Sequence[int] = ()) -> "BinMapper":
        n, f = X.shape
        cats = set(int(c) for c in categorical_features)
        self.categorical = [j in cats for j in range(f)]
        self.upper_bounds = []
        self.cat_levels = {}
        for j in range(f):
            col = X[:, j].astype(np.float64)
            finite = col[~np.isnan(col)]
            if self.categorical[j]:
                levels = np.unique(finite)
                if len(levels) > self.max_bin - 1:
                    raise ValueError(
                        f"categorical feature {j} has {len(levels)} levels "
                        f"> max_bin-1={self.max_bin - 1}")
                self.cat_levels[j] = levels
                self.upper_bounds.append(np.zeros(0))
                continue
            uniq = np.unique(finite)
            if len(uniq) <= self.max_bin - 1:
                # one bin per distinct value; boundaries at midpoints
                bounds = (uniq[:-1] + uniq[1:]) / 2.0 if len(uniq) > 1 \
                    else np.zeros(0)
            else:
                qs = np.quantile(finite,
                                 np.linspace(0, 1, self.max_bin)[1:-1])
                bounds = np.unique(qs)
            self.upper_bounds.append(bounds.astype(np.float64))
        return self

    # -- transform ----------------------------------------------------------

    def transform(self, X: np.ndarray) -> np.ndarray:
        """Raw (n, F) floats -> (n, F) int32 bins (0 = missing)."""
        n, f = X.shape
        out = np.zeros((n, f), dtype=np.int32)
        for j in range(f):
            col = X[:, j].astype(np.float64)
            nan = np.isnan(col)
            if self.categorical[j]:
                idx = np.searchsorted(self.cat_levels[j], col)
                idx = np.clip(idx, 0, len(self.cat_levels[j]) - 1)
                hit = ~nan & (self.cat_levels[j][idx] == col)
                # unseen levels -> missing bin (consistent with LightGBM's
                # other-category handling at predict time)
                out[:, j] = np.where(hit, idx + 1, MISSING_BIN)
            else:
                bins = np.searchsorted(self.upper_bounds[j], col, side="left")
                out[:, j] = np.where(nan, MISSING_BIN, bins + 1)
        return out

    def threshold_value(self, feature: int, threshold_bin: int) -> float:
        """Raw-value threshold for 'bin <= threshold_bin' numeric splits."""
        bounds = self.upper_bounds[feature]
        b = int(threshold_bin) - 1  # shift for missing bin
        if b < 0:
            return -np.inf
        if b >= len(bounds):
            return np.inf
        return float(bounds[b])

    # -- persistence --------------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        return {
            "max_bin": self.max_bin,
            "upper_bounds": [b.tolist() for b in self.upper_bounds],
            "categorical": list(self.categorical),
            "cat_levels": {str(k): v.tolist() for k, v in self.cat_levels.items()},
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "BinMapper":
        return BinMapper(
            max_bin=d["max_bin"],
            upper_bounds=[np.asarray(b, dtype=np.float64)
                          for b in d["upper_bounds"]],
            categorical=list(d["categorical"]),
            cat_levels={int(k): np.asarray(v, dtype=np.float64)
                        for k, v in d["cat_levels"].items()},
        )
