"""Booster: the boosting loop over TreeGrower — LightGBM-core parity.

Covers the C-API surface the reference drives over SWIG
(`LGBM_BoosterCreate/UpdateOneIter/GetEval/SaveModelToString/
LoadModelFromString/PredictForMat/FeatureImportance/Merge`, call sites in
`TrainUtils.scala`, `LightGBMBooster.scala`): gbdt/rf/dart/goss boosting,
binary/multiclass/regression/quantile/tweedie/poisson/l1 objectives,
bagging + feature fraction, early stopping against validation sets,
model-string save/load, split/gain feature importances, batched device
prediction, and booster merging for incremental batch training
(`LGBM_BoosterMerge`, `LightGBMBase.scala:25-37`).

Distribution is by sharding: keep ``bins``/``grad``/``hess`` sharded over
the mesh ``data`` axis and every histogram reduction becomes an ICI psum
(see tree.py) — the TPU replacement for `tree_learner=data`'s socket
allreduce.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from mmlspark_tpu.gbdt.binning import BinMapper
from mmlspark_tpu.gbdt.objectives import Objective, get_objective
from mmlspark_tpu.gbdt.tree import (
    GrowthParams, Tree, TreeGrower, depth_bucket, predict_tree_raw,
)


@dataclasses.dataclass(frozen=True)
class BoosterParams:
    """Parity: LightGBMParams (~25 params, `LightGBMParams.scala:13`) +
    TrainParams -> native param string (`TrainParams.scala:8-66`)."""

    objective: str = "regression"
    boosting_type: str = "gbdt"          # gbdt | rf | dart | goss
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_depth: int = -1
    max_bin: int = 255
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    feature_fraction: float = 1.0
    num_class: int = 2
    alpha: float = 0.9                   # quantile level
    tweedie_variance_power: float = 1.5
    # dart
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    # goss
    top_rate: float = 0.2
    other_rate: float = 0.1
    # early stopping
    early_stopping_round: int = 0
    metric: str = ""                     # default chosen from objective
    seed: int = 0
    # histogram engine: auto -> Pallas MXU kernel on TPU (single-device),
    # XLA scatter-add otherwise (see pallas_hist.py)
    histogram_impl: str = "auto"         # auto | xla | pallas | pallas_interpret
    # distributed tree learner (parity: tree_learner param,
    # `LightGBMParams.scala:13-18`): data | feature | voting
    tree_learner: str = "data"
    top_k: int = 20                      # voting-parallel candidates/worker

    def growth(self) -> GrowthParams:
        return GrowthParams(
            num_leaves=self.num_leaves, max_depth=self.max_depth,
            min_data_in_leaf=self.min_data_in_leaf,
            min_sum_hessian_in_leaf=self.min_sum_hessian_in_leaf,
            lambda_l1=self.lambda_l1, lambda_l2=self.lambda_l2,
            min_gain_to_split=self.min_gain_to_split)


DEFAULT_METRICS = {"binary": "auc", "multiclass": "multi_logloss",
                   "regression": "rmse", "regression_l1": "l1",
                   "quantile": "quantile", "poisson": "poisson",
                   "tweedie": "tweedie"}


def eval_metric(name: str, y: np.ndarray, pred: np.ndarray,
                obj: Objective, alpha: float = 0.9,
                tweedie_p: float = 1.5) -> Tuple[float, bool]:
    """Returns (value, higher_is_better). ``pred`` is user-facing."""
    y = np.asarray(y, dtype=np.float64)
    pred = np.asarray(pred, dtype=np.float64)
    eps = 1e-15
    if name == "auc":
        # tie-averaged ranks (rank-sum AUC), pure numpy
        uniq, inv, counts = np.unique(pred, return_inverse=True,
                                      return_counts=True)
        cum = np.cumsum(counts)
        avg_rank = (cum - counts + 1 + cum) / 2.0
        ranks = avg_rank[inv]
        n_pos = float(np.sum(y == 1))
        n_neg = float(np.sum(y == 0))
        if n_pos == 0 or n_neg == 0:
            return 0.5, True
        auc = (np.sum(ranks[y == 1]) - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
        return float(auc), True
    if name == "binary_logloss":
        p = np.clip(pred, eps, 1 - eps)
        return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))), False
    if name == "binary_error":
        return float(np.mean((pred > 0.5) != (y > 0.5))), False
    if name == "multi_logloss":
        p = np.clip(pred[np.arange(len(y)), y.astype(int)], eps, 1)
        return float(-np.mean(np.log(p))), False
    if name == "multi_error":
        return float(np.mean(np.argmax(pred, axis=1) != y)), False
    if name in ("rmse", "l2"):
        mse = float(np.mean((pred - y) ** 2))
        return (np.sqrt(mse) if name == "rmse" else mse), False
    if name in ("l1", "mae"):
        return float(np.mean(np.abs(pred - y))), False
    if name == "quantile":
        d = y - pred
        return float(np.mean(np.where(d >= 0, alpha * d, (alpha - 1) * d))), False
    if name == "poisson":
        mu = np.maximum(pred, eps)
        return float(np.mean(mu - y * np.log(mu))), False
    if name == "tweedie":
        p_ = tweedie_p
        mu = np.maximum(pred, eps)
        dev = -y * np.power(mu, 1 - p_) / (1 - p_) + np.power(mu, 2 - p_) / (2 - p_)
        return float(np.mean(dev)), False
    raise ValueError(f"unknown metric {name!r}")


class Booster:
    """A trained (or training) additive tree model."""

    def __init__(self, params: BoosterParams, mapper: BinMapper,
                 obj: Objective, feature_names: Sequence[str]):
        self.params = params
        self.mapper = mapper
        self.obj = obj
        self.feature_names = list(feature_names)
        self.trees: List[List[Tree]] = []  # [iteration][output]
        self.init_score: np.ndarray = np.zeros(1)
        self.best_iteration: int = -1

    # -- training -----------------------------------------------------------

    @staticmethod
    def train(params: BoosterParams, X: np.ndarray, y: np.ndarray,
              weights: Optional[np.ndarray] = None,
              categorical_features: Sequence[int] = (),
              feature_names: Optional[Sequence[str]] = None,
              valid_sets: Sequence[Tuple[np.ndarray, np.ndarray]] = (),
              init_model: Optional["Booster"] = None,
              sharding=None,
              log_every: int = 0) -> "Booster":
        """Fit a booster. ``sharding``: optional jax batch sharding for the
        row-dimension arrays (data-parallel tree learner)."""
        X = np.asarray(X, dtype=np.float64)
        y = np.asarray(y)
        n, F = X.shape
        obj = get_objective(params.objective, params.num_class,
                            params.alpha, params.tweedie_variance_power)
        K = obj.num_model_outputs

        if init_model is not None:
            mapper = init_model.mapper
            booster = init_model
        else:
            mapper = BinMapper(max_bin=params.max_bin).fit(
                X, categorical_features)
            booster = Booster(params, mapper, obj,
                              feature_names or [f"f{j}" for j in range(F)])
            booster.init_score = np.atleast_1d(
                np.asarray(obj.init_score(y, _weights(weights, n)),
                           dtype=np.float64))

        bins_np = mapper.transform(X)
        n_bins = mapper.max_bins_total
        w_np = _weights(weights, n).astype(np.float32)
        y_np = np.asarray(y, dtype=np.float32)
        valid_rows = np.ones(n, dtype=bool)
        if params.tree_learner not in ("data", "feature", "voting"):
            raise ValueError(f"unknown tree_learner {params.tree_learner!r}")
        tree_learner = params.tree_learner if sharding is not None else "data"
        if sharding is not None and tree_learner == "feature":
            # feature-parallel: shard the bin matrix over the FEATURE axis
            # (each device histograms its feature shard locally, zero
            # histogram traffic); row-dim arrays stay replicated
            from jax.sharding import NamedSharding, PartitionSpec as P
            feat_sharding = NamedSharding(sharding.mesh, P(None, "data"))
            n_padded = n
            # pad the feature dim to the shard multiple; pad columns are
            # all-missing-bin so every candidate split on them is invalid
            from mmlspark_tpu.parallel import pad_to_multiple
            bins_np, _ = pad_to_multiple(bins_np,
                                         sharding.mesh.shape["data"], axis=1)
            bins = jax.device_put(bins_np, feat_sharding)
            put = jnp.asarray
            w, y_dev = put(w_np), put(y_np)
        else:
            if sharding is not None:
                # pad rows to the data-axis multiple; pad rows carry zero
                # weight and are excluded from sampling masks, so histograms
                # and leaf stats are untouched
                from mmlspark_tpu.parallel import pad_to_multiple
                n_shards = sharding.mesh.shape["data"]
                bins_np, _ = pad_to_multiple(bins_np, n_shards)
                y_np, _ = pad_to_multiple(y_np, n_shards)
                w_np, _ = pad_to_multiple(w_np, n_shards)
                valid_rows, _ = pad_to_multiple(valid_rows, n_shards,
                                                pad_value=False)
            n_padded = len(bins_np)
            put = (lambda a: jax.device_put(a, sharding)) \
                if sharding is not None else jnp.asarray
            bins = put(bins_np)
            w = put(w_np)
            y_dev = put(y_np)

        hist_impl = params.histogram_impl
        if hist_impl not in ("auto", "xla", "pallas", "pallas_interpret"):
            raise ValueError(f"unknown histogram_impl {hist_impl!r}")
        from mmlspark_tpu.gbdt.pallas_hist import pallas_available
        if hist_impl == "auto":
            hist_impl = ("pallas" if sharding is None and pallas_available()
                         else "xla")
        elif hist_impl != "xla" and sharding is not None:
            # the pallas kernel has no GSPMD partitioning rule; sharded
            # fits always take the XLA path (its reductions become psums)
            import warnings
            warnings.warn("histogram_impl='pallas' is single-device only; "
                          "falling back to 'xla' for the sharded fit")
            hist_impl = "xla"
        elif hist_impl == "pallas" and not pallas_available():
            raise ValueError(
                "histogram_impl='pallas' needs a TPU backend; use 'auto' "
                "(selects the right engine) or 'pallas_interpret' for "
                "CPU debugging")
        grower = TreeGrower(mapper, params.growth(), bins_np.shape[1], n_bins,
                            hist_impl=hist_impl, tree_learner=tree_learner,
                            mesh=sharding.mesh if sharding is not None else None,
                            top_k=params.top_k)
        rng = np.random.default_rng(params.seed)

        # raw predictions (n_padded, K) on device
        raw_np = np.broadcast_to(
            np.asarray(booster.init_score, dtype=np.float32)[None, :],
            (n_padded, K)).copy()
        if init_model is not None and booster.trees:
            prior = (booster._predict_raw_np(X)
                     - booster.init_score[None, :]).astype(np.float32)
            raw_np[:n] += prior
        raw = put(raw_np)

        # continuation must re-decide the best iteration over the new run
        booster.best_iteration = -1

        grad_fn = jax.jit(obj.grad_hess)
        is_rf = params.boosting_type == "rf"
        is_dart = params.boosting_type == "dart"
        is_goss = params.boosting_type == "goss"
        shrink = 1.0 if is_rf else params.learning_rate

        # validation state
        metric_name = params.metric or DEFAULT_METRICS.get(obj.name, "l2")
        best_metric, best_iter, rounds_no_improve = None, -1, 0
        tree_raw_contribs: List[jnp.ndarray] = []  # dart needs per-tree raw
        valid_eval: Optional[_ValidEval] = None  # incremental valid scorer

        start_iter = len(booster.trees)

        # -- fully-fused fit: the whole boosting loop as ONE device scan
        # (the TPU shape of the reference's native hot loop,
        # `TrainUtils.scala:95-146`) — eligible when nothing in the loop
        # needs the host: gbdt or goss boosting (any small K; the scan
        # body unrolls K tree growers, so huge class counts would
        # balloon compile time and keep the cached per-tree path
        # instead) and no per-iteration logging. Bagging, goss, and
        # feature sampling ride the scan as device RNG (threefry key in
        # the carry — a different stream than the host loop's numpy rng,
        # so sampled fits match in distribution/quality, not
        # tree-for-tree); ``init_model`` continuations seed the scan's
        # raw scores with the prior. Early stopping IS eligible:
        # validation rows ride the scan (appended + masked, metric
        # evaluated on device — the reference's in-native eval loop,
        # `TrainUtils.scala:105-145`) and the host replays the stopping
        # rule on the fetched metric series, so an early-stopping fit
        # still pays exactly one fetch.
        es_active = bool(valid_sets) and params.early_stopping_round > 0
        device_metric = None
        if es_active and not log_every and len(valid_sets) == 1 \
                and len(valid_sets[0][0]) > 0 and sharding is None:
            from mmlspark_tpu.gbdt.device_metrics import get_device_metric
            device_metric = get_device_metric(
                metric_name, obj, params.alpha,
                params.tweedie_variance_power)
        fused = (params.boosting_type in ("gbdt", "goss") and K <= 16
                 and tree_learner == "data" and grower._voting_fn is None
                 and (not es_active or device_metric is not None)
                 and not log_every)
        if fused:
            from mmlspark_tpu.gbdt.tree import (boost_loop_device,
                                                tree_from_arrays)
            n_valid = 0
            bins_dev, y_fit, w_fit, mask_fit, raw_fit = \
                bins, y_dev, w, put(valid_rows), raw.astype(jnp.float32)
            if device_metric is not None:
                # validation rows become the tail of the row set: masked
                # out of histograms/sampling/renewal, routed (and
                # scored) for free
                vX = np.asarray(valid_sets[0][0], dtype=np.float64)
                vy_np = np.asarray(valid_sets[0][1], dtype=np.float32)
                n_valid = len(vX)
                vbins = mapper.transform(vX)
                bins_dev = put(np.concatenate([bins_np, vbins]))
                y_fit = put(np.concatenate([y_np, vy_np]))
                w_fit = put(np.concatenate(
                    [w_np, np.ones(n_valid, np.float32)]))
                mask_fit = put(np.concatenate(
                    [valid_rows, np.zeros(n_valid, bool)]))
                raw_v = np.broadcast_to(
                    np.asarray(booster.init_score, np.float32)[None, :],
                    (n_valid, K)).copy()
                if init_model is not None and booster.trees:
                    raw_v += (booster._predict_raw_np(vX)
                              - booster.init_score[None, :]
                              ).astype(np.float32)
                raw_fit = put(np.concatenate([raw_np, raw_v])
                              .astype(np.float32))
            bins_t = (grower._get_bins_t(bins_dev)
                      if grower.hist_impl != "xla" else None)

            _, stacked = boost_loop_device(
                bins_dev, bins_t, y_fit, w_fit, mask_fit, raw_fit,
                obj.grad_hess,  # cached objective => stable jit cache key
                params.num_iterations, K, params.growth(),
                grower.is_categorical, None, grower.n_features,
                grower.n_bins, grower.hist_impl, shrink,
                obj.renew_quantile, n_valid=n_valid,
                metric_fn=device_metric[0] if device_metric else None,
                rng_key=jax.random.PRNGKey(params.seed),
                bagging_fraction=params.bagging_fraction,
                bagging_freq=params.bagging_freq,
                goss=is_goss, top_rate=params.top_rate,
                other_rate=params.other_rate,
                feature_fraction=params.feature_fraction,
                n_real=n, it_offset=start_iter)
            host = jax.device_get(stacked)  # ONE fetch for the whole fit
            kept = params.num_iterations
            if device_metric is not None:
                # replay the host loop's stopping rule over the fetched
                # per-iteration metric series (same comparisons, same
                # messages — only the evaluation moved on device)
                _, higher = device_metric
                for it in range(params.num_iterations):
                    val = float(host["metric"][it])
                    improved = (best_metric is None or
                                (val > best_metric if higher
                                 else val < best_metric))
                    if improved:
                        best_metric, best_iter, rounds_no_improve = \
                            val, it, 0
                    else:
                        rounds_no_improve += 1
                    if rounds_no_improve >= params.early_stopping_round:
                        kept = it + 1
                        booster.best_iteration = best_iter
                        print(f"[gbdt] early stop at iter {it + 1}; "
                              f"best iter {best_iter + 1} "
                              f"{metric_name}={best_metric:.6f}")
                        break
            for it in range(kept):
                booster.trees.append([tree_from_arrays(
                    mapper, host["feature"][it][k],
                    host["threshold_bin"][it][k],
                    host["missing_left"][it][k], host["categorical"][it][k],
                    host["cat_mask"][it][k], host["left"][it][k],
                    host["right"][it][k], host["value"][it][k],
                    host["gain"][it][k], int(host["n_nodes"][it][k]))
                    for k in range(K)])
            if booster.best_iteration < 0:
                booster.best_iteration = len(booster.trees) - 1
            booster.__dict__.pop("_mdc", None)
            booster.__dict__.pop("_tree_dev", None)
            return booster

        bag_mask_host = None   # persisted bag between bagging redraws
        for it in range(start_iter, start_iter + params.num_iterations):
            # -- dart: drop trees for this round's gradient computation
            # (drop indices are relative to THIS run's trees,
            # tree_raw_contribs[d] <-> booster.trees[start_iter + d])
            dropped: List[int] = []
            if is_dart and tree_raw_contribs and rng.random() >= params.skip_drop:
                k_drop = min(max(1, int(params.drop_rate * len(tree_raw_contribs))),
                             params.max_drop)
                dropped = list(rng.choice(len(tree_raw_contribs),
                                          size=k_drop, replace=False))
            raw_for_grad = raw
            if dropped:
                raw_for_grad = raw - sum(tree_raw_contribs[d] for d in dropped)

            if is_rf:
                base = jnp.broadcast_to(
                    jnp.asarray(booster.init_score, jnp.float32)[None, :],
                    (n_padded, K))
                grad, hess = grad_fn(_squeeze(base, K), y_dev, w)
            else:
                grad, hess = grad_fn(_squeeze(raw_for_grad, K), y_dev, w)
            grad = _unsqueeze(grad, K)
            hess = _unsqueeze(hess, K)

            # -- row sampling: bagging / goss (over real rows only)
            sample = valid_rows.copy()
            goss_amp = None
            if is_goss and it >= 1:
                g_abs = np.abs(np.asarray(jnp.sum(jnp.abs(grad), axis=1)))
                g_abs[~valid_rows] = -np.inf  # pad rows never sampled
                n_top = int(params.top_rate * n)
                n_other = int(params.other_rate * n)
                top_idx = np.argpartition(-g_abs, max(n_top - 1, 0))[:n_top]
                rest = np.setdiff1d(np.flatnonzero(valid_rows), top_idx,
                                    assume_unique=False)
                other_idx = rng.choice(rest, size=min(n_other, len(rest)),
                                       replace=False)
                sample = np.zeros(n_padded, dtype=bool)
                sample[top_idx] = True
                sample[other_idx] = True
                goss_amp = np.ones(n_padded, dtype=np.float32)
                goss_amp[other_idx] = (1.0 - params.top_rate) / max(
                    params.other_rate, 1e-12)
            elif params.bagging_fraction < 1.0 and (
                    is_rf or params.bagging_freq > 0):
                # LightGBM semantics: redraw every bagging_freq
                # iterations (rf: every iteration), and the bag PERSISTS
                # between redraws — intermediate iterations train on the
                # held bag, not on the full data
                if (is_rf or it % params.bagging_freq == 0
                        or bag_mask_host is None):
                    bag_mask_host = valid_rows & (
                        rng.random(n_padded) < params.bagging_fraction)
                sample = bag_mask_host

            # -- feature sampling: exactly int(frac * F) columns without
            # replacement per iteration (LightGBM's count semantics)
            feat_mask = None
            if params.feature_fraction < 1.0:
                k_keep = max(int(params.feature_fraction * F), 1)
                keep = np.zeros(F, dtype=bool)
                keep[rng.permutation(F)[:k_keep]] = True
                feat_mask = keep

            sample_dev = put(sample)
            amp_dev = put(goss_amp) if goss_amp is not None else None

            iter_trees: List[Tree] = []
            new_contrib = jnp.zeros((n_padded, K), jnp.float32)
            for k in range(K):
                gk, hk = grad[:, k], hess[:, k]
                if amp_dev is not None:
                    gk, hk = gk * amp_dev, hk * amp_dev
                fm_dev = None
                if feat_mask is not None:
                    # excluded at split-finding time (find_best_split), so
                    # the bin matrix is never copied per iteration
                    fm_dev = jnp.asarray(np.pad(
                        feat_mask, (0, bins.shape[1] - len(feat_mask))))
                renew = None
                if obj.renew_quantile is not None:
                    # L1/quantile: the grower renews leaf outputs to the
                    # residual quantile over each leaf's sampled rows
                    # (LightGBM RenewTreeOutput) before shrinkage. The
                    # residual is taken against the same scores the
                    # gradients used (RF trees fit y - init, not the
                    # accumulated ensemble).
                    scores = base if is_rf else raw_for_grad
                    renew = {"q": obj.renew_quantile,
                             "residual": y_dev - _squeeze(scores, K),
                             "weights": w}
                tree, row_vals, _ = grower.grow(
                    bins, gk, hk, sample_dev, shrink, feat_mask=fm_dev,
                    renew=renew)
                iter_trees.append(tree)
                new_contrib = new_contrib.at[:, k].add(row_vals)

            # -- dart normalization
            if dropped:
                factor = len(dropped) / (len(dropped) + params.learning_rate)
                # scale new tree and re-add scaled dropped trees
                new_contrib = new_contrib * (params.learning_rate /
                                             (len(dropped) + params.learning_rate))
                for k in range(K):
                    iter_trees[k].value *= (params.learning_rate /
                                            (len(dropped) + params.learning_rate))
                for d in dropped:
                    tree_raw_contribs[d] = tree_raw_contribs[d] * factor
                    for t in booster.trees[start_iter + d]:
                        t.value *= factor
                raw = raw_for_grad + new_contrib + sum(
                    tree_raw_contribs[d] for d in dropped)
            else:
                raw = raw + new_contrib

            booster.trees.append(iter_trees)
            booster.__dict__.pop("_mdc", None)       # tree set changed
            booster.__dict__.pop("_tree_dev", None)  # (incl. dart rescale)
            if is_dart:
                tree_raw_contribs.append(new_contrib)

            # -- eval + early stopping
            if valid_sets and (params.early_stopping_round > 0 or log_every):
                if valid_eval is None:
                    valid_eval = _ValidEval(booster, valid_sets[0][0])
                vy = valid_sets[0][1]
                vpred = valid_eval.predict()
                val, higher = eval_metric(metric_name, vy, vpred, obj,
                                          params.alpha,
                                          params.tweedie_variance_power)
                improved = (best_metric is None or
                            (val > best_metric if higher else val < best_metric))
                if improved:
                    best_metric, best_iter, rounds_no_improve = val, it, 0
                else:
                    rounds_no_improve += 1
                if log_every and (it + 1) % log_every == 0:
                    print(f"[gbdt] iter {it + 1} valid {metric_name}={val:.6f}")
                if (params.early_stopping_round > 0 and
                        rounds_no_improve >= params.early_stopping_round):
                    booster.best_iteration = best_iter
                    print(f"[gbdt] early stop at iter {it + 1}; "
                          f"best iter {best_iter + 1} "
                          f"{metric_name}={best_metric:.6f}")
                    break
            elif log_every and (it + 1) % log_every == 0:
                print(f"[gbdt] iter {it + 1}")

        if booster.best_iteration < 0:
            booster.best_iteration = len(booster.trees) - 1
        return booster

    # -- prediction ---------------------------------------------------------

    def _tree_to_arrays(self, t: Tree) -> Dict[str, Any]:
        B = self.mapper.max_bins_total
        cm = t.cat_mask
        if cm.shape[1] < B:
            cm = np.pad(cm, ((0, 0), (0, B - cm.shape[1])))
        return {
            "feature": jnp.asarray(t.feature),
            "threshold": jnp.asarray(t.threshold, dtype=jnp.float32),
            "missing_left": jnp.asarray(t.missing_left),
            "categorical": jnp.asarray(t.categorical),
            "cat_mask": jnp.asarray(cm),
            "left": jnp.asarray(t.left),
            "right": jnp.asarray(t.right),
            "value": jnp.asarray(t.value),
        }

    def _tree_arrays(self) -> List[List[Dict[str, Any]]]:
        """Device-resident tree constants, uploaded ONCE per tree set —
        per-call uploads would dominate serving micro-batch latency.
        Invalidated (with ``_mdc``) wherever the tree set or leaf values
        change."""
        if not hasattr(self, "_tree_dev"):
            self._tree_dev = [[self._tree_to_arrays(t) for t in iteration]
                              for iteration in self.trees]
        return self._tree_dev

    def _cat_bins(self, X: np.ndarray) -> np.ndarray:
        """Bin-space values for categorical features (0 elsewhere)."""
        if not any(self.mapper.categorical):
            return np.zeros(X.shape, dtype=np.int32)
        bins = self.mapper.transform(np.asarray(X, dtype=np.float64))
        keep = np.asarray(self.mapper.categorical)
        return np.where(keep[None, :], bins, 0).astype(np.int32)

    def predict_raw(self, X: np.ndarray,
                    num_iteration: Optional[int] = None) -> np.ndarray:
        X = np.asarray(X, dtype=np.float64)
        zf = getattr(self, "zero_missing_features", None)
        if zf:
            # imported LightGBM missing_type=Zero (zero_as_missing=true):
            # |x| <= 1e-35 is missing on these features and routes to the
            # node's default side — pre-map to NaN so the ordinary
            # missing_left routing reproduces LightGBM's NumericalDecision
            X = X.copy()
            for j in zf:
                col = X[:, j]
                X[:, j] = np.where(np.abs(col) <= 1e-35, np.nan, col)
        n = X.shape[0]
        K = self.obj.num_model_outputs
        stop = (num_iteration if num_iteration is not None
                else self.best_iteration + 1) or len(self.trees)
        raw = np.broadcast_to(self.init_score[None, :], (n, K)).copy()
        if n == 0 or not self.trees:
            return raw
        # bucket the row count: serving feeds arbitrary micro-batch sizes,
        # and every distinct shape is a fresh compile of the jitted
        # traversal; bucketing keeps the set of compiled shapes small
        # (bin BEFORE padding — transform is per-row CPU work)
        from mmlspark_tpu.parallel import pad_to_bucket
        cat_bins = self._cat_bins(X)
        X, _ = pad_to_bucket(X)
        cat_bins, _ = pad_to_bucket(cat_bins)
        X_dev = jnp.asarray(X)
        acc = jnp.zeros((X.shape[0], K), dtype=jnp.float32)
        cat_bins_dev = jnp.asarray(cat_bins)
        for iteration in self._tree_arrays()[:stop]:
            for k, arrs in enumerate(iteration):
                acc = acc.at[:, k].add(
                    predict_tree_raw(arrs, X_dev, cat_bins_dev,
                                     depth_bucket(self._max_depth_cache())))
        raw = raw + np.asarray(acc, dtype=np.float64)[:n]
        if self.params.boosting_type == "rf":
            raw = (self.init_score[None, :]
                   + (raw - self.init_score[None, :]) / max(stop, 1))
        return raw

    def _max_depth_cache(self) -> int:
        if not hasattr(self, "_mdc"):
            self._mdc = max((t.max_depth() for it in self.trees for t in it),
                            default=0)
        return self._mdc

    def _predict_raw_np(self, X: np.ndarray) -> np.ndarray:
        return self.predict_raw(X, num_iteration=len(self.trees))

    def predict(self, X: np.ndarray,
                num_iteration: Optional[int] = None) -> np.ndarray:
        raw = self.predict_raw(X, num_iteration)
        out = np.asarray(self.obj.transform(jnp.asarray(raw)))
        if self.obj.num_model_outputs == 1:
            return out[:, 0]
        return out

    # -- introspection ------------------------------------------------------

    def feature_importances(self, importance_type: str = "split") -> np.ndarray:
        """Parity: LGBM_BoosterFeatureImportance (split counts or gains)."""
        imp = np.zeros(len(self.feature_names))
        for iteration in self.trees:
            for t in iteration:
                for i in range(t.n_nodes):
                    f = t.feature[i]
                    if f >= 0:
                        imp[f] += 1 if importance_type == "split" else \
                            float(t.gain[i])
        return imp

    @property
    def num_total_iterations(self) -> int:
        return len(self.trees)

    # -- persistence (parity: SaveModelToString/LoadModelFromString) --------

    def model_to_string(self) -> str:
        d = {
            "format": "mmlspark_tpu.gbdt.v1",
            "params": dataclasses.asdict(self.params),
            "mapper": self.mapper.to_json(),
            "objective": self.obj.name,
            "num_class": self.params.num_class,
            "feature_names": self.feature_names,
            "init_score": self.init_score.tolist(),
            "best_iteration": self.best_iteration,
            "trees": [[t.to_json() for t in it] for it in self.trees],
        }
        # imported-LightGBM predict-time state must survive the json
        # roundtrip too (the text format carries these in its own
        # encoding: sigmoid in the objective spec, Zero missing in
        # decision_type)
        sigmoid = getattr(self, "lgbm_sigmoid", 1.0)
        if sigmoid != 1.0:
            d["lgbm_sigmoid"] = sigmoid
        zf = getattr(self, "zero_missing_features", None)
        if zf:
            d["zero_missing_features"] = sorted(int(j) for j in zf)
        return json.dumps(d)

    def to_lightgbm_string(self) -> str:
        """Export as LightGBM's text model format (the reverse of the
        importer; reference `LightGBMBooster.saveNativeModel`)."""
        from mmlspark_tpu.gbdt.lgbm_compat import to_lightgbm_text
        return to_lightgbm_text(self)

    @staticmethod
    def from_string(s: str) -> "Booster":
        from mmlspark_tpu.gbdt.lgbm_compat import (
            from_lightgbm_text, is_lightgbm_text)
        if is_lightgbm_text(s):
            return from_lightgbm_text(s)
        d = json.loads(s)
        params = BoosterParams(**d["params"])
        mapper = BinMapper.from_json(d["mapper"])
        obj = get_objective(params.objective, params.num_class,
                            params.alpha, params.tweedie_variance_power)
        b = Booster(params, mapper, obj, d["feature_names"])
        b.init_score = np.asarray(d["init_score"], dtype=np.float64)
        b.best_iteration = d["best_iteration"]
        b.trees = [[Tree.from_json(t) for t in it] for it in d["trees"]]
        sigmoid = float(d.get("lgbm_sigmoid", 1.0))
        if sigmoid != 1.0:
            from mmlspark_tpu.gbdt.objectives import jax_sigmoid
            b.obj = dataclasses.replace(
                b.obj, transform=lambda raw, k=sigmoid: jax_sigmoid(k * raw))
            b.lgbm_sigmoid = sigmoid
        if d.get("zero_missing_features"):
            b.zero_missing_features = frozenset(
                int(j) for j in d["zero_missing_features"])
        return b

    def merge(self, other: "Booster") -> "Booster":
        """Append another booster's trees (parity: LGBM_BoosterMerge)."""
        self.trees.extend(other.trees)
        self.best_iteration = len(self.trees) - 1
        self.__dict__.pop("_mdc", None)
        self.__dict__.pop("_tree_dev", None)
        return self


class _ValidEval:
    """Incremental validation scorer for the training loop.

    Bins/uploads the validation set once and accumulates only the newly
    added iterations' raw scores each eval round (the naive path re-binned
    the set and re-uploaded every tree each round — O(T^2) over training).
    DART mutates the leaf values of already-scored trees when it drops
    them, so DART falls back to a full re-score per eval.
    """

    def __init__(self, booster: "Booster", vx: np.ndarray):
        self.booster = booster
        self.vx = np.asarray(vx, dtype=np.float64)
        self.cat_bins_dev = jnp.asarray(booster._cat_bins(self.vx))
        self.X_dev = jnp.asarray(self.vx)
        K = booster.obj.num_model_outputs
        self.acc = jnp.zeros((len(self.vx), K), dtype=jnp.float32)
        self.done = 0

    def predict(self) -> np.ndarray:
        b = self.booster
        if b.params.boosting_type == "dart":
            return b.predict(self.vx, num_iteration=len(b.trees))
        for iteration in b.trees[self.done:]:
            for k, t in enumerate(iteration):
                arrs = b._tree_to_arrays(t)
                self.acc = self.acc.at[:, k].add(
                    predict_tree_raw(arrs, self.X_dev, self.cat_bins_dev,
                                     depth_bucket(t.max_depth())))
        self.done = len(b.trees)
        raw = np.asarray(self.acc, dtype=np.float64) + b.init_score[None, :]
        if b.params.boosting_type == "rf":
            raw = (b.init_score[None, :]
                   + (raw - b.init_score[None, :]) / max(self.done, 1))
        out = np.asarray(b.obj.transform(jnp.asarray(raw)))
        return out[:, 0] if b.obj.num_model_outputs == 1 else out


def _weights(w: Optional[np.ndarray], n: int) -> np.ndarray:
    return np.ones(n, dtype=np.float32) if w is None \
        else np.asarray(w, dtype=np.float32)


def _squeeze(raw, K: int):
    return raw[:, 0] if K == 1 else raw


def _unsqueeze(g, K: int):
    return g[:, None] if K == 1 else g
