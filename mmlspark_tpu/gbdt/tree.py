"""Histogram building, split finding, and leaf-wise tree growth.

The TPU rebuild of LightGBM's serial tree learner + its distributed
variants (reference: `LGBM_BoosterUpdateOneIter` hot loop,
`TrainUtils.scala:95-146`; `tree_learner=data/feature/voting`,
`LightGBMParams.scala:13-18`). All device work is jitted with static
shapes:

- **histograms** are one XLA scatter-add over (rows x features) into a
  flat (F*B, 3) accumulator — when the row arrays are sharded over the
  mesh's ``data`` axis, GSPMD turns the reduction into the ICI psum that
  replaces LightGBM's TCP-socket allreduce;
- **split finding** is a vectorized cumsum scan over every (feature, bin)
  at once, with L1/L2 regularization, min-child constraints, missing-bin
  default directions, and G/H-sorted categorical subset splits;
- **leaf-wise growth** keeps the best-split-per-leaf frontier and splits
  the globally best leaf until ``num_leaves`` (LightGBM's growth policy),
  using the parent-minus-child histogram subtraction trick.

Trees are stored as flat arrays (feature/threshold/children/value per
node) so batched prediction is a short gather loop on device.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from mmlspark_tpu.gbdt.binning import MISSING_BIN


@dataclasses.dataclass(frozen=True)
class GrowthParams:
    num_leaves: int = 31
    max_depth: int = -1  # -1 = unlimited (bounded by num_leaves)
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0


# ---------------------------------------------------------------------------
# Histograms
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n_features", "n_bins"))
def build_histogram(bins, grad, hess, in_leaf, n_features: int, n_bins: int):
    """Per-(feature, bin) sums of grad/hess/count for rows where ``in_leaf``.

    bins: (n, F) int32; grad/hess: (n,) f32; in_leaf: (n,) bool.
    Returns (F, B, 3) float32: [sum_grad, sum_hess, count].
    """
    mask = in_leaf.astype(jnp.float32)
    offsets = jnp.arange(n_features, dtype=jnp.int32) * n_bins
    flat_idx = (bins + offsets[None, :]).reshape(-1)          # (n*F,)
    vals = jnp.stack([grad * mask, hess * mask, mask], axis=1)  # (n, 3)
    vals = jnp.repeat(vals[:, None, :], n_features, axis=1).reshape(-1, 3)
    hist = jnp.zeros((n_features * n_bins, 3), jnp.float32)
    hist = hist.at[flat_idx].add(vals)
    return hist.reshape(n_features, n_bins, 3)


# ---------------------------------------------------------------------------
# Split finding
# ---------------------------------------------------------------------------

def _leaf_value(g, h, l1, l2):
    g_reg = jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)
    return -g_reg / (h + l2 + 1e-12)


def _split_score(g, h, l1, l2):
    g_reg = jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)
    return jnp.square(g_reg) / (h + l2 + 1e-12)


def split_gain_matrix(hist, is_categorical, params: GrowthParams):
    """All candidate-split gains of one leaf: ((2, F, B) gains, (F, B) order).

    Factored out of :func:`find_best_split` so the distributed learners
    (voting votes, feature-parallel local search — `learners.py`) can
    score candidates with identical math. Slot 0 of the first axis sends
    the missing bin left, slot 1 sends it right.
    """
    F, B, _ = hist.shape
    l1, l2 = params.lambda_l1, params.lambda_l2

    g_tot = jnp.sum(hist[:, :, 0], axis=1)   # (F,)
    h_tot = jnp.sum(hist[:, :, 1], axis=1)
    c_tot = jnp.sum(hist[:, :, 2], axis=1)
    # parent stats are per-leaf constants; feature histograms can disagree
    # on them only when a feature's histogram is masked out (voting mode),
    # so take the row-count-richest feature as the source of truth
    src = jnp.argmax(c_tot)
    parent_score = _split_score(g_tot[src], h_tot[src], l1, l2)

    # --- ordering per feature ---------------------------------------------
    # numeric: natural order. categorical: sort non-empty bins by G/H.
    ratio = hist[:, :, 0] / (hist[:, :, 1] + 1e-12)
    empty = hist[:, :, 2] < 0.5
    cat_key = jnp.where(empty, jnp.inf, ratio)  # empty bins sort last
    cat_order = jnp.argsort(cat_key, axis=1)
    num_order = jnp.broadcast_to(jnp.arange(B), (F, B))
    order = jnp.where(is_categorical[:, None], cat_order, num_order)

    hist_ord = jnp.take_along_axis(hist, order[:, :, None], axis=1)

    def scan_gain(h_ordered, skip_first):
        """Cut after each ordered bin; optionally exclude bin 0 (missing)."""
        g = h_ordered[:, :, 0]
        h = h_ordered[:, :, 1]
        c = h_ordered[:, :, 2]
        if skip_first:  # missing bin routed right: exclude from left sums
            g = g.at[:, 0].set(0.0)
            h = h.at[:, 0].set(0.0)
            c = c.at[:, 0].set(0.0)
        gl = jnp.cumsum(g, axis=1)
        hl = jnp.cumsum(h, axis=1)
        cl = jnp.cumsum(c, axis=1)
        gr = g_tot[:, None] - gl
        hr = h_tot[:, None] - hl
        cr = c_tot[:, None] - cl
        gain = (_split_score(gl, hl, l1, l2) + _split_score(gr, hr, l1, l2)
                - parent_score)
        ok = ((cl >= params.min_data_in_leaf) & (cr >= params.min_data_in_leaf)
              & (hl >= params.min_sum_hessian_in_leaf)
              & (hr >= params.min_sum_hessian_in_leaf))
        return jnp.where(ok, gain, -jnp.inf)

    gain_left = scan_gain(hist_ord, skip_first=False)   # missing goes left
    gain_right = scan_gain(hist_ord, skip_first=True)   # missing goes right
    # categorical uses only the left variant (missing treated as a level)
    gain_right = jnp.where(is_categorical[:, None], -jnp.inf, gain_right)
    # last cut position leaves right side empty -> invalid
    gain_left = gain_left.at[:, B - 1].set(-jnp.inf)
    gain_right = gain_right.at[:, B - 1].set(-jnp.inf)

    return jnp.stack([gain_left, gain_right]), order    # (2, F, B), (F, B)


def find_best_split(hist, is_categorical, params: GrowthParams,
                    feat_mask=None):
    """Best split over all (feature, bin) cut points of one leaf.

    Convenience dict view over :func:`eval_leaf` (the grower uses the
    packed form directly). Numeric features scan bins in index order
    twice — once sending the missing bin left, once right (learned
    default direction); categorical features scan bins in G/H-sorted
    order (LightGBM's many-vs-many).
    """
    packed_dev, order = eval_leaf(hist, is_categorical, params, feat_mask)
    packed = np.asarray(packed_dev)
    feat = int(packed[EV_FEATURE])
    return {
        "gain": float(packed[EV_GAIN]),
        "feature": feat,
        "cut_pos": int(packed[EV_CUT_POS]),
        "missing_left": bool(packed[EV_MISSING_LEFT]),
        "order": order[feat],
        "threshold_bin": int(packed[EV_THRESHOLD_BIN]),
        "leaf_value": float(packed[EV_VALUE]),
        "stats": (float(packed[EV_G]), float(packed[EV_H]),
                  float(packed[EV_COUNT])),
    }


# packed layout of eval_leaf's scalar vector (single host fetch per leaf)
EV_GAIN, EV_FEATURE, EV_CUT_POS, EV_MISSING_LEFT, EV_THRESHOLD_BIN, \
    EV_G, EV_H, EV_COUNT, EV_VALUE = range(9)


@partial(jax.jit, static_argnames=("params",))
def eval_leaf(hist, is_categorical, params: GrowthParams, feat_mask=None):
    """Everything the grower needs about one leaf, in ONE device program:
    best split (gain/feature/cut/missing-direction/threshold-bin), leaf
    totals, and the leaf value — packed into a 9-float vector so the
    host pays a single fetch per leaf instead of ~8 scalar syncs (the
    driver of fit latency when dispatch round-trips are expensive).

    Returns (packed (9,) f32, order (F, B) int32 — stays on device; only
    categorical splits ever materialize a row of it).
    """
    F, B, _ = hist.shape
    both, order = split_gain_matrix(hist, is_categorical, params)
    if feat_mask is not None:
        both = jnp.where(feat_mask[None, :, None], both, -jnp.inf)
    flat = both.reshape(2, -1)
    best_flat = jnp.argmax(flat, axis=1)
    best_gain_lr = jnp.take_along_axis(flat, best_flat[:, None], axis=1)[:, 0]
    direction = jnp.argmax(best_gain_lr)              # 0: missing left
    best_idx = best_flat[direction]
    feat = best_idx // B
    cut_pos = best_idx % B

    c_tot = jnp.sum(hist[:, :, 2], axis=1)
    src = jnp.argmax(c_tot)
    g, h, c = (jnp.sum(hist[src, :, 0]), jnp.sum(hist[src, :, 1]),
               c_tot[src])
    value = _leaf_value(g, h, params.lambda_l1, params.lambda_l2)

    packed = jnp.stack([
        best_gain_lr[direction],
        feat.astype(jnp.float32),
        cut_pos.astype(jnp.float32),
        (direction == 0).astype(jnp.float32),
        order[feat, cut_pos].astype(jnp.float32),     # threshold bin
        g, h, c, value,
    ])
    return packed, order


# ---------------------------------------------------------------------------
# Tree structure
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Tree:
    """Flat-array decision tree (numeric thresholds + categorical masks)."""

    feature: np.ndarray        # (N,) int32; -1 for leaves
    threshold: np.ndarray      # (N,) float64 raw-value threshold
    threshold_bin: np.ndarray  # (N,) int32 bin-space threshold
    missing_left: np.ndarray   # (N,) bool: NaN/unseen routed left?
    categorical: np.ndarray    # (N,) bool: membership split?
    cat_mask: np.ndarray       # (N, B) bool: bins going LEFT for cat splits
    left: np.ndarray           # (N,) int32 child ids
    right: np.ndarray
    value: np.ndarray          # (N,) float32 leaf outputs (post-shrinkage)
    gain: np.ndarray           # (N,) float32 split gains (importance)
    n_nodes: int

    def to_json(self) -> Dict[str, Any]:
        d = {f.name: getattr(self, f.name) for f in dataclasses.fields(self)}
        return {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                for k, v in d.items()}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Tree":
        dtypes = {"feature": np.int32, "threshold": np.float64,
                  "threshold_bin": np.int32, "missing_left": bool,
                  "categorical": bool, "cat_mask": bool,
                  "left": np.int32, "right": np.int32,
                  "value": np.float32, "gain": np.float32}
        kw = {k: (np.asarray(v, dtype=dtypes[k]) if k in dtypes else v)
              for k, v in d.items()}
        return Tree(**kw)

    def max_depth(self) -> int:
        depth = np.zeros(self.n_nodes, dtype=np.int32)
        out = 0
        for i in range(self.n_nodes):
            if self.feature[i] >= 0:
                for ch in (self.left[i], self.right[i]):
                    depth[ch] = depth[i] + 1
                    out = max(out, int(depth[ch]))
        return out


def depth_bucket(d: int) -> int:
    """Round a traversal depth up to a multiple of 8 so the jitted
    traversal compiles for a handful of depth keys, not one per tree."""
    return max(8, (d + 7) // 8 * 8)


@partial(jax.jit, static_argnames=("max_depth",))
def predict_tree_raw(tree_arrays, X, cat_bins, max_depth: int):
    """Batched raw-feature traversal: X (n, F) float -> (n,) leaf values.

    tree_arrays: dict of jnp arrays mirroring Tree fields (immutable per
    tree — cacheable on device); cat_bins: (n, F) int32 bin-space values
    for categorical features (zeros when unused). Jitted with a shape
    cache — callers bucket the row count (Booster.predict_raw) and the
    depth (:func:`depth_bucket`) so serving micro-batches of assorted
    sizes reuse a few executables.
    """
    feature = tree_arrays["feature"]
    threshold = tree_arrays["threshold"]
    missing_left = tree_arrays["missing_left"]
    categorical = tree_arrays["categorical"]
    cat_mask = tree_arrays["cat_mask"]
    bins_for_cat = cat_bins               # (n, F) int32 (0 if not needed)
    left, right = tree_arrays["left"], tree_arrays["right"]
    value = tree_arrays["value"]

    n = X.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)

    def step(node, _):
        feat = feature[node]
        is_leaf = feat < 0
        f = jnp.maximum(feat, 0)
        xv = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        is_nan = jnp.isnan(xv)
        go_left_num = jnp.where(is_nan, missing_left[node], xv <= threshold[node])
        bv = jnp.take_along_axis(bins_for_cat, f[:, None], axis=1)[:, 0]
        go_left_cat = cat_mask[node, bv]
        go_left = jnp.where(categorical[node], go_left_cat, go_left_num)
        nxt = jnp.where(go_left, left[node], right[node])
        return jnp.where(is_leaf, node, nxt), None

    node, _ = jax.lax.scan(step, node, None, length=max_depth + 1)
    return value[node]


# ---------------------------------------------------------------------------
# Leaf-wise grower — on-device program
# ---------------------------------------------------------------------------

@partial(jax.jit,
         static_argnames=("params", "n_features", "n_bins", "hist_impl"))
def grow_tree_device(bins, bins_t, grad, hess, sample_mask, is_categorical,
                     feat_mask, params: GrowthParams, n_features: int,
                     n_bins: int, hist_impl: str):
    """Grow one whole tree as a single ``lax.while_loop`` device program.

    The reference's hot loop is fully native (`TrainUtils.scala:95-146`,
    one `LGBM_BoosterUpdateOneIter` per iteration); the TPU equivalent
    keeps the entire leaf-wise frontier — per-node split records, leaf
    histograms (a slot pool using the parent-minus-child subtraction
    trick), and the row→leaf assignment — in device arrays, so a tree
    costs ONE dispatch and the host pays one fetch per tree instead of
    two round-trips per leaf. Sharded inputs turn the histogram
    reductions into ICI psums exactly as in the per-leaf path.

    Returns the final state dict (node arrays sized ``2*num_leaves-1``,
    ``n_nodes`` counter, per-row assignment).
    """
    L = params.num_leaves
    max_nodes = 2 * L - 1
    B, F = n_bins, n_features

    def hist_fn(in_leaf):
        if hist_impl == "xla":
            return build_histogram(bins, grad, hess, in_leaf, F, B)
        from mmlspark_tpu.gbdt import pallas_hist
        return pallas_hist.build_histogram_pallas(
            bins_t, grad, hess, in_leaf, F, B,
            interpret=(hist_impl == "pallas_interpret"))

    gate = max(params.min_gain_to_split, 0.0)

    def eligible(packed, depth_val):
        ok = packed[EV_COUNT] >= 2 * params.min_data_in_leaf
        if params.max_depth >= 0:
            ok = ok & (depth_val < params.max_depth)
        return ok & (packed[EV_GAIN] > gate)

    # ALL rows are routed through the tree (their raw scores must receive
    # every tree's contribution — LightGBM adds predictions to the full
    # score vector, not just the bag); only sampled rows enter histograms.
    node_of_row = jnp.zeros(bins.shape[0], jnp.int32)
    root_hist = hist_fn(sample_mask)
    root_packed, _ = eval_leaf(root_hist, is_categorical, params, feat_mask)

    state = dict(
        feature=jnp.full(max_nodes, -1, jnp.int32),
        threshold_bin=jnp.zeros(max_nodes, jnp.int32),
        missing_left=jnp.zeros(max_nodes, dtype=bool),
        categorical=jnp.zeros(max_nodes, dtype=bool),
        cat_mask=jnp.zeros((max_nodes, B), dtype=bool),
        left=jnp.zeros(max_nodes, jnp.int32),
        right=jnp.zeros(max_nodes, jnp.int32),
        value=jnp.zeros(max_nodes, jnp.float32).at[0]
            .set(root_packed[EV_VALUE]),
        gain=jnp.zeros(max_nodes, jnp.float32),
        depth=jnp.zeros(max_nodes, jnp.int32),
        fr_packed=jnp.zeros((max_nodes, 9), jnp.float32).at[0]
            .set(root_packed),
        fr_gain=jnp.full(max_nodes, -jnp.inf, jnp.float32).at[0].set(
            jnp.where(eligible(root_packed, 0), root_packed[EV_GAIN],
                      -jnp.inf)),
        slot=jnp.zeros(max_nodes, jnp.int32),
        pool=jnp.zeros((L, F, B, 3), jnp.float32).at[0].set(root_hist),
        node_of_row=node_of_row,
        n_nodes=jnp.int32(1),
        n_leaves=jnp.int32(1),
    )

    def cond(s):
        return (s["n_leaves"] < L) & jnp.isfinite(jnp.max(s["fr_gain"]))

    def body(s):
        leaf = jnp.argmax(s["fr_gain"]).astype(jnp.int32)
        packed = s["fr_packed"][leaf]
        feat = packed[EV_FEATURE].astype(jnp.int32)
        cut_pos = packed[EV_CUT_POS].astype(jnp.int32)
        thr_bin = packed[EV_THRESHOLD_BIN].astype(jnp.int32)
        m_left = packed[EV_MISSING_LEFT] > 0.5
        is_cat = is_categorical[feat]
        pslot = s["slot"][leaf]
        phist = s["pool"][pslot]

        # ordering of the split feature's bins (same math as
        # split_gain_matrix: numeric = index order, categorical = G/H
        # sorted with empty bins last)
        hrow = phist[feat]                                   # (B, 3)
        ratio = hrow[:, 0] / (hrow[:, 1] + 1e-12)
        cat_key = jnp.where(hrow[:, 2] < 0.5, jnp.inf, ratio)
        order_row = jnp.where(is_cat, jnp.argsort(cat_key),
                              jnp.arange(B, dtype=jnp.int32))
        pos_of_bin = jnp.zeros(B, jnp.int32).at[order_row].set(
            jnp.arange(B, dtype=jnp.int32))
        cat_row = pos_of_bin <= cut_pos          # bins going LEFT (cat)

        li = s["n_nodes"]
        ri = s["n_nodes"] + 1

        bins_col = jnp.take(bins, feat, axis=1)
        num_left = jnp.where(bins_col == MISSING_BIN, m_left,
                             (bins_col <= thr_bin)
                             & (bins_col != MISSING_BIN))
        go_left = jnp.where(is_cat, cat_row[bins_col], num_left)
        in_leaf = s["node_of_row"] == leaf
        new_assign = jnp.where(in_leaf & go_left, li,
                               jnp.where(in_leaf, ri, s["node_of_row"]))

        # child histograms: build left, subtract for right
        lhist = hist_fn((new_assign == li) & sample_mask)
        rhist = phist - lhist
        lp, _ = eval_leaf(lhist, is_categorical, params, feat_mask)
        rp, _ = eval_leaf(rhist, is_categorical, params, feat_mask)
        dch = s["depth"][leaf] + 1

        rslot = s["n_leaves"]  # slots allocated sequentially: one per leaf
        return dict(
            feature=s["feature"].at[leaf].set(feat),
            threshold_bin=s["threshold_bin"].at[leaf].set(thr_bin),
            missing_left=s["missing_left"].at[leaf].set(m_left),
            categorical=s["categorical"].at[leaf].set(is_cat),
            cat_mask=s["cat_mask"].at[leaf].set(
                jnp.where(is_cat, cat_row, jnp.zeros(B, dtype=bool))),
            left=s["left"].at[leaf].set(li),
            right=s["right"].at[leaf].set(ri),
            value=s["value"].at[li].set(lp[EV_VALUE])
                .at[ri].set(rp[EV_VALUE]),
            gain=s["gain"].at[leaf].set(packed[EV_GAIN]),
            depth=s["depth"].at[li].set(dch).at[ri].set(dch),
            fr_packed=s["fr_packed"].at[li].set(lp).at[ri].set(rp),
            fr_gain=s["fr_gain"].at[leaf].set(-jnp.inf)
                .at[li].set(jnp.where(eligible(lp, dch), lp[EV_GAIN],
                                      -jnp.inf))
                .at[ri].set(jnp.where(eligible(rp, dch), rp[EV_GAIN],
                                      -jnp.inf)),
            slot=s["slot"].at[li].set(pslot).at[ri].set(rslot),
            pool=s["pool"].at[pslot].set(lhist).at[rslot].set(rhist),
            node_of_row=new_assign,
            n_nodes=s["n_nodes"] + 2,
            n_leaves=s["n_leaves"] + 1,
        )

    return jax.lax.while_loop(cond, body, state)


# ---------------------------------------------------------------------------
# Leaf-wise grower
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=())
def _route_left(bins_col, threshold_bin, missing_left, is_cat, order, cut_pos):
    """Which rows of a split leaf go left, in bin space."""
    # categorical: bin's position in sorted order <= cut_pos
    B = order.shape[0]
    pos_of_bin = jnp.zeros(B, jnp.int32).at[order].set(jnp.arange(B, dtype=jnp.int32))
    cat_left = pos_of_bin[bins_col] <= cut_pos
    num_left = jnp.where(bins_col == MISSING_BIN, missing_left,
                         (bins_col <= threshold_bin) & (bins_col != MISSING_BIN))
    return jnp.where(is_cat, cat_left, num_left)


class TreeGrower:
    """Grows one tree leaf-wise over binned data living on device."""

    def __init__(self, bin_mapper, params: GrowthParams, n_features: int,
                 n_bins: int, hist_impl: str = "xla",
                 tree_learner: str = "data", mesh=None, top_k: int = 20):
        self.mapper = bin_mapper
        self.params = params
        self.n_features = n_features
        self.n_bins = n_bins
        # n_features may exceed the mapper's count (feature-parallel pads
        # the feature dim to the shard multiple); pads are numeric
        cats = list(bin_mapper.categorical)
        cats += [False] * (n_features - len(cats))
        self.is_categorical = jnp.asarray(cats, dtype=bool)
        self.hist_impl = hist_impl       # xla | pallas | pallas_interpret
        self.tree_learner = tree_learner  # data | feature | voting
        self._bins_src = None            # identity key for the cached
        self._bins_t = None              # pre-transposed pallas layout
        self._voting_fn = None
        if tree_learner == "voting" and mesh is not None:
            from mmlspark_tpu.gbdt.learners import make_voting_hist
            self._voting_fn = make_voting_hist(
                mesh, params, self.is_categorical, n_features, n_bins, top_k)

    # voting histograms are exact only on the voted feature subset, which
    # differs between a parent and its children — the parent-minus-child
    # subtraction trick is unsound there, so both children build directly
    @property
    def _no_subtract(self) -> bool:
        return self._voting_fn is not None

    def _hist(self, bins, grad, hess, in_leaf, feat_mask=None):
        """Histogram dispatch: XLA scatter-add, per-feature scatter
        (feature-parallel), voting shard_map, or the Pallas MXU kernel."""
        if self._voting_fn is not None:
            fm = (feat_mask if feat_mask is not None
                  else jnp.ones(self.n_features, bool))
            return self._voting_fn(bins, grad, hess, in_leaf, fm)
        if self.tree_learner == "feature":
            from mmlspark_tpu.gbdt.learners import build_histogram_per_feature
            return build_histogram_per_feature(bins, grad, hess, in_leaf,
                                               self.n_bins)
        if self.hist_impl == "xla":
            return build_histogram(bins, grad, hess, in_leaf,
                                   self.n_features, self.n_bins)
        from mmlspark_tpu.gbdt import pallas_hist
        return pallas_hist.build_histogram_pallas(
            self._get_bins_t(bins), grad, hess, in_leaf,
            self.n_features, self.n_bins,
            interpret=(self.hist_impl == "pallas_interpret"))

    def _get_bins_t(self, bins):
        """Pallas layout of ``bins``, transposed once per fit and reused
        (identity-keyed cache shared by the host and device growers)."""
        if self._bins_src is not bins:
            from mmlspark_tpu.gbdt import pallas_hist
            self._bins_t = pallas_hist.prepare_bins_t(bins)
            self._bins_src = bins
        return self._bins_t

    def grow(self, bins, grad, hess, sample_mask,
             shrinkage: float, feat_mask=None, renew=None
             ) -> Tuple[Tree, jnp.ndarray, jnp.ndarray]:
        """Returns (tree, per-row raw value of the new tree, row→node ids).

        ``renew``: optional ``{"q", "residual", "weights"}`` — L1/quantile
        leaf-output renewal (:func:`renew_leaf_values`) computed inside
        the grower so the device grower still pays ONE host fetch per
        tree (a separate renewal fetch would double the per-tree
        round-trips, which dominate on high-latency links).

        bins (n, F) int32 / grad,hess (n,) f32 / sample_mask (n,) bool —
        all may be sharded over the data axis; everything here is jitted
        calls over them, so GSPMD handles cross-device reduction.

        The ``data`` tree learner grows the whole tree in one device
        program (:func:`grow_tree_device` — one dispatch + one host fetch
        per tree); the feature/voting learners keep the per-leaf host
        loop, whose shard_map histogram programs aren't nested inside a
        ``while_loop``.
        """
        if self.tree_learner == "data" and self._voting_fn is None:
            return self._grow_device(bins, grad, hess, sample_mask,
                                     shrinkage, feat_mask, renew)
        return self._grow_host(bins, grad, hess, sample_mask,
                               shrinkage, feat_mask, renew)

    def _grow_device(self, bins, grad, hess, sample_mask,
                     shrinkage: float, feat_mask=None, renew=None
                     ) -> Tuple[Tree, jnp.ndarray, jnp.ndarray]:
        p = self.params
        bins_t = self._get_bins_t(bins) if self.hist_impl != "xla" else None
        s = grow_tree_device(bins, bins_t, grad, hess, sample_mask,
                             self.is_categorical, feat_mask, p,
                             self.n_features, self.n_bins, self.hist_impl)
        val_dev = s["value"]
        if renew is not None:
            rv, rc = renew_leaf_values(
                s["node_of_row"], renew["residual"], renew["weights"],
                sample_mask, 2 * p.num_leaves - 1, renew["q"])
            val_dev = jnp.where((s["feature"] < 0) & (rc > 0), rv, val_dev)
        # ONE host fetch for the whole tree (renewed values included)
        (feature, threshold_bin, missing_left, categorical, cat_mask,
         left, right, value, gain_arr, n_nodes) = jax.device_get(
            (s["feature"], s["threshold_bin"], s["missing_left"],
             s["categorical"], s["cat_mask"], s["left"], s["right"],
             val_dev, s["gain"], s["n_nodes"]))
        n_nodes = int(n_nodes)
        value_arr = (value * shrinkage).astype(np.float32)
        tree = tree_from_arrays(self.mapper, feature, threshold_bin,
                                missing_left, categorical, cat_mask,
                                left, right, value_arr, gain_arr, n_nodes)

        node_of_row = s["node_of_row"]
        row_vals = (val_dev * shrinkage)[node_of_row]
        return tree, row_vals, node_of_row

    def _grow_host(self, bins, grad, hess, sample_mask,
                   shrinkage: float, feat_mask=None, renew=None
                   ) -> Tuple[Tree, jnp.ndarray, jnp.ndarray]:
        p = self.params
        max_nodes = 2 * p.num_leaves - 1
        B = self.n_bins

        feature = np.full(max_nodes, -1, np.int32)
        threshold = np.zeros(max_nodes, np.float64)
        threshold_bin = np.zeros(max_nodes, np.int32)
        missing_left = np.zeros(max_nodes, bool)
        categorical = np.zeros(max_nodes, bool)
        cat_mask = np.zeros((max_nodes, B), bool)
        left = np.zeros(max_nodes, np.int32)
        right = np.zeros(max_nodes, np.int32)
        value = np.zeros(max_nodes, np.float32)
        gain_arr = np.zeros(max_nodes, np.float32)
        depth = np.zeros(max_nodes, np.int32)

        # ALL rows are routed (every row's raw score receives the tree's
        # contribution, as LightGBM's score updater does); only rows in
        # sample_mask contribute to histograms/split decisions
        node_of_row = jnp.zeros(bins.shape[0], jnp.int32)

        fm = jnp.asarray(feat_mask) if feat_mask is not None else None

        def evaluate(hist):
            """One fused device program + ONE host fetch per leaf."""
            packed_dev, order = eval_leaf(hist, self.is_categorical, p, fm)
            return np.asarray(packed_dev), order

        root_hist = self._hist(bins, grad, hess, sample_mask, feat_mask)
        root_packed, root_order = evaluate(root_hist)
        value[0] = root_packed[EV_VALUE]

        # frontier: leaf id -> (hist, packed scalars, device order)
        frontier: Dict[int, Dict[str, Any]] = {}

        def consider(leaf_id, hist, packed, order):
            if packed[EV_COUNT] < 2 * p.min_data_in_leaf:
                return
            if 0 <= p.max_depth <= depth[leaf_id]:
                return
            if packed[EV_GAIN] > max(p.min_gain_to_split, 0.0):
                frontier[leaf_id] = {"hist": hist, "packed": packed,
                                     "order": order}

        consider(0, root_hist, root_packed, root_order)
        n_nodes = 1
        n_leaves = 1

        while n_leaves < p.num_leaves and frontier:
            # split the leaf with the globally best gain (leaf-wise policy)
            leaf_id = max(frontier,
                          key=lambda k: frontier[k]["packed"][EV_GAIN])
            entry = frontier.pop(leaf_id)
            packed = entry["packed"]
            feat = int(packed[EV_FEATURE])
            cut_pos = int(packed[EV_CUT_POS])
            is_cat = bool(self.mapper.categorical[feat]) \
                if feat < len(self.mapper.categorical) else False

            li, ri = n_nodes, n_nodes + 1
            n_nodes += 2
            n_leaves += 1

            feature[leaf_id] = feat
            threshold_bin[leaf_id] = int(packed[EV_THRESHOLD_BIN])
            missing_left[leaf_id] = bool(packed[EV_MISSING_LEFT])
            categorical[leaf_id] = is_cat
            gain_arr[leaf_id] = packed[EV_GAIN]
            left[leaf_id], right[leaf_id] = li, ri
            depth[li] = depth[ri] = depth[leaf_id] + 1
            order_row = entry["order"][feat]          # device (B,) int32
            if is_cat:
                # the only path that materializes an order row on host
                order_np = np.asarray(order_row)
                cat_mask[leaf_id, order_np[:cut_pos + 1]] = True
            else:
                threshold[leaf_id] = self.mapper.threshold_value(
                    feat, threshold_bin[leaf_id])

            # route rows
            go_left = _route_left(bins[:, feat],
                                  jnp.int32(threshold_bin[leaf_id]),
                                  jnp.asarray(bool(missing_left[leaf_id])),
                                  jnp.asarray(is_cat),
                                  order_row,
                                  jnp.int32(cut_pos))
            in_leaf = node_of_row == leaf_id
            node_of_row = jnp.where(in_leaf & go_left, li,
                                    jnp.where(in_leaf, ri, node_of_row))

            # child histograms: build smaller side, subtract for the other
            lhist = self._hist(bins, grad, hess,
                               (node_of_row == li) & sample_mask, feat_mask)
            rhist = (self._hist(bins, grad, hess,
                                (node_of_row == ri) & sample_mask, feat_mask)
                     if self._no_subtract else entry["hist"] - lhist)
            # dispatch BOTH children before fetching either: the fetches
            # overlap the other child's device work (one round-trip/split)
            lp_dev, lorder = eval_leaf(lhist, self.is_categorical, p, fm)
            rp_dev, rorder = eval_leaf(rhist, self.is_categorical, p, fm)
            lpacked, rpacked = np.asarray(lp_dev), np.asarray(rp_dev)
            value[li] = lpacked[EV_VALUE]
            value[ri] = rpacked[EV_VALUE]
            consider(li, lhist, lpacked, lorder)
            consider(ri, rhist, rpacked, rorder)

        if renew is not None:
            rv, rc = jax.device_get(renew_leaf_values(
                node_of_row, renew["residual"], renew["weights"],
                sample_mask, max_nodes, renew["q"]))
            is_leaf = (feature < 0) & (rc > 0)
            value = np.where(is_leaf, rv, value)
        value_arr = (value * shrinkage).astype(np.float32)
        tree = Tree(feature=feature[:n_nodes], threshold=threshold[:n_nodes],
                    threshold_bin=threshold_bin[:n_nodes],
                    missing_left=missing_left[:n_nodes],
                    categorical=categorical[:n_nodes],
                    cat_mask=cat_mask[:n_nodes],
                    left=left[:n_nodes], right=right[:n_nodes],
                    value=value_arr[:n_nodes], gain=gain_arr[:n_nodes],
                    n_nodes=n_nodes)

        # training-time prediction of this tree: gather leaf values
        val_dev = jnp.asarray(value_arr)
        row_vals = val_dev[node_of_row]
        return tree, row_vals, node_of_row


# ---------------------------------------------------------------------------
# Leaf-output renewal (L1 / quantile objectives)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("max_nodes", "q"))
def renew_leaf_values(node_of_row, residual, weights, sample_mask,
                      max_nodes: int, q: float):
    """Per-leaf weighted ``q``-quantile of residuals, on device.

    LightGBM renews L1/quantile leaf outputs to the residual percentile
    over the leaf's bagged rows before shrinkage (`RenewTreeOutput` in
    `regression_objective.hpp`; invoked from `GBDT::Train`) — the
    constant-hessian Newton step alone converges far off the optimum.

    One device program per tree, O(n log n) work and O(n + max_nodes)
    memory: rows are sorted by residual then stably regrouped by leaf
    (zero-weight rows pushed to each segment's tail), so each leaf is a
    contiguous residual-ascending segment of its weighted rows; the
    global weight cumsum minus each segment's base gives within-leaf
    cumulative weights, and a scatter-min picks the first row reaching
    the target quantile weight. When the target weight falls strictly
    between two rows' cumulative weights the value is linearly
    interpolated between the bracketing sorted residuals (a pure
    ceiling pick drifts high on small leaves). This interpolates in
    cumulative-*weight* space, which *approximates* — not matches —
    LightGBM's ``PercentileFun`` convention of positional
    ``(cnt-1)*alpha`` interpolation for the unweighted case (e.g. the
    unweighted median of a 2-row leaf is the lower residual here, the
    midpoint in LightGBM); the host-side reference in the tests mirrors
    this rule. Returns ``(values
    (max_nodes,) f32, counts (max_nodes,) f32)``; leaves with zero
    sampled rows keep their caller-side value (count==0 flags them).
    """
    n = residual.shape[0]
    w = jnp.where(sample_mask, weights, 0.0).astype(jnp.float32)
    by_res = jnp.argsort(residual)
    # key = leaf*2 + (weight==0): zero-weight (unsampled) rows regroup to
    # the END of their leaf's segment, so a crossing row's predecessor is
    # always a genuine weighted order statistic of the same leaf
    zero_tail = (w[by_res] <= 0.0).astype(node_of_row.dtype)
    regroup = jnp.argsort(node_of_row[by_res] * 2 + zero_tail, stable=True)
    order = by_res[regroup]
    sorted_leaf = node_of_row[order]
    sorted_w = w[order]
    sorted_res = residual[order].astype(jnp.float32)

    cumw = jnp.cumsum(sorted_w)                       # nondecreasing
    # weight cumsum just before each leaf segment starts, forward-filled
    # (cummax forward-fills because cumw is nondecreasing)
    starts = jnp.concatenate([jnp.array([True]),
                              sorted_leaf[1:] != sorted_leaf[:-1]])
    cumw_prev = jnp.concatenate([jnp.zeros(1, cumw.dtype), cumw[:-1]])
    seg_base = jax.lax.cummax(jnp.where(starts, cumw_prev, 0.0))
    cw_in = cumw - seg_base                           # within-leaf cumsum

    tot = jnp.zeros(max_nodes, jnp.float32).at[sorted_leaf].add(sorted_w)
    target_leaf = jnp.maximum(q * tot, 1e-12)
    pos = jnp.arange(n, dtype=jnp.int32)
    idx = jnp.full(max_nodes, n, jnp.int32).at[sorted_leaf].min(
        jnp.where(cw_in >= target_leaf[sorted_leaf], pos, n))
    first = jnp.full(max_nodes, n, jnp.int32).at[sorted_leaf].min(pos)
    idx_c = jnp.minimum(idx, n - 1)
    v_hi = sorted_res[idx_c]
    # interpolate toward the previous order statistic when the target
    # falls between the two rows' cumulative weights; the segment's
    # first row has no predecessor and is returned as-is
    prev = jnp.maximum(idx_c - 1, 0)
    has_prev = idx_c > first
    cw_lo = jnp.where(has_prev, cw_in[prev], 0.0)
    v_lo = jnp.where(has_prev, sorted_res[prev], v_hi)
    denom = jnp.maximum(cw_in[idx_c] - cw_lo, 1e-12)
    bias = jnp.clip((target_leaf - cw_lo) / denom, 0.0, 1.0)
    values = v_lo + bias * (v_hi - v_lo)
    counts = jnp.zeros(max_nodes, jnp.float32).at[sorted_leaf].add(
        (sorted_w > 0).astype(jnp.float32))
    return values, counts


def tree_from_arrays(mapper, feature, threshold_bin, missing_left,
                     categorical, cat_mask, left, right, value, gain,
                     n_nodes: int) -> Tree:
    """Assemble a :class:`Tree` from fetched node arrays, mapping numeric
    threshold bins to raw-value thresholds (the one rule shared by the
    per-tree grower fetch and the fused whole-fit fetch)."""
    n_mapped = len(mapper.categorical)
    threshold = np.zeros(len(feature), np.float64)
    for i in range(n_nodes):
        if feature[i] >= 0 and not categorical[i] and feature[i] < n_mapped:
            threshold[i] = mapper.threshold_value(int(feature[i]),
                                                  int(threshold_bin[i]))
    return Tree(feature=feature[:n_nodes], threshold=threshold[:n_nodes],
                threshold_bin=threshold_bin[:n_nodes],
                missing_left=missing_left[:n_nodes],
                categorical=categorical[:n_nodes],
                cat_mask=cat_mask[:n_nodes],
                left=left[:n_nodes], right=right[:n_nodes],
                value=np.asarray(value[:n_nodes], np.float32),
                gain=gain[:n_nodes], n_nodes=n_nodes)


# ---------------------------------------------------------------------------
# Whole-fit device loop
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=(
    "grad_hess", "n_iters", "n_outputs", "params", "n_features", "n_bins",
    "hist_impl", "shrinkage", "renew_q", "n_valid", "metric_fn",
    "bagging_fraction", "bagging_freq", "goss", "top_rate", "other_rate",
    "feature_fraction", "n_real", "it_offset"))
def boost_loop_device(bins, bins_t, y, w, valid_mask, init_raw, grad_hess,
                      n_iters: int, n_outputs: int, params: GrowthParams,
                      is_categorical, feat_mask, n_features: int,
                      n_bins: int, hist_impl: str, shrinkage: float,
                      renew_q: Optional[float],
                      n_valid: int = 0, metric_fn=None,
                      rng_key=None,
                      bagging_fraction: float = 1.0, bagging_freq: int = 0,
                      goss: bool = False, top_rate: float = 0.2,
                      other_rate: float = 0.1,
                      feature_fraction: float = 1.0,
                      n_real: int = 0, it_offset: int = 0):
    """The ENTIRE boosting fit as one scanned device program.

    Eligible fits need the host only twice: once to start the scan and
    once to fetch every tree's node arrays at the end — against the
    reference's fully-native hot loop (`TrainUtils.scala:95-146`, one
    `LGBM_BoosterUpdateOneIter` per iteration) this is the TPU shape of
    the same idea, and it removes the per-tree dispatch + fetch
    round-trips that dominate wall-clock on high-latency host<->device
    links.

    Per scan step: gradients from the carried ``(n, K)`` raw scores, one
    :func:`grow_tree_device` tree per model output (K trees for
    multiclass), optional L1/quantile leaf renewal, raw update. Emits
    per-iteration node arrays stacked as ``(n_iters, K, ...)``.
    Returns (final raw, stacked dict).

    Row/feature sampling lives in the scan as device RNG (threefry key
    in the carry) — the reference never pays per-iteration host
    round-trips for sampling modes either (`TrainUtils.scala:95-146`
    covers every boosting mode natively):

    - ``bagging_fraction < 1`` with ``bagging_freq > 0``: a per-row
      Bernoulli mask redrawn every ``freq`` iterations (carried
      between redraws), feeding the same ``in_leaf`` masks the full-data
      fit uses. LightGBM semantics: subsample, no reweighting.
    - ``goss=True``: per iteration (from absolute iteration 1), the
      ``int(top_rate * n_real)`` rows with the largest summed |gradient|
      plus ``int(other_rate * n_real)`` uniformly drawn others, the
      others' grad/hess amplified by ``(1 - top_rate) / other_rate``
      (LightGBM's GOSS estimator).
    - ``feature_fraction < 1``: per-iteration fixed-size feature draw —
      exactly ``max(int(feature_fraction * F), 1)`` columns without
      replacement (LightGBM's count semantics), applied at
      split-finding time.

    The device RNG stream differs from the host loop's numpy stream, so
    sampled fits match the host path in distribution and quality, not
    tree-for-tree (the exact-equality tests cover the deterministic
    modes).

    Validation/early stopping (the reference's in-native eval loop,
    `TrainUtils.scala:105-145`): the caller appends the validation rows
    as the LAST ``n_valid`` rows of ``bins``/``y``/``w`` with
    ``valid_mask`` False there — they are excluded from histograms,
    leaf stats, sampling, and renewal, but :func:`grow_tree_device`
    routes every row, so their raw scores accrue each tree for free.
    Each iteration then emits ``metric_fn(raw[-n_valid:], y[-n_valid:])``
    under ``"metric"``; the host replays the stopping rule on the
    fetched (n_iters,) series and truncates — identical trees, one
    fetch. ``init_raw`` may carry a continuation prior (``init_model``),
    and ``it_offset`` keeps the absolute iteration number for the
    goss warm-up and bagging redraw phases.
    """
    K = n_outputs
    max_nodes = 2 * params.num_leaves - 1
    emit_keys = ("feature", "threshold_bin", "missing_left", "categorical",
                 "cat_mask", "left", "right", "gain", "n_nodes")
    n_total = bins.shape[0]
    vy = y[n_total - n_valid:] if n_valid else None
    bagging = bagging_fraction < 1.0 and bagging_freq > 0 and not goss
    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)

    def iteration(carry, it):
        raw, key, bag_mask = carry
        pred = raw[:, 0] if K == 1 else raw
        g, h = grad_hess(pred, y, w)
        g = g if g.ndim == 2 else g[:, None]
        h = h if h.ndim == 2 else h[:, None]

        amp = None
        if goss:
            key, sub = jax.random.split(key)
            g_abs = jnp.where(valid_mask, jnp.sum(jnp.abs(g), axis=1),
                              -jnp.inf)
            n_top = int(top_rate * n_real)
            n_other = int(other_rate * n_real)
            order = jnp.argsort(-g_abs)
            top_mask = (jnp.zeros(n_total, bool).at[order[:n_top]].set(True)
                        & valid_mask)
            r = jnp.where(valid_mask & ~top_mask,
                          jax.random.uniform(sub, (n_total,)), jnp.inf)
            other_order = jnp.argsort(r)
            other_mask = (jnp.zeros(n_total, bool)
                          .at[other_order[:n_other]].set(True)
                          & valid_mask & ~top_mask)
            warm = (it + it_offset) >= 1   # LightGBM: full first iteration
            sample = jnp.where(warm, top_mask | other_mask, valid_mask)
            amp = jnp.where(
                warm & other_mask,
                (1.0 - top_rate) / max(other_rate, 1e-12), 1.0
            ).astype(jnp.float32)
        elif bagging:
            key, sub = jax.random.split(key)
            # redraw on the freq schedule AND at the scan's first
            # iteration (a continuation whose start_iter is mid-cycle
            # must still open with a fresh bag, like the host loop's
            # "bag_mask_host is None" draw)
            redraw = (((it + it_offset) % bagging_freq) == 0) | (it == 0)
            fresh = valid_mask & (jax.random.uniform(sub, (n_total,))
                                  < bagging_fraction)
            bag_mask = jnp.where(redraw, fresh, bag_mask)
            sample = bag_mask
        else:
            sample = valid_mask

        fm = feat_mask
        if feature_fraction < 1.0:
            key, sub = jax.random.split(key)
            # fixed-size selection without replacement (the k smallest
            # of per-feature uniforms), matching LightGBM's exactly
            # int(frac * F) columns per iteration — a Bernoulli mask's
            # variable count diverges badly at small F (r4 advisor)
            k_keep = max(int(feature_fraction * n_features), 1)
            r = jax.random.uniform(sub, (n_features,))
            keep = (jnp.zeros(n_features, bool)
                    .at[jnp.argsort(r)[:k_keep]].set(True))
            pad_f = bins.shape[1] - n_features
            fm = (jnp.concatenate([keep, jnp.zeros(pad_f, bool)])
                  if pad_f else keep)
            if feat_mask is not None:
                fm = fm & feat_mask

        emits = []
        for k in range(K):  # static unroll: one tree per model output
            gk, hk = g[:, k], h[:, k]
            if amp is not None:
                gk, hk = gk * amp, hk * amp
            s = grow_tree_device(bins, bins_t, gk, hk,
                                 sample, is_categorical, fm,
                                 params, n_features, n_bins, hist_impl)
            val = s["value"]
            if renew_q is not None:  # renewal objectives are all K == 1
                rv, rc = renew_leaf_values(
                    s["node_of_row"], y - raw[:, 0], w,
                    sample, max_nodes, renew_q)
                val = jnp.where((s["feature"] < 0) & (rc > 0), rv, val)
            shrunk = (val * shrinkage).astype(jnp.float32)
            raw = raw.at[:, k].add(shrunk[s["node_of_row"]])
            emit = {kk: s[kk] for kk in emit_keys}
            emit["value"] = shrunk
            emits.append(emit)
        stacked = {kk: jnp.stack([e[kk] for e in emits])
                   for kk in emits[0]}
        if n_valid:
            stacked["metric"] = metric_fn(raw[n_total - n_valid:], vy)
        return (raw, key, bag_mask), stacked

    (raw_out, _, _), stacked = jax.lax.scan(
        iteration, (init_raw, rng_key, valid_mask),
        jnp.arange(n_iters), length=n_iters)
    return raw_out, stacked
