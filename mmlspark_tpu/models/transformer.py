"""SPMD transformer LM: dp/tp/pp/sp/ep over one named device mesh.

The reference's only distribution strategy is data parallelism over Spark
partitions plus MPI data-parallel SGD (`CommandBuilders.scala:108-267`,
SURVEY.md §2.9); tensor/pipeline/sequence/expert parallelism are absent
there. This framework treats them as first-class: a single
``shard_map``-based train step over a mesh with axes

- ``data``   — batch sharding, gradient psum (DP)
- ``seq``    — sequence/context parallelism via ring attention (SP)
- ``model``  — Megatron-style tensor parallelism: attention heads and
               MLP hidden sharded; psum fan-in after out-proj / MLP (TP)
- ``expert`` — MoE experts sharded; psum combine over the axis (EP)
- ``pipe``   — GPipe pipeline: one stage per rank, activations rotate
               with ``ppermute``, microbatches fill the bubble (PP)

Every collective is explicit (psum / ppermute), so the computation maps
1:1 onto ICI; XLA overlaps the ring steps with compute. Any subset of
axes may be absent (size-1 or missing) and the same code runs unchanged
— the test suite exercises the full composition on a virtual 8-device
CPU mesh exactly like a pod run.

Backprop over the manual shardings relies on shard_map's VMA
(varying-manual-axes) type system (``check_vma=True``, the default):
every value carries the set of mesh axes it varies over, psum/ppermute
transpose type-correctly, and gradient reductions for replicated
parameters (the all-reduce a hand-written DP/TP backward would insert)
fall out of autodiff — verified exactly against an unsharded reference
model in tests/test_transformer.py.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.parallel.ring_attention import (
    dense_attention, ring_attention_local,
)
from mmlspark_tpu.parallel.topology import (
    AXIS_DATA, AXIS_EXPERT, AXIS_MODEL, AXIS_PIPE, AXIS_SEQ,
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Architecture + schedule. ``n_stages`` must equal the pipe-axis size."""

    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    d_head: int = 16
    d_ff: int = 128
    n_stages: int = 1
    layers_per_stage: int = 1
    n_experts: int = 0        # 0 = dense MLP; >0 = MoE in every block
    # 0 = dense dispatch (every token through every local expert, psum
    # combine — compute scales with n_experts); > 0 = capacity-factor
    # routing: per-expert token budget ceil(factor * T / E), all_to_all
    # over the expert axis, overflow tokens dropped to the residual —
    # compute scales with the factor, not the expert count
    moe_capacity_factor: float = 0.0
    # Switch-style load-balancing auxiliary loss weight (0 = off). With
    # capacity routing this is what keeps experts from collapsing to a
    # favored few (and overflow drops bounded): per MoE layer,
    # aux = E * sum_e f_e * P_e with f_e the routed-token fraction and
    # P_e the mean router probability — 1.0 at perfect balance.
    moe_aux_weight: float = 0.0
    # experts consulted per token. 1 = Switch-style (combine weight is
    # the raw router probability); k >= 2 = Mixtral-style (weights are
    # the top-k probabilities renormalized to sum to 1). The capacity
    # budget scales with k: C = ceil(factor * T * k / E).
    moe_top_k: int = 1
    # router z-loss weight (0 = off): weight * mean_tokens
    # logsumexp(router_logits)^2 — keeps router logits from drifting
    # large (train instability / bf16 overflow), the ST-MoE regularizer
    # that production MoE configs run alongside the balance aux.
    moe_zloss_weight: float = 0.0
    # capacity-dispatch engine. "sort" (default): stable-sort routings
    # by expert so per-expert queues are CONTIGUOUS runs — dispatch is E
    # dynamic slices and combine is E ascending dynamic-update-slices
    # (no scatter in either direction; the permutation rides a
    # gather-both-ways custom VJP). "scatter": the one-hot cumsum +
    # scatter/gather queue build (kept for A/B and as the golden
    # cross-check — both engines drop the same overflow routings).
    moe_dispatch: str = "sort"
    # routing direction. "token" (default): tokens pick their top-k
    # experts (Switch/Mixtral semantics, needs the balance aux to stay
    # balanced). "expert_choice": each expert picks its top-C tokens
    # (C = ceil(moe_capacity_factor * T_local / E)) from its affinity
    # column — perfectly balanced BY CONSTRUCTION (no aux needed; a
    # token may be served by 0..E experts). Expert choice is applied
    # within each rank's token shard (the standard group-wise form);
    # requires moe_capacity_factor > 0, ignores moe_top_k.
    moe_router: str = "token"
    microbatches: int = 1
    dtype: str = "float32"
    # un-ring-sharded attention engine: "dense" = XLA softmax-attention;
    # "folded" = the feature-major Pallas kernel (heads on the sublane
    # axis — no lane padding at short head dims; custom VJP, nothing
    # (S x S) ever reaches HBM); "flash" = the head-per-program Pallas
    # kernel (for shapes the folded layout can't take); "auto" = folded
    # on TPU from S >= 256 at short head dims (< 128), flash from
    # S >= 2048 otherwise, dense below (at short S, XLA's fused dense
    # path with stored probabilities wins)
    attention_impl: str = "auto"
    # cross-entropy engine for the vocab head: "fused" = the Pallas
    # streaming kernel (ops/fused_ce.py — logit tiles live in VMEM,
    # d_logits never reaches HBM; the move that cut the CE section of
    # the b8/s1024 step from ~8.5 ms of f32 logit round-trips);
    # "fused_interpret" runs it interpreted (CPU tests); "xla" = the
    # einsum + logsumexp path; "auto" = fused on TPU when eligible
    # (d_model lane-aligned), xla otherwise
    ce_impl: str = "auto"

    @property
    def n_layers(self) -> int:
        return self.n_stages * self.layers_per_stage


# ---------------------------------------------------------------------------
# parameters


def _dense(key, shape, scale=0.02):
    return (scale * jax.random.normal(key, shape)).astype(jnp.float32)


def init_params(cfg: TransformerConfig, seed: int = 0) -> Dict[str, Any]:
    """Host pytree. Stage leaves carry a leading ``n_stages`` dim (pipe)."""
    key = jax.random.PRNGKey(seed)
    ks = iter(jax.random.split(key, 16 + 16 * cfg.n_layers))
    p: Dict[str, Any] = {
        "embed": _dense(next(ks), (cfg.vocab, cfg.d_model)),
        "head": _dense(next(ks), (cfg.d_model, cfg.vocab)),
        "final_norm": jnp.ones((cfg.d_model,)),
    }
    blocks: List[Dict[str, Any]] = []
    s, d, h, dh, f = (cfg.n_stages, cfg.d_model, cfg.n_heads, cfg.d_head,
                      cfg.d_ff)
    for _ in range(cfg.layers_per_stage):
        b = {
            "ln1": jnp.ones((s, d)),
            "wq": _dense(next(ks), (s, d, h, dh)),
            "wk": _dense(next(ks), (s, d, h, dh)),
            "wv": _dense(next(ks), (s, d, h, dh)),
            "wo": _dense(next(ks), (s, h, dh, d)),
            "ln2": jnp.ones((s, d)),
        }
        if cfg.n_experts:
            b["router"] = _dense(next(ks), (s, d, cfg.n_experts))
            b["ew1"] = _dense(next(ks), (s, cfg.n_experts, d, f))
            b["ew2"] = _dense(next(ks), (s, cfg.n_experts, f, d))
        else:
            b["w1"] = _dense(next(ks), (s, d, f))
            b["b1"] = jnp.zeros((s, f))
            b["w2"] = _dense(next(ks), (s, f, d))
            b["b2"] = jnp.zeros((s, d))
        blocks.append(b)
    p["blocks"] = blocks
    return p


def param_specs(cfg: TransformerConfig, mesh) -> Dict[str, Any]:
    """PartitionSpec tree matching ``init_params`` for ``mesh``.

    Axes not present in the mesh are dropped from the specs (replicated).
    """
    from jax.sharding import PartitionSpec as P

    names = set(mesh.axis_names)

    def ax(a):
        return a if a in names else None

    pipe, model, expert = ax(AXIS_PIPE), ax(AXIS_MODEL), ax(AXIS_EXPERT)
    specs: Dict[str, Any] = {
        "embed": P(), "head": P(), "final_norm": P(),
    }
    blocks = []
    for _ in range(cfg.layers_per_stage):
        b = {
            "ln1": P(pipe), "ln2": P(pipe),
            "wq": P(pipe, None, model, None),
            "wk": P(pipe, None, model, None),
            "wv": P(pipe, None, model, None),
            "wo": P(pipe, model, None, None),
        }
        if cfg.n_experts:
            b["router"] = P(pipe, None, None)
            b["ew1"] = P(pipe, expert, None, None)
            b["ew2"] = P(pipe, expert, None, None)
        else:
            b["w1"] = P(pipe, None, model)
            b["b1"] = P(pipe, model)
            b["w2"] = P(pipe, model, None)
            b["b2"] = P(pipe, None)
        blocks.append(b)
    specs["blocks"] = blocks
    return specs


# ---------------------------------------------------------------------------
# per-device forward (runs inside shard_map)


@dataclasses.dataclass(frozen=True)
class _Axes:
    """Mesh axes visible to the per-device program (None = absent)."""

    data: Optional[str]
    seq: Optional[str]
    model: Optional[str]
    expert: Optional[str]
    pipe: Optional[str]

    @staticmethod
    def of(mesh) -> "_Axes":
        names = set(mesh.axis_names)
        return _Axes(*(a if a in names else None for a in
                       (AXIS_DATA, AXIS_SEQ, AXIS_MODEL, AXIS_EXPERT,
                        AXIS_PIPE)))


def _size(axis):
    return jax.lax.axis_size(axis) if axis else 1


def _index(axis):
    return jax.lax.axis_index(axis) if axis else jnp.int32(0)


def _psum_if(x, axis):
    return jax.lax.psum(x, axis) if axis else x


def _rmsnorm(x, g, eps=1e-6):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + eps) * g


def _rope(x, pos):
    """Rotary embedding from *global* positions (seq-shard aware)."""
    dh = x.shape[-1]
    freqs = 1.0 / (10000.0 ** (jnp.arange(0, dh, 2) / dh))
    ang = pos[:, None] * freqs[None, :]                  # [S, Dh/2]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out


def _compute_dtype(cfg: TransformerConfig):
    return jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32


def _attention(bp, x, cfg: TransformerConfig, ax: _Axes, pos):
    # mixed precision: the heavy projections AND the two S^2 attention
    # matmuls run in cfg.dtype (bf16 hits the MXU's fast path, f32 MXU
    # accumulation via preferred_element_type — no upcast pass over the
    # scores); rope/softmax and the residual stream stay f32
    dt = _compute_dtype(cfg)
    mm_dt = dt if dt != jnp.float32 else None
    h = _rmsnorm(x, bp["ln1"]).astype(dt)
    q = jnp.einsum("bsd,dhk->bshk", h, bp["wq"].astype(dt)).astype(jnp.float32)
    k = jnp.einsum("bsd,dhk->bshk", h, bp["wk"].astype(dt)).astype(jnp.float32)
    v = jnp.einsum("bsd,dhk->bshk", h, bp["wv"].astype(dt)).astype(jnp.float32)
    q, k = _rope(q, pos), _rope(k, pos)
    if ax.seq:
        # auto_train: the ring module's shared policy resolves to the
        # differentiable folded kernel where it pays off (never the
        # forward-only flash), dense otherwise
        ring_impl = ("auto_train" if cfg.attention_impl == "auto"
                     else "folded" if cfg.attention_impl == "folded"
                     else "dense")
        a = ring_attention_local(q, k, v, ax.seq, causal=True,
                                 compute_dtype=mm_dt,
                                 block_impl=ring_impl)
    else:
        from mmlspark_tpu.parallel.pallas_attention import (
            flash_attention, flash_attention_folded, flash_available,
            folded_available)
        b_, s_, h_, dh_ = q.shape
        impl = cfg.attention_impl
        if impl == "auto":
            # the folded (feature-major) kernel wins from S >= 256 at
            # short head dims (measured at dh=64: 2.1x whole-step at
            # S=1024 and 1.29x at S=256 vs XLA dense —
            # tools/probe_transformer_perf.py); at dh >= 128 its
            # rationale (dodging lane padding) vanishes and it is
            # unmeasured, so those shapes keep the flash kernel's
            # long-S gate; below both, XLA's fused dense attention
            # (which stores p instead of recomputing) is faster
            if folded_available(s_, s_, dh_, h_) and s_ >= 256 and dh_ < 128:
                impl = "folded"
            elif flash_available() and s_ >= 2048:
                impl = "flash"
            else:
                impl = "dense"
        if impl in ("folded", "flash") and mm_dt is not None:
            q, k, v = q.astype(dt), k.astype(dt), v.astype(dt)
        if impl == "folded" and folded_available(s_, s_, dh_, h_):
            a = flash_attention_folded(q, k, v, True)
        elif impl in ("flash", "folded") and flash_available():
            if cfg.attention_impl == "folded":
                # the user named a specific engine and is getting a
                # different one — say so (silent fallback is reserved
                # for 'auto'); folded needs head_dim % 8 == 0, a
                # 128-tileable sequence, AND an (H*Dh x tile) working
                # set inside the VMEM budget (r4 advisor)
                import warnings
                warnings.warn(
                    f"attention_impl='folded' ineligible at shape "
                    f"(S={s_}, head_dim={dh_}, H*Dh={h_ * dh_}) — needs "
                    f"head_dim % 8 == 0, 128-tileable S, and H*Dh "
                    f"within the folded VMEM budget; falling back to "
                    f"the lane-padded flash kernel", stacklevel=2)
            a = flash_attention(q, k, v, True)
        else:
            if cfg.attention_impl in ("folded", "flash"):
                import warnings
                warnings.warn(
                    f"attention_impl={cfg.attention_impl!r} unavailable "
                    f"(backend {jax.default_backend()!r}, S={s_}, "
                    f"head_dim={dh_}, H*Dh={h_ * dh_} — needs a TPU "
                    f"backend and, for 'folded', an eligible "
                    f"shape/VMEM envelope); using dense attention",
                    stacklevel=2)
            a = dense_attention(q, k, v, causal=True, compute_dtype=mm_dt)
    o = jnp.einsum("bshk,hkd->bsd", a.astype(dt),
                   bp["wo"].astype(dt)).astype(jnp.float32)
    return _psum_if(o, ax.model)


def _mlp(bp, x, ax: _Axes, cfg: TransformerConfig):
    dt = _compute_dtype(cfg)
    h = _rmsnorm(x, bp["ln2"]).astype(dt)
    z = jax.nn.relu(jnp.einsum("bsd,df->bsf", h, bp["w1"].astype(dt))
                    + bp["b1"].astype(dt))
    y = jnp.einsum("bsf,fd->bsd", z,
                   bp["w2"].astype(dt)).astype(jnp.float32)
    return _psum_if(y, ax.model) + bp["b2"]


def _route_top_k(probs, k: int):
    """``(weights, experts)`` for the top-k choices, trailing dim k.

    k == 1 keeps Switch semantics (raw top probability); k >= 2 uses the
    Mixtral rule (top-k probabilities renormalized to sum to one).
    """
    vals, idx = jax.lax.top_k(probs, k)
    if k > 1:
        vals = vals / jnp.maximum(
            jnp.sum(vals, axis=-1, keepdims=True), 1e-12)
    return vals, idx


def _pmean_token_axes(x, axes):
    """pmean a token-linear statistic over every token-holding axis."""
    for a in axes:
        if a:
            x = jax.lax.pmean(x, a)
    return x


def _router_stats(probs2d, top, E: int, axes):
    """GLOBAL per-layer routing statistics for the Switch aux loss.

    ``probs2d`` (T_local, E) / ``top`` (T_local,) are this rank's token
    share; returns ``(f, P)`` — routed-fraction and mean-probability
    vectors pmean'd over every token-holding axis in ``axes``. The aux
    ``E * sum_e f_e P_e`` is NONLINEAR in (f, P), so only these linear
    statistics may be averaged across shards (and across microbatches —
    see ``local_loss``); the product is taken once, at the end, from the
    fully aggregated vectors, exactly matching the unsharded golden.
    """
    f = jnp.mean(jax.nn.one_hot(top, E, dtype=jnp.float32), axis=0)
    P = jnp.mean(probs2d.astype(jnp.float32), axis=0)
    for a in axes:
        if a:
            f = jax.lax.pmean(f, a)
            P = jax.lax.pmean(P, a)
    return f, P


@jax.custom_vjp
def _permute_rows(x, order, inv):
    """``x[order]`` with a gather in BOTH autodiff directions.

    A permutation gather's transpose is a scatter in general, but for a
    bijection it equals gathering with the inverse permutation — XLA
    cannot see that, so without this rewrite every sorted-dispatch
    gather would pay a full row-scatter in the backward pass (the exact
    cost the sort exists to avoid)."""
    return x[order]


def _permute_rows_fwd(x, order, inv):
    return x[order], (order, inv)


def _permute_rows_bwd(res, g):
    order, inv = res
    zero = np.zeros(order.shape, dtype=jax.dtypes.float0)
    return g[inv], zero, zero


_permute_rows.defvjp(_permute_rows_fwd, _permute_rows_bwd)


def _sorted_capacity_queues(h_rep, top, wf, E: int, C: int, dt):
    """Counting-sort capacity dispatch: returns ``(disp (E, C, dtype
    dt), combine)`` where ``combine(y (E, C, d) f32) -> (Tk, d) f32``
    routes expert outputs back to routing order with router weights
    applied.

    With only E distinct keys no comparison sort is needed: the one-hot
    cumsum gives each routing its arrival-order slot within its expert,
    ``dest = starts[expert] + slot`` IS the grouping permutation
    (stable by construction — the SAME overflow routings drop as in the
    scatter engine), and its inverse costs one O(Tk) int scatter. Rows
    then move only through permutation gathers (gather in BOTH autodiff
    directions via :func:`_permute_rows`) and per-expert dynamic
    slices; the combine rebuilds sorted rows with ascending
    dynamic-update-slices (group e's tail overlap is always rewritten
    by group e+1). Queue rows beyond an expert's count hold other
    groups' tokens — the keep mask zeroes their contribution, and their
    zero cotangent keeps gradients exact. No row scatter exists in
    either direction of either pass."""
    Tk, d = h_rep.shape
    onehot = jax.nn.one_hot(top, E, dtype=jnp.int32)     # (Tk, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1
    slot = jnp.take_along_axis(pos, top[:, None], axis=1)[:, 0]
    counts = jnp.sum(onehot, axis=0)                     # (E,)
    starts = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1]])
    inv = starts[top] + slot          # routing -> its sorted row (dest)
    order = jnp.zeros((Tk,), jnp.int32).at[inv].set(
        jnp.arange(Tk, dtype=jnp.int32))                 # sorted -> routing
    keep = (slot < C).astype(jnp.float32)                # routing order
    hs = _permute_rows(h_rep, order, inv)                # (Tk, d) sorted
    hs_pad = jnp.concatenate([hs, jnp.zeros((C, d), hs.dtype)])
    disp = jnp.stack([
        jax.lax.dynamic_slice_in_dim(hs_pad, starts[e], C)
        for e in range(E)]).astype(dt)                   # (E, C, d)

    def combine(y):
        y_s = jnp.zeros((Tk + C, d), jnp.float32)
        for e in range(E):
            y_s = jax.lax.dynamic_update_slice_in_dim(
                y_s, y[e], starts[e], 0)
        y_r = _permute_rows(y_s[:Tk], inv, order)        # routing order
        return y_r * (keep * wf)[:, None]

    return disp, combine


def _scatter_capacity_queues(h_rep, top, wf, E: int, C: int, dt):
    """One-hot cumsum + scatter/gather capacity dispatch: the golden
    reference engine :func:`_sorted_capacity_queues` is A/B'd against.
    Same contract: ``(disp (E, C, dtype dt), combine)`` with router
    weights applied on the way back; overflow routings land in a
    scratch column that is sliced away (dispatch) / zero-weighted
    (combine). Shared by the model's ``moe_dispatch='scatter'`` branch
    and ``tools/bench_moe_engines.py``, so the bench times exactly the
    code the model runs."""
    Tk, d = h_rep.shape
    onehot = jax.nn.one_hot(top, E, dtype=jnp.int32)      # (Tk, E)
    # position of each routing within its expert's queue (arrival order)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1
    slot = jnp.take_along_axis(pos, top[:, None], axis=1)[:, 0]
    keep = slot < C
    # overflow routings land in a scratch column C, sliced away
    slot_c = jnp.where(keep, slot, C)
    disp = jnp.zeros((E, C + 1, d), dt).at[top, slot_c].set(
        h_rep.astype(dt))[:, :C]                          # (E, C, d)

    def combine(y):
        y = jnp.pad(y, ((0, 0), (0, 1), (0, 0)))          # overflow row
        return y[top, slot_c] * (keep * wf)[:, None]

    return disp, combine


def _moe_capacity(bp, x, cfg: TransformerConfig, ax: _Axes):
    """Capacity-factor top-k MoE dispatch (the production shape).

    Each token contributes ``moe_top_k`` routings; each rank builds
    per-expert routing queues bounded by ``C = ceil(factor * T * k /
    E)`` (routings beyond an expert's budget drop to the residual),
    ``all_to_all`` over the ``expert`` axis swaps queue shards so every
    rank holds the full cross-rank queues of its LOCAL experts, the
    expert FFNs run as one batched einsum, and a second ``all_to_all``
    routes results home, combined with the top-k router weights
    (:func:`_route_top_k`). Per-token FLOPs scale with the capacity
    factor and k, not ``n_experts`` — unlike :func:`_moe`'s dense
    dispatch, which multiplies every token through every local expert.
    """
    import math
    dt = _compute_dtype(cfg)
    h = _rmsnorm(x, bp["ln2"])
    logits = jnp.einsum("bsd,de->bse", h, bp["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    b, s, d = x.shape
    T, E = b * s, cfg.n_experts
    e_size, e_rank = _size(ax.expert), _index(ax.expert)
    if T % e_size:
        raise ValueError(
            f"capacity MoE dispatch needs local tokens ({T}) divisible "
            f"by the expert axis ({e_size})")
    # activations arrive REPLICATED over the expert axis; treat that
    # axis as extra token parallelism: each rank routes its own token
    # shard, so expert compute per rank scales with T/e_size
    T_sh = T // e_size
    off = e_rank * T_sh
    k = cfg.moe_top_k
    hT = jax.lax.dynamic_slice_in_dim(h.reshape(T, d), off, T_sh)
    wts, experts = _route_top_k(probs.reshape(T, E), k)  # [T, k]
    wts = jax.lax.dynamic_slice_in_dim(wts, off, T_sh)
    experts = jax.lax.dynamic_slice_in_dim(experts, off, T_sh)
    # each (token, choice) routing occupies one queue slot; the budget
    # scales with k so factor=1 still holds everything at perfect balance
    C = max(int(math.ceil(cfg.moe_capacity_factor * T_sh * k / E)), 1)

    top = experts.reshape(T_sh * k)                      # routing slots
    wf = wts.reshape(T_sh * k)
    if cfg.moe_dispatch == "sort":
        # the whole permute/queue chain runs in the compute dtype: the
        # sorted rows are matmul inputs, and bf16 halves the sort-path
        # HBM traffic
        disp, combine = _sorted_capacity_queues(
            jnp.repeat(hT.astype(dt), k, axis=0), top, wf, E, C, dt)
    elif cfg.moe_dispatch == "scatter":
        disp, combine = _scatter_capacity_queues(
            jnp.repeat(hT.astype(dt), k, axis=0), top, wf, E, C, dt)
    else:
        raise ValueError(f"unknown moe_dispatch {cfg.moe_dispatch!r}")

    if ax.expert:
        # queues regrouped so each rank holds the ALL-RANK queues of
        # its local experts: [E, C, d] -> [e_local, e_size*C, d]
        disp = jax.lax.all_to_all(disp, ax.expert, split_axis=0,
                                  concat_axis=1, tiled=True)
    z = jax.nn.relu(jnp.einsum("ecd,edf->ecf", disp,
                               bp["ew1"].astype(dt)))
    y = jnp.einsum("ecf,efd->ecd", z,
                   bp["ew2"].astype(dt)).astype(jnp.float32)
    if ax.expert:
        # route results back to their owner ranks: [E, C, d] again
        y = jax.lax.all_to_all(y, ax.expert, split_axis=1,
                               concat_axis=0, tiled=True)
    yflat = combine(y)                                   # [T_sh*k, d]
    ytok = jnp.sum(yflat.reshape(T_sh, k, d), axis=1)    # combine choices
    f_stat = (jnp.zeros(E, jnp.float32), jnp.zeros(E, jnp.float32))
    if cfg.moe_aux_weight > 0:
        pT = jax.lax.dynamic_slice_in_dim(
            probs.reshape(T, E), off, T_sh)
        # aux counts the FIRST choice (Switch definition) for any k
        f_stat = _router_stats(pT, experts[:, 0], E,
                               (ax.data, ax.seq, ax.expert))
    z_stat = jnp.float32(0.0)
    if cfg.moe_zloss_weight > 0:
        lse = jax.nn.logsumexp(
            jax.lax.dynamic_slice_in_dim(logits.reshape(T, E), off, T_sh),
            axis=-1)
        z_stat = _pmean_token_axes(jnp.mean(jnp.square(lse)),
                                   (ax.data, ax.seq, ax.expert))
    stats = (*f_stat, z_stat)
    # restore expert-axis replication: every rank contributes its own
    # token shard, psum rebuilds the full (invariant) token set
    full = jnp.zeros((T, d), jnp.float32)
    full = jax.lax.dynamic_update_slice_in_dim(full, ytok, off, axis=0)
    return _psum_if(full, ax.expert).reshape(b, s, d), stats


def _moe_expert_choice(bp, x, cfg: TransformerConfig, ax: _Axes):
    """Expert-choice routing (Zhou et al. 2022): each expert picks its
    top-C tokens from its affinity column instead of tokens picking
    experts — per-expert load is exactly C by construction, so no
    balance aux is needed and no overflow drops exist. Applied within
    each rank's token shard (the standard group-wise form at scale);
    the dispatch/return ``all_to_all`` skeleton and token-shard
    parallelism over the ``expert`` axis match :func:`_moe_capacity`.
    The combine weight is the router probability of each (expert,
    token) pick; a token may be served by several experts or none
    (riding the residual).
    """
    import math
    dt = _compute_dtype(cfg)
    h = _rmsnorm(x, bp["ln2"])
    logits = jnp.einsum("bsd,de->bse", h, bp["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    b, s, d = x.shape
    T, E = b * s, cfg.n_experts
    e_size, e_rank = _size(ax.expert), _index(ax.expert)
    if T % e_size:
        raise ValueError(
            f"expert-choice MoE needs local tokens ({T}) divisible by "
            f"the expert axis ({e_size})")
    T_sh = T // e_size
    off = e_rank * T_sh
    hT = jax.lax.dynamic_slice_in_dim(h.reshape(T, d), off, T_sh)
    pT = jax.lax.dynamic_slice_in_dim(probs.reshape(T, E), off, T_sh)
    C = max(int(math.ceil(cfg.moe_capacity_factor * T_sh / E)), 1)

    wts, idx = jax.lax.top_k(pT.T, min(C, T_sh))       # (E, C) over tokens
    disp = hT[idx].astype(dt)                          # (E, C, d)
    if ax.expert:
        disp = jax.lax.all_to_all(disp, ax.expert, split_axis=0,
                                  concat_axis=1, tiled=True)
    z = jax.nn.relu(jnp.einsum("ecd,edf->ecf", disp,
                               bp["ew1"].astype(dt)))
    y = jnp.einsum("ecf,efd->ecd", z,
                   bp["ew2"].astype(dt)).astype(jnp.float32)
    if ax.expert:
        y = jax.lax.all_to_all(y, ax.expert, split_axis=1,
                               concat_axis=0, tiled=True)
    ytok = jnp.zeros((T_sh, d), jnp.float32).at[idx.reshape(-1)].add(
        y.reshape(-1, d) * wts.reshape(-1)[:, None])
    E_ = cfg.n_experts
    # load is balanced by construction: the aux stats stay zero
    stats = (jnp.zeros(E_, jnp.float32), jnp.zeros(E_, jnp.float32))
    z_stat = jnp.float32(0.0)
    if cfg.moe_zloss_weight > 0:
        lse = jax.nn.logsumexp(
            jax.lax.dynamic_slice_in_dim(logits.reshape(T, E), off, T_sh),
            axis=-1)
        z_stat = _pmean_token_axes(jnp.mean(jnp.square(lse)),
                                   (ax.data, ax.seq, ax.expert))
    full = jnp.zeros((T, d), jnp.float32)
    full = jax.lax.dynamic_update_slice_in_dim(full, ytok, off, axis=0)
    return _psum_if(full, ax.expert).reshape(b, s, d), (*stats, z_stat)


def _moe(bp, x, cfg: TransformerConfig, ax: _Axes):
    """Top-1 MoE, experts sharded over ``expert``: each rank runs its
    local experts on its local tokens; psum over the axis combines (the
    gate selects exactly one expert somewhere on the axis). Dense
    dispatch by default; ``cfg.moe_capacity_factor > 0`` switches to
    the capacity-based all_to_all dispatch (:func:`_moe_capacity`).
    Returns ``(y, aux)`` — the load-balancing aux scalar is 0 unless
    ``cfg.moe_aux_weight > 0``."""
    if cfg.moe_router == "expert_choice":
        if cfg.moe_capacity_factor <= 0:
            raise ValueError("moe_router='expert_choice' needs "
                             "moe_capacity_factor > 0 (defines C)")
        return _moe_expert_choice(bp, x, cfg, ax)
    if cfg.moe_router != "token":
        raise ValueError(f"unknown moe_router {cfg.moe_router!r}")
    if cfg.moe_capacity_factor > 0:
        return _moe_capacity(bp, x, cfg, ax)
    dt = _compute_dtype(cfg)
    h = _rmsnorm(x, bp["ln2"])
    # router stays f32 (softmax + routing decisions); the expert
    # matmuls — the MoE's dominant FLOPs — run in cfg.dtype
    logits = jnp.einsum("bsd,de->bse", h, bp["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    wts, experts = _route_top_k(probs, cfg.moe_top_k)    # [b, s, k]
    e_size, e_rank = _size(ax.expert), _index(ax.expert)
    e_local = cfg.n_experts // e_size
    h_c = h.astype(dt)
    y = jnp.zeros_like(x)
    for e in range(e_local):
        gid = e_rank * e_local + e
        sel = jnp.sum((experts == gid).astype(jnp.float32) * wts,
                      axis=-1)                           # [b, s]
        z = jax.nn.relu(jnp.einsum("bsd,df->bsf", h_c,
                                   bp["ew1"][e].astype(dt)))
        z = jnp.einsum("bsf,fd->bsd", z,
                       bp["ew2"][e].astype(dt)).astype(jnp.float32)
        y = y + z * sel[..., None]
    E = cfg.n_experts
    f_stat = (jnp.zeros(E, jnp.float32), jnp.zeros(E, jnp.float32))
    if cfg.moe_aux_weight > 0:
        # tokens are REPLICATED over the expert axis here, so only the
        # data/seq axes hold distinct tokens; the aux counts the FIRST
        # choice (the Switch definition), whatever k is
        f_stat = _router_stats(probs.reshape(-1, E),
                               experts[..., 0].reshape(-1), E,
                               (ax.data, ax.seq))
    z_stat = jnp.float32(0.0)
    if cfg.moe_zloss_weight > 0:
        lse = jax.nn.logsumexp(logits, axis=-1)
        z_stat = _pmean_token_axes(jnp.mean(jnp.square(lse)),
                                   (ax.data, ax.seq))
    return _psum_if(y, ax.expert), (*f_stat, z_stat)


def _stage(stage_blocks, x, cfg: TransformerConfig, ax: _Axes, pos):
    """One pipeline stage = ``layers_per_stage`` transformer blocks.
    Returns ``(x, f_stack, P_stack, z_stack)``: per-block [n_blocks, E]
    routing statistics for the load-balancing aux plus the per-block
    z-loss scalars [n_blocks] (zeros when dense-MLP or the regularizers
    are disabled) — kept as linear stats so microbatches can be averaged
    before the aux's nonlinear product (see ``local_loss``)."""
    fs, Ps, zs = [], [], []
    for bp in stage_blocks:
        x = x + _attention(bp, x, cfg, ax, pos)
        if cfg.n_experts:
            y, (f, P, z) = _moe(bp, x, cfg, ax)
            x = x + y
            fs.append(f)
            Ps.append(P)
            zs.append(z)
        else:
            x = x + _mlp(bp, x, ax, cfg)
    if not fs:
        z = jnp.zeros((len(stage_blocks), max(cfg.n_experts, 1)),
                      jnp.float32)
        return x, z, z, jnp.zeros(len(stage_blocks), jnp.float32)
    return x, jnp.stack(fs), jnp.stack(Ps), jnp.stack(zs)


def local_loss(params, tokens, labels, mask, cfg: TransformerConfig,
               ax: _Axes):
    """Per-device mean-CE loss over the full mesh (replicated scalar).

    GPipe schedule: rank 0 ingests a microbatch per tick, activations
    rotate over ``pipe`` each tick, the last rank collects outputs after
    the ``n_stages - 1``-tick fill; loss is psum'd over pipe+data+seq.
    """
    p_size, p_rank = _size(ax.pipe), _index(ax.pipe)
    m = cfg.microbatches
    b_loc, s_loc = tokens.shape
    if b_loc % m:
        raise ValueError(f"local batch {b_loc} not divisible by "
                         f"microbatches {m}")
    mb = b_loc // m
    pos = _index(ax.seq) * s_loc + jnp.arange(s_loc)     # global positions
    # my stage's blocks: pipe-sharded leaves arrive [1, ...]
    stage_blocks = [{k: v[0] for k, v in bp.items()} for bp in
                    params["blocks"]]
    tok_mb = tokens.reshape(m, mb, s_loc)

    state = jnp.zeros((mb, s_loc, cfg.d_model), jnp.float32)
    out = jnp.zeros((m, mb, s_loc, cfg.d_model), jnp.float32)
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]
    n_blk = len(stage_blocks)
    F_acc = jnp.zeros((n_blk, max(cfg.n_experts, 1)), jnp.float32)
    P_acc = jnp.zeros_like(F_acc)
    Z_acc = jnp.zeros(n_blk, jnp.float32)
    for t in range(m + p_size - 1):
        if t < m:
            inp = params["embed"][tok_mb[t]]             # [mb, S_loc, D]
            state = jnp.where(p_rank == 0, inp, state)
        state, f_t, p_t, z_t = _stage(stage_blocks, state, cfg, ax, pos)
        if cfg.n_experts and (cfg.moe_aux_weight > 0
                              or cfg.moe_zloss_weight > 0):
            # accumulate only ticks where REAL data flows through this
            # rank (fill/drain ticks carry garbage activations); the
            # stats are linear, so averaging them over microbatches then
            # taking the product equals the full-batch aux exactly
            real = ((p_rank <= t) & (t < p_rank + m)).astype(jnp.float32)
            F_acc = F_acc + f_t * real
            P_acc = P_acc + p_t * real
            Z_acc = Z_acc + z_t * real
        o_idx = t - (p_size - 1)
        if o_idx >= 0:
            out = out.at[o_idx].set(
                jnp.where(p_rank == p_size - 1, state, out[o_idx]))
        if p_size > 1 and t < m + p_size - 2:
            state = jax.lax.ppermute(state, ax.pipe, perm)

    h = _rmsnorm(out.reshape(b_loc, s_loc, cfg.d_model), params["final_norm"])
    dt = _compute_dtype(cfg)
    ce_impl = cfg.ce_impl
    if ce_impl == "auto":
        from mmlspark_tpu.ops.fused_ce import fused_ce_available
        ce_impl = ("fused" if fused_ce_available(
            b_loc * s_loc, cfg.d_model, cfg.vocab,
            itemsize=jnp.dtype(dt).itemsize) else "xla")
    if ce_impl in ("fused", "fused_interpret"):
        # the Pallas streaming CE: logit tiles stay in VMEM, d_logits
        # never reaches HBM, and the only large write is one
        # compute-dtype logits copy for the backward (ops/fused_ce.py)
        from mmlspark_tpu.ops.fused_ce import fused_softmax_xent
        ce = fused_softmax_xent(
            h.reshape(b_loc * s_loc, cfg.d_model), params["head"],
            labels.reshape(b_loc * s_loc), compute_dtype=dt,
            interpret=ce_impl == "fused_interpret",
        ).reshape(b_loc, s_loc)
    else:
        # the vocab head is a third of a small LM's forward FLOPs: run
        # the matmul with bf16 inputs + f32 MXU accumulation. The logits
        # COME OUT f32 (preferred_element_type), so there is no separate
        # upcast pass over [b, s, vocab] — the trap that made a plain
        # bf16 head slower
        if dt != jnp.float32:
            logits = jnp.einsum("bsd,dv->bsv", h.astype(dt),
                                params["head"].astype(dt),
                                preferred_element_type=jnp.float32)
        else:
            logits = jnp.einsum("bsd,dv->bsv", h, params["head"])
        # fused CE: logsumexp - gold logit. log_softmax would
        # materialize a second [b, s, vocab] array (logp) just to gather
        # one column — at 32k vocab that is a gigabyte of pure HBM
        # traffic per step
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None],
                                   axis=-1)[..., 0]
        ce = lse - gold
    is_last = (p_rank == p_size - 1).astype(jnp.float32)
    loss_sum = jnp.sum(ce * mask) * is_last
    count = jnp.sum(mask) * is_last
    axes = tuple(a for a in (ax.pipe, ax.data, ax.seq) if a)
    if axes:
        loss_sum = jax.lax.psum(loss_sum, axes)
        count = jax.lax.psum(count, axes)
    loss = loss_sum / jnp.maximum(count, 1.0)
    if cfg.n_experts and cfg.moe_aux_weight > 0:
        # per-layer aux from microbatch-averaged (f, P), summed over
        # this rank's layers, then over all stages (each pipe rank
        # holds different layers)
        aux = cfg.n_experts * jnp.sum((F_acc / m) * (P_acc / m))
        if ax.pipe:
            aux = jax.lax.psum(aux, ax.pipe)
        loss = loss + cfg.moe_aux_weight * aux
    if cfg.n_experts and cfg.moe_zloss_weight > 0:
        # z-loss is already token-linear; microbatch average then sum
        # over this rank's layers and all stages
        zterm = jnp.sum(Z_acc / m)
        if ax.pipe:
            zterm = jax.lax.psum(zterm, ax.pipe)
        loss = loss + cfg.moe_zloss_weight * zterm
    return loss


# ---------------------------------------------------------------------------
# reference (unsharded) forward — golden model for the SPMD tests


def _reference_ec(bp, h, cfg: TransformerConfig, ec_groups: int):
    """Unsharded expert-choice MoE matching the sharded rule: expert
    choice runs WITHIN each token group (a rank's token shard in the
    SPMD step — pass the number of token shards as ``ec_groups``)."""
    import math
    b, s, d = h.shape
    T, E = b * s, cfg.n_experts
    hf = h.reshape(T, d)
    logits = jnp.einsum("td,de->te", hf, bp["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    Tg = T // ec_groups
    C = max(int(math.ceil(cfg.moe_capacity_factor * Tg / E)), 1)
    y = jnp.zeros((T, d), jnp.float32)
    for g in range(ec_groups):
        pg = probs[g * Tg:(g + 1) * Tg]                # (Tg, E)
        hg = hf[g * Tg:(g + 1) * Tg]
        wts, idx = jax.lax.top_k(pg.T, min(C, Tg))     # (E, C)
        z = jax.nn.relu(jnp.einsum("ecd,edf->ecf", hg[idx], bp["ew1"]))
        out = jnp.einsum("ecf,efd->ecd", z, bp["ew2"])
        yg = jnp.zeros((Tg, d), jnp.float32).at[idx.reshape(-1)].add(
            out.reshape(-1, d) * wts.reshape(-1)[:, None])
        y = y.at[g * Tg:(g + 1) * Tg].add(yg)
    lse = jax.nn.logsumexp(logits, axis=-1)
    return y.reshape(b, s, d), jnp.mean(jnp.square(lse))


def _reference_forward(params, tokens, cfg: TransformerConfig,
                       ec_groups: int = 1):
    """Unsharded forward: ``(logits, aux_total, z_total)``."""
    x = params["embed"][tokens]
    pos = jnp.arange(tokens.shape[1])
    aux_total = jnp.float32(0.0)
    z_total = jnp.float32(0.0)
    for s in range(cfg.n_stages):
        for bp_all in params["blocks"]:
            bp = {k: v[s] for k, v in bp_all.items()}
            h = _rmsnorm(x, bp["ln1"])
            q = _rope(jnp.einsum("bsd,dhk->bshk", h, bp["wq"]), pos)
            k = _rope(jnp.einsum("bsd,dhk->bshk", h, bp["wk"]), pos)
            v = jnp.einsum("bsd,dhk->bshk", h, bp["wv"])
            a = dense_attention(q, k, v, causal=True)
            x = x + jnp.einsum("bshk,hkd->bsd", a, bp["wo"])
            h = _rmsnorm(x, bp["ln2"])
            if cfg.n_experts and cfg.moe_router == "expert_choice":
                y, z_layer = _reference_ec(bp, h, cfg, ec_groups)
                x = x + y
                if cfg.moe_zloss_weight > 0:
                    z_total = z_total + z_layer
            elif cfg.n_experts:
                logits = jnp.einsum("bsd,de->bse", h, bp["router"])
                probs = jax.nn.softmax(logits, axis=-1)
                wts, experts = _route_top_k(probs, cfg.moe_top_k)
                y = jnp.zeros_like(x)
                for e in range(cfg.n_experts):
                    sel = jnp.sum((experts == e).astype(jnp.float32)
                                  * wts, axis=-1)
                    z = jax.nn.relu(jnp.einsum("bsd,df->bsf", h, bp["ew1"][e]))
                    z = jnp.einsum("bsf,fd->bsd", z, bp["ew2"][e])
                    y = y + z * sel[..., None]
                x = x + y
                if cfg.moe_aux_weight > 0:
                    f, P = _router_stats(
                        probs.reshape(-1, cfg.n_experts),
                        experts[..., 0].reshape(-1), cfg.n_experts, ())
                    aux_total = aux_total + cfg.n_experts * jnp.sum(f * P)
                if cfg.moe_zloss_weight > 0:
                    lse_r = jax.nn.logsumexp(logits, axis=-1)
                    z_total = z_total + jnp.mean(jnp.square(lse_r))
            else:
                z = jax.nn.relu(
                    jnp.einsum("bsd,df->bsf", h, bp["w1"]) + bp["b1"])
                x = x + jnp.einsum("bsf,fd->bsd", z, bp["w2"]) + bp["b2"]
    h = _rmsnorm(x, params["final_norm"])
    logits = jnp.einsum("bsd,dv->bsv", h, params["head"])
    return logits, aux_total, z_total


def reference_logits(params, tokens, cfg: TransformerConfig):
    """Per-position next-token logits ``[b, s, vocab]`` on one device —
    the scoring entry for sequence-labeling / generation consumers (the
    era analogue of scoring a pretrained BiLSTM tagger, `notebooks/
    samples/DeepLearning - BiLSTM Medical Entity Extraction.ipynb`)."""
    return _reference_forward(params, tokens, cfg)[0]


def reference_loss(params, tokens, labels, mask, cfg: TransformerConfig,
                   ec_groups: int = 1):
    """Same math as the SPMD step on one device: dense attention, dense
    MoE, no pipeline — the golden model for the sharded tests.
    ``ec_groups``: for expert-choice routing, the number of token
    groups the SPMD step shards tokens into (expert choice is
    group-wise; see :func:`_reference_ec`)."""
    logits, aux_total, z_total = _reference_forward(params, tokens, cfg,
                                                    ec_groups)
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    ce = lse - gold
    loss = jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return (loss + cfg.moe_aux_weight * aux_total
            + cfg.moe_zloss_weight * z_total)


# ---------------------------------------------------------------------------
# train step


def _validate_mesh_config(cfg: TransformerConfig, mesh) -> "_Axes":
    """The shared build-time checks of BOTH train-step formulations
    (manual shard_map and pjit): every mesh/config mismatch fails
    loudly at build, never as a cryptic XLA partitioning error."""
    ax = _Axes.of(mesh)
    if ax.pipe and mesh.shape[ax.pipe] != cfg.n_stages:
        raise ValueError(
            f"n_stages={cfg.n_stages} != pipe axis size {mesh.shape[ax.pipe]}")
    if not ax.pipe and cfg.n_stages != 1:
        raise ValueError("n_stages > 1 requires a 'pipe' mesh axis")
    if ax.model and cfg.n_heads % mesh.shape[ax.model]:
        raise ValueError("n_heads must divide over the model axis")
    if ax.model and cfg.d_ff % mesh.shape[ax.model]:
        raise ValueError("d_ff must divide over the model axis")
    if ax.expert and cfg.n_experts and cfg.n_experts % mesh.shape[ax.expert]:
        raise ValueError("n_experts must divide over the expert axis")
    if cfg.n_experts and not 1 <= cfg.moe_top_k <= cfg.n_experts:
        raise ValueError(
            f"moe_top_k={cfg.moe_top_k} must be in [1, n_experts="
            f"{cfg.n_experts}]")
    return ax


def build_spmd_train_step(cfg: TransformerConfig, mesh,
                          learning_rate: float = 0.1,
                          momentum: float = 0.9,
                          donate: bool = True,
                          check_vma: bool = True,
                          impl: str = "auto"):
    """Jitted full train step over ``mesh``: fwd + bwd + per-leaf grad
    psum + momentum-SGD update.

    Two interchangeable formulations exist (``impl``):

    * ``"shard_map"`` — the manual per-device program (explicit
      psum/ppermute/all_to_all; maps 1:1 onto ICI). Needs the VMA-era
      jax: its backward relies on vma types to insert the
      replicated-parameter grad psums.
    * ``"pjit"`` — the same math as ONE global GSPMD program
      (:func:`build_pjit_train_step`): XLA inserts every collective
      from the ``NamedSharding`` annotations, so it runs on pre-VMA
      jaxes too. Fixed-seed parity between the two is test-pinned
      wherever a VMA jax exists.

    ``"auto"`` picks shard_map on a VMA jax and pjit elsewhere —
    which is what deleted the old loud pre-VMA build failure.
    ``check_vma=False`` (test-only; see the warning below) always
    takes the shard_map path: its documented under-reduction boundary
    is itself pinned by tests.

    Returns ``step(params, velocity, tokens, labels, mask) ->
    (params, velocity, loss)`` where params/velocity are device arrays
    laid out per :func:`param_specs`. Replaces the reference's
    mpirun/BrainScript data-parallel SGD chain (`CommandBuilders.scala`)
    with one compiled program; adds tp/pp/sp/ep the reference never had.

    .. warning:: With ``donate=True`` (the default) the ``params`` and
       ``velocity`` arguments are **donated**: their buffers are reused
       for the outputs, and the input arrays are invalidated after the
       call *on TPU/GPU* (CPU ignores donation, so misuse only surfaces
       on accelerator backends). Always rebind, ``params, velocity,
       loss = step(params, velocity, ...)``; callers that must reuse the
       pre-step state (warm-up probes, pre/post diffing) should pass
       ``donate=False``.
    """
    from jax.sharding import PartitionSpec as P

    if impl not in ("auto", "shard_map", "pjit"):
        raise ValueError(f"unknown train-step impl {impl!r}")
    if impl == "auto":
        from mmlspark_tpu.parallel import compat
        # check_vma=False is a shard_map-specific contract (the
        # interpret-mode escape hatch + the documented under-reduction
        # boundary) — it must keep meaning the manual path
        impl = ("shard_map" if not check_vma or compat.vma_native()
                else "pjit")
    if impl == "pjit":
        return build_pjit_train_step(cfg, mesh, learning_rate, momentum,
                                     donate=donate)

    ax = _validate_mesh_config(cfg, mesh)
    specs = param_specs(cfg, mesh)
    data_spec = P(ax.data, ax.seq)

    def local_step(params, velocity, tokens, labels, mask):
        loss, grads = jax.value_and_grad(local_loss)(
            params, tokens, labels, mask, cfg, ax)
        velocity = jax.tree.map(lambda v, g: momentum * v + g,
                                velocity, grads)
        params = jax.tree.map(lambda p, v: p - learning_rate * v,
                              params, velocity)
        return params, velocity, loss

    # check_vma=False exists ONLY for interpret-mode Pallas kernels in
    # CPU tests (the HLO interpreter re-runs the kernel body with
    # vma-typed values, where kernel-internal iota/scratch constants
    # cannot be matched). It is sound only on single-device meshes:
    # without vma types the shard_map transpose does NOT insert the
    # cross-shard psums for replicated-parameter gradients (embed/head),
    # so a real multi-shard mesh silently under-reduces them —
    # tests/test_fused_ce.py pins this boundary from both sides.
    sharded = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, specs, data_spec, data_spec, data_spec),
        out_specs=(specs, specs, P()), check_vma=check_vma)
    # donate params+velocity: the optimizer update happens in place in
    # HBM instead of allocating (and copying into) a second full copy
    # of the model state every step
    return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())


# ---------------------------------------------------------------------------
# pjit (GSPMD) train step — the global-array formulation
#
# The manual shard_map program above expresses every collective
# explicitly; this one expresses NONE: the same math is written over
# the global arrays, params/batch arrive with NamedSharding layouts
# (the identical `param_specs` tree), and XLA/GSPMD inserts the grad
# allreduces and TP/EP collectives from the annotations. Because no
# vma typing is involved, it builds and runs on pre-VMA jaxes — the
# trainer path no longer has a jax-version boundary. The one semantic
# subtlety is capacity-factor MoE: the manual step computes its
# capacity C and drops overflow *per rank's token shard*, so the
# global formulation reproduces that grouping exactly (tokens split
# into data x seq x expert contiguous groups — `_token_groups`), which
# keeps the two formulations bit-comparable drop-for-drop.


def _token_groups(h, D: int, Q: int):
    """Global ``[B, S, ...]`` -> rank-local token blocks
    ``[D*Q, T_local, ...]`` in exactly the manual step's order (batch
    sharded over ``data``, sequence over ``seq``, rows flattened
    batch-major within a rank)."""
    B, S = h.shape[0], h.shape[1]
    rest = h.shape[2:]
    g = h.reshape(D, B // D, Q, S // Q, *rest)
    g = jnp.moveaxis(g, 2, 1)                  # [D, Q, B/D, S/Q, ...]
    return g.reshape(D * Q, (B // D) * (S // Q), *rest)


def _ungroup_tokens(g, D: int, Q: int, B: int, S: int):
    """Inverse of :func:`_token_groups`."""
    rest = g.shape[2:]
    g = g.reshape(D, Q, B // D, S // Q, *rest)
    g = jnp.moveaxis(g, 1, 2)                  # [D, B/D, Q, S/Q, ...]
    return g.reshape(B, S, *rest)


def _pjit_moe_grouped(bp, x, cfg: TransformerConfig, D: int, Q: int,
                      E_ax: int, wsc=None):
    """Capacity-factor token-choice MoE, group-wise: the global twin of
    :func:`_moe_capacity`. Each of the ``D*Q*E_ax`` token groups
    builds its own capacity queues (same engines, same overflow
    drops); expert FFNs run on the full queue set — numerically what
    the manual step's all_to_all round-trip computes."""
    import math
    dt = _compute_dtype(cfg)
    h = _rmsnorm(x, bp["ln2"])
    # jax-0.4.x XLA:CPU SPMD mis-lowers the grouped top-k/queue/
    # scatter chains when their operands carry mesh shardings
    # (repro'd: 1e-3..3e-2 divergence vs the identical eager math on
    # data x expert meshes) — this fallback formulation therefore pins
    # the whole capacity/EC block replicated: forward AND backward
    # then match the unsharded golden exactly. The manual shard_map
    # formulation keeps the truly-parallel dispatch.
    h = wsc(h) if wsc is not None else h
    logits = jnp.einsum("bsd,de->bse", h, bp["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    B, S, d = x.shape
    T, E = B * S, cfg.n_experts
    n_rank = D * Q
    if T % n_rank:
        raise ValueError(f"tokens ({T}) must divide over data x seq "
                         f"({n_rank})")
    T_local = T // n_rank
    if T_local % E_ax:
        raise ValueError(
            f"capacity MoE dispatch needs local tokens ({T_local}) "
            f"divisible by the expert axis ({E_ax})")
    T_sh = T_local // E_ax
    k = cfg.moe_top_k
    C = max(int(math.ceil(cfg.moe_capacity_factor * T_sh * k / E)), 1)
    hg = _token_groups(h, D, Q)                # [n_rank, T_local, d]
    pg = _token_groups(probs, D, Q)            # [n_rank, T_local, E]
    engine = (_sorted_capacity_queues if cfg.moe_dispatch == "sort"
              else _scatter_capacity_queues)
    ew1 = wsc(bp["ew1"]) if wsc is not None else bp["ew1"]
    ew2 = wsc(bp["ew2"]) if wsc is not None else bp["ew2"]
    if cfg.moe_dispatch not in ("sort", "scatter"):
        raise ValueError(f"unknown moe_dispatch {cfg.moe_dispatch!r}")
    out_groups = []
    for g in range(n_rank):
        wts, experts = _route_top_k(pg[g], k)  # [T_local, k]
        parts = []
        for er in range(E_ax):
            sl = slice(er * T_sh, (er + 1) * T_sh)
            top = experts[sl].reshape(T_sh * k)
            wf = wts[sl].reshape(T_sh * k)
            disp, combine = engine(
                jnp.repeat(hg[g][sl].astype(dt), k, axis=0),
                top, wf, E, C, dt)
            z = jax.nn.relu(jnp.einsum("ecd,edf->ecf", disp,
                                       ew1.astype(dt)))
            y = jnp.einsum("ecf,efd->ecd", z,
                           ew2.astype(dt)).astype(jnp.float32)
            yflat = combine(y)                 # [T_sh*k, d]
            parts.append(jnp.sum(yflat.reshape(T_sh, k, d), axis=1))
        out_groups.append(jnp.concatenate(parts, axis=0))
    ytok = jnp.stack(out_groups)               # [n_rank, T_local, d]
    y = _ungroup_tokens(ytok, D, Q, B, S)
    y = wsc(y) if wsc is not None else y       # exit the block replicated
    # aux statistics are token-LINEAR, so the global means equal the
    # manual step's pmean-over-token-axes exactly (equal-size groups)
    E_ = cfg.n_experts
    f_stat = (jnp.zeros(E_, jnp.float32), jnp.zeros(E_, jnp.float32))
    if cfg.moe_aux_weight > 0:
        _, exp_all = _route_top_k(probs.reshape(T, E), k)
        f_stat = _router_stats(probs.reshape(T, E), exp_all[:, 0], E, ())
    z_stat = jnp.float32(0.0)
    if cfg.moe_zloss_weight > 0:
        lse = jax.nn.logsumexp(logits.reshape(T, E), axis=-1)
        z_stat = jnp.mean(jnp.square(lse))
    return y, (*f_stat, z_stat)


def _pjit_moe_expert_choice(bp, x, cfg: TransformerConfig, D: int,
                            Q: int, E_ax: int, wsc=None):
    """Expert-choice routing, group-wise: the global twin of
    :func:`_moe_expert_choice` (experts pick their top-C tokens WITHIN
    each rank-shaped token group)."""
    import math
    dt = _compute_dtype(cfg)
    h = _rmsnorm(x, bp["ln2"])
    # same SPMD-lowering pin as the capacity path (see above)
    h = wsc(h) if wsc is not None else h
    logits = jnp.einsum("bsd,de->bse", h, bp["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    B, S, d = x.shape
    T, E = B * S, cfg.n_experts
    n_rank = D * Q
    T_local = T // n_rank
    if T_local % E_ax:
        raise ValueError(
            f"expert-choice MoE needs local tokens ({T_local}) "
            f"divisible by the expert axis ({E_ax})")
    T_sh = T_local // E_ax
    C = max(int(math.ceil(cfg.moe_capacity_factor * T_sh / E)), 1)
    hg = _token_groups(h, D, Q).reshape(n_rank * E_ax, T_sh, d)
    pg = _token_groups(probs, D, Q).reshape(n_rank * E_ax, T_sh, E)
    # same SPMD-lowering pin as the capacity path (see above)
    ew1 = wsc(bp["ew1"]) if wsc is not None else bp["ew1"]
    ew2 = wsc(bp["ew2"]) if wsc is not None else bp["ew2"]
    outs = []
    for g in range(n_rank * E_ax):
        wts, idx = jax.lax.top_k(pg[g].T, min(C, T_sh))  # (E, C)
        disp = hg[g][idx].astype(dt)
        z = jax.nn.relu(jnp.einsum("ecd,edf->ecf", disp,
                                   ew1.astype(dt)))
        y = jnp.einsum("ecf,efd->ecd", z,
                       ew2.astype(dt)).astype(jnp.float32)
        outs.append(jnp.zeros((T_sh, d), jnp.float32)
                    .at[idx.reshape(-1)]
                    .add(y.reshape(-1, d) * wts.reshape(-1)[:, None]))
    ytok = jnp.stack(outs).reshape(n_rank, T_local, d)
    y = _ungroup_tokens(ytok, D, Q, B, S)
    y = wsc(y) if wsc is not None else y       # exit the block replicated
    E_ = cfg.n_experts
    stats = (jnp.zeros(E_, jnp.float32), jnp.zeros(E_, jnp.float32))
    z_stat = jnp.float32(0.0)
    if cfg.moe_zloss_weight > 0:
        lse = jax.nn.logsumexp(logits.reshape(T, E), axis=-1)
        z_stat = jnp.mean(jnp.square(lse))
    return y, (*stats, z_stat)


def _pjit_moe_dense(bp, x, cfg: TransformerConfig):
    """Dense-dispatch token-choice MoE over the global batch — the
    global twin of :func:`_moe`'s default branch (identical to the
    reference math)."""
    dt = _compute_dtype(cfg)
    h = _rmsnorm(x, bp["ln2"])
    logits = jnp.einsum("bsd,de->bse", h, bp["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    wts, experts = _route_top_k(probs, cfg.moe_top_k)
    h_c = h.astype(dt)
    y = jnp.zeros_like(x)
    for e in range(cfg.n_experts):
        sel = jnp.sum((experts == e).astype(jnp.float32) * wts, axis=-1)
        z = jax.nn.relu(jnp.einsum("bsd,df->bsf", h_c,
                                   bp["ew1"][e].astype(dt)))
        z = jnp.einsum("bsf,fd->bsd", z,
                       bp["ew2"][e].astype(dt)).astype(jnp.float32)
        y = y + z * sel[..., None]
    E = cfg.n_experts
    f_stat = (jnp.zeros(E, jnp.float32), jnp.zeros(E, jnp.float32))
    if cfg.moe_aux_weight > 0:
        f_stat = _router_stats(probs.reshape(-1, E),
                               experts[..., 0].reshape(-1), E, ())
    z_stat = jnp.float32(0.0)
    if cfg.moe_zloss_weight > 0:
        lse = jax.nn.logsumexp(logits, axis=-1)
        z_stat = jnp.mean(jnp.square(lse))
    return y, (*f_stat, z_stat)


def _pjit_moe(bp, x, cfg: TransformerConfig, D: int, Q: int, E_ax: int,
              wsc=None):
    """MoE branch selection mirroring :func:`_moe`, global form."""
    if cfg.moe_router == "expert_choice":
        if cfg.moe_capacity_factor <= 0:
            raise ValueError("moe_router='expert_choice' needs "
                             "moe_capacity_factor > 0 (defines C)")
        return _pjit_moe_expert_choice(bp, x, cfg, D, Q, E_ax, wsc)
    if cfg.moe_router != "token":
        raise ValueError(f"unknown moe_router {cfg.moe_router!r}")
    if cfg.moe_capacity_factor > 0:
        return _pjit_moe_grouped(bp, x, cfg, D, Q, E_ax, wsc)
    return _pjit_moe_dense(bp, x, cfg)


def _pjit_attention(bp, x, cfg: TransformerConfig, pos):
    """Global-batch attention with the manual step's mixed-precision
    flow (heavy matmuls in ``cfg.dtype``, rope/softmax/residuals f32).
    Always the XLA dense engine: the Pallas kernels are per-device
    programs and stay with the shard_map formulation."""
    dt = _compute_dtype(cfg)
    mm_dt = dt if dt != jnp.float32 else None
    h = _rmsnorm(x, bp["ln1"]).astype(dt)
    q = jnp.einsum("bsd,dhk->bshk", h, bp["wq"].astype(dt)).astype(jnp.float32)
    k = jnp.einsum("bsd,dhk->bshk", h, bp["wk"].astype(dt)).astype(jnp.float32)
    v = jnp.einsum("bsd,dhk->bshk", h, bp["wv"].astype(dt)).astype(jnp.float32)
    q, k = _rope(q, pos), _rope(k, pos)
    a = dense_attention(q, k, v, causal=True, compute_dtype=mm_dt)
    return jnp.einsum("bshk,hkd->bsd", a.astype(dt),
                      bp["wo"].astype(dt)).astype(jnp.float32)


def _pjit_loss(params, tokens, labels, mask, cfg: TransformerConfig,
               groups: "Tuple[int, int, int]", ce_impl: str, wsc=None):
    """The global-array loss: identical math to ``local_loss`` (same
    CE, same aux/z-loss formulas, group-faithful capacity dispatch)
    with the pipeline schedule flattened to a sequential stage loop —
    a pure perf schedule, not a semantic one, so the loss is
    unchanged."""
    D, Q, E_ax = groups
    B, S = tokens.shape
    x = params["embed"][tokens]
    pos = jnp.arange(S)
    aux_total = jnp.float32(0.0)
    z_total = jnp.float32(0.0)
    for s in range(cfg.n_stages):
        for bp_all in params["blocks"]:
            bp = {k: v[s] for k, v in bp_all.items()}
            x = x + _pjit_attention(bp, x, cfg, pos)
            if cfg.n_experts:
                y, (f, P_, z) = _pjit_moe(bp, x, cfg, D, Q, E_ax, wsc)
                x = x + y
                if cfg.moe_aux_weight > 0:
                    aux_total = aux_total + cfg.n_experts * jnp.sum(f * P_)
                if cfg.moe_zloss_weight > 0:
                    z_total = z_total + z
            else:
                dt = _compute_dtype(cfg)
                h = _rmsnorm(x, bp["ln2"]).astype(dt)
                z = jax.nn.relu(
                    jnp.einsum("bsd,df->bsf", h, bp["w1"].astype(dt))
                    + bp["b1"].astype(dt))
                y = jnp.einsum("bsf,fd->bsd", z,
                               bp["w2"].astype(dt)).astype(jnp.float32)
                x = x + y + bp["b2"]
    h = _rmsnorm(x, params["final_norm"])
    dt = _compute_dtype(cfg)
    if ce_impl in ("fused", "fused_interpret"):
        from mmlspark_tpu.ops.fused_ce import fused_softmax_xent
        ce = fused_softmax_xent(
            h.reshape(B * S, cfg.d_model), params["head"],
            labels.reshape(B * S), compute_dtype=dt,
            interpret=ce_impl == "fused_interpret").reshape(B, S)
    else:
        if dt != jnp.float32:
            logits = jnp.einsum("bsd,dv->bsv", h.astype(dt),
                                params["head"].astype(dt),
                                preferred_element_type=jnp.float32)
        else:
            logits = jnp.einsum("bsd,dv->bsv", h, params["head"])
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None],
                                   axis=-1)[..., 0]
        ce = lse - gold
    loss = jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    if cfg.n_experts and cfg.moe_aux_weight > 0:
        loss = loss + cfg.moe_aux_weight * aux_total
    if cfg.n_experts and cfg.moe_zloss_weight > 0:
        loss = loss + cfg.moe_zloss_weight * z_total
    return loss


def build_pjit_train_step(cfg: TransformerConfig, mesh,
                          learning_rate: float = 0.1,
                          momentum: float = 0.9,
                          donate: bool = True):
    """The train step as ONE global GSPMD program (pjit): same
    signature, layouts (:func:`param_specs`), and math as the
    shard_map formulation — XLA inserts every collective from the
    ``NamedSharding`` annotations, so this builds and runs on pre-VMA
    jaxes (jax 0.4.x) where the manual step's replication checker
    cannot. ``build_spmd_train_step(impl="auto")`` selects it there
    automatically; fixed-seed parity between the formulations is
    pinned in tests/test_transformer.py wherever a VMA jax exists.

    The Pallas attention/CE kernels are per-device programs: this
    formulation uses the XLA engines except on a single-device mesh,
    where an explicitly requested fused CE still runs (the ``auto``
    resolution matches ``local_loss``'s gates there)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    ax = _validate_mesh_config(cfg, mesh)
    n_dev = int(mesh.devices.size)
    specs = param_specs(cfg, mesh)
    is_spec = lambda s: isinstance(s, P)  # noqa: E731
    p_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=is_spec)
    data_sh = NamedSharding(mesh, P(ax.data, ax.seq))
    repl = NamedSharding(mesh, P())
    groups = (mesh.shape.get(ax.data, 1) if ax.data else 1,
              mesh.shape.get(ax.seq, 1) if ax.seq else 1,
              mesh.shape.get(ax.expert, 1) if ax.expert else 1)
    ce_impl = cfg.ce_impl
    if ce_impl == "auto":
        # the fused-CE kernel is a per-device program; "auto" under the
        # global formulation resolves to the XLA path (explicit
        # requests still run it on a single-device mesh, where no
        # partitioning exists to break it)
        ce_impl = "xla"
    elif ce_impl in ("fused", "fused_interpret") and n_dev > 1:
        import warnings
        warnings.warn(
            f"ce_impl={cfg.ce_impl!r} is a per-device Pallas kernel; "
            f"the pjit train-step formulation on a {n_dev}-device mesh "
            f"uses the XLA CE path instead (the shard_map formulation "
            f"runs the kernel per shard)", stacklevel=2)
        ce_impl = "xla"

    wsc = None
    if n_dev > 1:
        def wsc(t, _repl=repl):
            return jax.lax.with_sharding_constraint(t, _repl)

    def step(params, velocity, tokens, labels, mask):
        loss, grads = jax.value_and_grad(_pjit_loss)(
            params, tokens, labels, mask, cfg, groups, ce_impl, wsc)
        velocity = jax.tree.map(lambda v, g: momentum * v + g,
                                velocity, grads)
        params = jax.tree.map(lambda p, v: p - learning_rate * v,
                              params, velocity)
        return params, velocity, loss

    return jax.jit(
        step,
        in_shardings=(p_sh, p_sh, data_sh, data_sh, data_sh),
        out_shardings=(p_sh, p_sh, repl),
        donate_argnums=(0, 1) if donate else ())


def shard_params(params, cfg: TransformerConfig, mesh):
    """Device-put a host param pytree with the canonical layout."""
    from jax.sharding import NamedSharding

    specs = param_specs(cfg, mesh)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs)


# ---------------------------------------------------------------------------
# checkpoint / resume


def save_train_state(path: str, params, velocity, step: int,
                     max_to_keep: int = 3) -> None:
    """Checkpoint the SPMD training state (params + velocity) at
    ``step``. Sharded arrays are written shard-by-shard (the native
    sharded store in :mod:`mmlspark_tpu.io.checkpoint` — no host
    gather); the on-disk format is mesh-layout independent, so a
    resume may use a different mesh (fewer/more chips, different axis
    split) than the run that saved it, and the digest manifest written
    last keeps every step flip-eligible for the rollout plane.
    """
    from mmlspark_tpu.io import checkpoint as _ckpt
    mngr = _ckpt.manager(path, max_to_keep)
    mngr.save(step, {"params": params, "velocity": velocity})
    mngr.wait_until_finished()
    mngr.close()


def restore_train_state(path: str, cfg: TransformerConfig, mesh,
                        step: Optional[int] = None):
    """Restore ``(params, velocity, step)`` directly onto ``mesh``'s
    canonical shardings (:func:`param_specs`: each device shard is
    assembled from only the saved files that overlap it) — the resume
    half of :func:`save_train_state`, valid across mesh layouts.
    ``step=None`` restores the latest checkpoint."""
    from jax.sharding import NamedSharding
    from mmlspark_tpu.io import checkpoint as _ckpt
    from mmlspark_tpu.io import fs as _fs
    if not _fs.exists(path):
        raise FileNotFoundError(f"no checkpoint under {path!r}")
    mngr = _ckpt.manager(path, create=False)
    target = step if step is not None else mngr.latest_step()
    if target is None:
        raise FileNotFoundError(f"no checkpoint under {path!r}")
    template = jax.eval_shape(lambda: init_params(cfg, seed=0))
    specs = param_specs(cfg, mesh)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(
                                 x, jax.sharding.PartitionSpec))
    state_template = {"params": template, "velocity": template}
    state_shardings = {"params": shardings, "velocity": shardings}
    restored = mngr.restore(target, state_template,
                            shardings=state_shardings)
    mngr.close()
    return restored["params"], restored["velocity"], target


def make_batch(rng: np.random.Generator, cfg: TransformerConfig,
               batch: int, seq: int):
    """Synthetic next-token batch (tokens, labels, mask) for tests/bench."""
    toks = rng.integers(0, cfg.vocab, size=(batch, seq + 1), dtype=np.int64)
    tokens = jnp.asarray(toks[:, :-1].astype(np.int32))
    labels = jnp.asarray(toks[:, 1:].astype(np.int32))
    mask = jnp.ones((batch, seq), jnp.float32)
    return tokens, labels, mask


# ---------------------------------------------------------------------------
# autoregressive decode: slot-indexed KV-cache pool
#
# The serving-side decode path. Shapes are FIXED at build time
# ([n_slots, ...] for the single-token step, a bucketed prompt ladder
# for prefill), the cache is one preallocated pool donated through
# every call (cache-in buffers are reused for cache-out — zero
# steady-state HBM allocations), and requests address it by SLOT: a
# request claims a free slot, prefill fills rows [0, len) of that
# slot's lane in every layer, each decode step appends one row at its
# position, and freeing the slot is just returning the index — the
# next occupant's prefill overwrites the lane. Dense-MLP and
# token-choice MoE configs (dense dispatch — see _decode_ffn);
# expert-choice routing is refused (it couples slots).
# Replicated per worker by default; under tensor parallelism
# (``decode_param_specs`` + ``decode_cache_spec``) ONE model and ONE
# pool span the mesh — heads and the MLP hidden shard over ``model``,
# each device's cache holds its heads' lanes, and the same jitted
# prefill/step run as sharded computations (XLA inserts the fan-in
# collectives; shapes, donation, and the compile-once contract are
# unchanged).


def _decode_block_params(params, cfg: TransformerConfig
                         ) -> List[Dict[str, Any]]:
    """Per-layer param dicts in reference order (stage-major), with
    the leading ``n_stages`` dim indexed away."""
    out = []
    for s in range(cfg.n_stages):
        for bp_all in params["blocks"]:
            out.append({k: v[s] for k, v in bp_all.items()})
    return out


def _rope_at(x, pos):
    """Rotary embedding for mid-sequence tokens: ``x`` [..., H, Dh] at
    positions ``pos`` matching the leading dims (``[N]`` for the
    single-token step, ``[N, W]`` for the speculative verify step —
    each slot is mid-sequence at its own depth, the batched analogue
    of :func:`_rope` at short S)."""
    dh = x.shape[-1]
    freqs = 1.0 / (10000.0 ** (jnp.arange(0, dh, 2) / dh))
    ang = pos[..., None].astype(jnp.float32) * freqs      # [..., Dh/2]
    cos = jnp.cos(ang)[..., None, :]                      # [..., 1, Dh/2]
    sin = jnp.sin(ang)[..., None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    return jnp.stack([r1, r2], axis=-1).reshape(x.shape)


def _check_decode_config(cfg: TransformerConfig) -> None:
    if cfg.n_experts and cfg.moe_router == "expert_choice":
        raise NotImplementedError(
            "expert-choice MoE has no decode form: each expert picks "
            "its top tokens ACROSS the batch, so slots would couple — "
            "the property continuous batching forbids. Token-choice "
            "MoE decodes via dense dispatch (_decode_ffn).")


def _q_matmul(x, w_q, w_s, act_dtype=jnp.bfloat16):
    """int8-weight matmul for the quantized decode FFN: ``x`` [T, I]
    f32, ``w_q`` [I, O] int8, ``w_s`` [O] f32 per-output-channel
    scales. The activation and the (exactly representable) int8
    weights meet as ``act_dtype`` on the MXU with f32 accumulation
    (``preferred_element_type``), and the scales fold into the f32
    accumulator AFTER the contraction — one multiply per output
    element, full scale precision. Returns f32 [T, O]."""
    acc = jax.lax.dot_general(
        x.astype(act_dtype), w_q.astype(act_dtype),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return acc * w_s


def quantize_decode_ffn(params, cfg: TransformerConfig,
                        scale_multiplier: float = 1.0):
    """Per-channel int8 quantization of the decode FFN weights —
    computed ONCE (rollout stage time), served forever.

    For every stage's ``w1`` [s, D, F] / ``w2`` [s, F, D], symmetric
    per-output-channel scales ``amax(|w|, axis=input) / 127`` (f32,
    zero-channels guard to 1.0), weights rounded into ``w1_q``/
    ``w2_q`` int8 with ``w1_s``/``w2_s`` scale vectors alongside; the
    f32 originals are dropped from the returned tree (the HBM win —
    biases and everything outside the FFN stay f32: rope, softmax,
    attention, and the residual stream keep the reference numerics,
    mirroring the ``cfg.dtype`` flow in the train path). MoE configs
    are refused — dense dispatch re-runs every expert per token, so
    there is no hot single matmul to win on yet.

    ``scale_multiplier`` deliberately corrupts the stored scales when
    != 1.0 — the chaos knob the rollout-verify tests use to prove a
    broken quantized config fails parity and never flips."""
    _check_decode_config(cfg)
    if cfg.n_experts:
        raise NotImplementedError(
            "quantized decode FFN supports dense-MLP configs only")
    out = dict(params)
    blocks = []
    for bp_all in params["blocks"]:
        b = {k: v for k, v in bp_all.items()
             if k not in ("w1", "w2")}
        for name, axis in (("w1", 1), ("w2", 1)):
            w = jnp.asarray(bp_all[name], jnp.float32)  # [s, I, O]
            s = jnp.max(jnp.abs(w), axis=axis) / 127.0  # [s, O]
            s = jnp.where(s > 0, s, 1.0)
            q = jnp.clip(jnp.round(w / s[:, None, :]),
                         -127, 127).astype(jnp.int8)
            b[name + "_q"] = q
            b[name + "_s"] = (s * float(scale_multiplier)
                              ).astype(jnp.float32)
        blocks.append(b)
    out["blocks"] = blocks
    return out


def _decode_ffn(bp, h, cfg: TransformerConfig):
    """The decode paths' FFN over post-``ln2`` activations ``h``
    ([..., D] — [1, S, D] prefill, [N, D] step, [N, W, D] verify).

    Dense-MLP configs run the plain two-matmul FFN. MoE configs run
    token-choice routing with **dense dispatch**: at decode the batch
    is one token per slot, so capacity queues degenerate (C would be
    0 or 1 and dropping a routing truncates a LIVE sequence) — every
    expert runs on every token and the top-k router weights combine,
    which is exactly :func:`_reference_forward`'s MoE math (the decode
    parity golden). Compute scales with ``n_experts``, acceptable at
    decode's tiny token counts; ``moe_capacity_factor`` is ignored
    here by design."""
    shape = h.shape
    hf = h.reshape(-1, shape[-1])
    if cfg.n_experts:
        logits = hf @ bp["router"]                        # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)
        wts, experts = _route_top_k(probs, cfg.moe_top_k)
        y = jnp.zeros_like(hf)
        for e in range(cfg.n_experts):
            sel = jnp.sum((experts == e).astype(jnp.float32) * wts,
                          axis=-1)
            z = jax.nn.relu(hf @ bp["ew1"][e])
            y = y + (z @ bp["ew2"][e]) * sel[:, None]
        return y.reshape(shape)
    if "w1_q" in bp:
        # int8-compute FFN (quantize_decode_ffn): int8 weights meet
        # bf16 activations on the MXU, f32 accumulate, per-channel
        # dequant on the accumulator; biases and the residual add
        # stay f32
        z = jax.nn.relu(_q_matmul(hf, bp["w1_q"], bp["w1_s"])
                        + bp["b1"])
        return (_q_matmul(z, bp["w2_q"], bp["w2_s"])
                + bp["b2"]).reshape(shape)
    z = jax.nn.relu(hf @ bp["w1"] + bp["b1"])
    return (z @ bp["w2"] + bp["b2"]).reshape(shape)


def decode_param_specs(cfg: TransformerConfig, mesh,
                       quantized_ffn: bool = False) -> Dict[str, Any]:
    """PartitionSpec tree for the decode path's params under tensor
    parallelism: attention heads and the MLP hidden shard over the
    ``model`` axis (the Megatron split — each device holds its heads'
    K/V lanes and its hidden slice; XLA inserts the out-proj/MLP
    fan-in collectives), embed/head/norms replicated. Requires
    ``n_heads`` and ``d_ff`` divisible by the model-axis size.
    ``quantized_ffn`` describes a :func:`quantize_decode_ffn` tree:
    the int8 weights take their f32 originals' split and each scale
    vector shards with its matmul's OUTPUT channels (``w1_s`` over the
    hidden like ``b1``, ``w2_s`` replicated like ``b2``)."""
    from jax.sharding import PartitionSpec as P

    _check_decode_config(cfg)
    model = AXIS_MODEL if AXIS_MODEL in mesh.axis_names else None
    tp = mesh.shape.get(AXIS_MODEL, 1)
    if model and cfg.n_heads % tp:
        raise ValueError(f"n_heads={cfg.n_heads} must divide over the "
                         f"model axis ({tp})")
    if model and cfg.d_ff % tp:
        raise ValueError(f"d_ff={cfg.d_ff} must divide over the "
                         f"model axis ({tp})")
    specs: Dict[str, Any] = {"embed": P(), "head": P(), "final_norm": P()}
    blocks = []
    for _ in range(cfg.layers_per_stage):
        b = {
            "ln1": P(), "ln2": P(),
            "wq": P(None, None, model, None),
            "wk": P(None, None, model, None),
            "wv": P(None, None, model, None),
            "wo": P(None, model, None, None),
        }
        if cfg.n_experts:
            # MoE decode (dense dispatch): router replicated, expert
            # FFNs Megatron-split over the hidden dim — the same
            # fan-in psum the dense MLP split relies on
            b["router"] = P()
            b["ew1"] = P(None, None, None, model)
            b["ew2"] = P(None, None, model, None)
        elif quantized_ffn:
            b["w1_q"] = P(None, None, model)
            b["w1_s"] = P(None, model)
            b["b1"] = P(None, model)
            b["w2_q"] = P(None, model, None)
            b["w2_s"] = P()
            b["b2"] = P()
        else:
            b["w1"] = P(None, None, model)
            b["b1"] = P(None, model)
            b["w2"] = P(None, model, None)
            b["b2"] = P()
        blocks.append(b)
    specs["blocks"] = blocks
    return specs


def decode_cache_spec(mesh):
    """The KV pool's sharding under tensor parallelism: the head dim
    (axis 3 of BOTH layouts — dense ``[n_layers, n_slots, max_len, H,
    Dh]`` and paged ``[n_layers, n_pages, page_size, H, Dh]``) over
    the ``model`` axis — each device's cache holds exactly its heads'
    lanes, so the pool's HBM footprint splits across the mesh."""
    from jax.sharding import PartitionSpec as P
    model = AXIS_MODEL if AXIS_MODEL in mesh.axis_names else None
    return P(None, None, None, model, None)


def init_kv_cache(cfg: TransformerConfig, n_slots: int, max_len: int
                  ) -> Dict[str, jax.Array]:
    """The preallocated slot-indexed KV pool: ``{"k", "v"}`` arrays of
    shape ``[n_layers, n_slots, max_len, n_heads, d_head]`` (f32 — the
    decode path mirrors the reference forward's numerics so greedy
    decode matches the full-context argmax token-for-token). Allocated
    ONCE; every prefill/decode call donates it back in."""
    _check_decode_config(cfg)
    shape = (cfg.n_layers, int(n_slots), int(max_len),
             cfg.n_heads, cfg.d_head)
    return {"k": jnp.zeros(shape, jnp.float32),
            "v": jnp.zeros(shape, jnp.float32)}


def _decode_out_shardings(cache_sharding):
    """Pin the jitted decode pair's output layout under tensor
    parallelism: the cache keeps its canonical head sharding through
    every donated call (otherwise XLA may pick a different layout for
    the prefill's output than the step expects — one silent retrace
    per transition), tokens/logits come back replicated (they are
    host-fetched anyway)."""
    if cache_sharding is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    repl = NamedSharding(cache_sharding.mesh, P())
    return ({"k": cache_sharding, "v": cache_sharding}, repl, repl)


def _make_inflight_attn(cfg: TransformerConfig, attn_impl: str,
                        cache_sharding):
    """Resolve the prefill builders' in-flight attention engine:
    ``attn(q, k, v)`` over the [B, S, H, Dh] q/k/v a prefill just
    computed. ``"dense"`` is the softmax path (the [S, S] score matrix
    materializes), ``"pallas"`` the streaming flash kernel
    (:func:`~mmlspark_tpu.parallel.pallas_attention.
    flash_prefill_attention` — no [S, S] intermediate),
    ``"pallas_interpret"`` the kernel interpreted for CPU parity.
    Under a TP mesh the kernel runs per head-slice via ``shard_map``
    (heads are independent — the decode kernel's dispatch, one shape
    earlier in the request's life)."""
    if attn_impl not in ("dense", "pallas", "pallas_interpret"):
        raise ValueError(f"unknown attn_impl {attn_impl!r}")
    scale = cfg.d_head ** -0.5
    if attn_impl == "dense":
        return lambda q, k, v: dense_attention(q, k, v, causal=True)
    from mmlspark_tpu.parallel.pallas_attention import (
        flash_prefill_attention)
    interp = attn_impl == "pallas_interpret"
    tp_mesh = None
    if cache_sharding is not None \
            and cache_sharding.mesh.shape.get(AXIS_MODEL, 1) > 1:
        tp_mesh = cache_sharding.mesh

    def attn(q, k, v):
        if tp_mesh is None:
            return flash_prefill_attention(q, k, v, scale, interp)
        from jax.sharding import PartitionSpec as P
        f = jax.shard_map(
            lambda q_, k_, v_: flash_prefill_attention(
                q_, k_, v_, scale, interp),
            mesh=tp_mesh,
            in_specs=(P(None, None, AXIS_MODEL, None),) * 3,
            out_specs=P(None, None, AXIS_MODEL, None),
            check_vma=False)
        return f(q, k, v)

    return attn


def build_prefill(cfg: TransformerConfig, donate: bool = True,
                  cache_sharding=None, attn_impl: str = "dense"):
    """Jitted ``prefill(params, cache, tokens, slot, length) ->
    (cache, next_token, last_logits)``.

    ``tokens`` is ONE bucket-padded prompt ``[S_pad]`` (one compile per
    bucket — the prompt ladder is the serving shape set), ``slot`` the
    claimed cache lane, ``length`` the true prompt length. Every
    layer's K/V rows land in ``cache[...][layer, slot, :S_pad]``; rows
    past ``length`` hold padding-token garbage, but the decode step's
    position mask never reads an index it has not yet overwritten, so
    they are dead by construction. The cache is donated: prefill
    writes in place, no second pool exists.

    ``next_token`` is the greedy argmax at position ``length - 1`` —
    the first generated token. ``attn_impl`` picks the in-flight
    attention engine (see :func:`_make_inflight_attn`)."""
    _check_decode_config(cfg)
    attn = _make_inflight_attn(cfg, attn_impl, cache_sharding)

    def prefill(params, cache, tokens, slot, length):
        x = params["embed"][tokens][None]              # [1, S, D]
        pos = jnp.arange(tokens.shape[0])
        ck, cv = cache["k"], cache["v"]
        for l, bp in enumerate(_decode_block_params(params, cfg)):
            h = _rmsnorm(x, bp["ln1"])
            q = _rope(jnp.einsum("bsd,dhk->bshk", h, bp["wq"]), pos)
            k = _rope(jnp.einsum("bsd,dhk->bshk", h, bp["wk"]), pos)
            v = jnp.einsum("bsd,dhk->bshk", h, bp["wv"])
            # [S, H, Dh] -> this layer's slot lane, rows [0, S)
            ck = jax.lax.dynamic_update_slice(
                ck, k[0][None, None], (l, slot, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                cv, v[0][None, None], (l, slot, 0, 0, 0))
            a = attn(q, k, v)
            x = x + jnp.einsum("bshk,hkd->bsd", a, bp["wo"])
            x = x + _decode_ffn(bp, _rmsnorm(x, bp["ln2"]), cfg)
        h = _rmsnorm(x[0], params["final_norm"])       # [S, D]
        last = jax.lax.dynamic_index_in_dim(h, length - 1, axis=0,
                                            keepdims=False)
        logits = last @ params["head"]
        return ({"k": ck, "v": cv},
                jnp.argmax(logits, -1).astype(jnp.int32), logits)

    kw = {}
    out_sh = _decode_out_shardings(cache_sharding)
    if out_sh is not None:
        kw["out_shardings"] = out_sh
    return jax.jit(prefill, donate_argnums=(1,) if donate else (), **kw)


def build_decode_step(cfg: TransformerConfig, n_slots: int,
                      max_len: int, donate: bool = True,
                      cache_sharding=None):
    """Jitted ``step(params, cache, tokens, pos) -> (cache,
    next_tokens, logits)`` — ONE token for every slot at once.

    All shapes are fixed at build time (``tokens``/``pos`` are
    ``[n_slots]`` int32), so the step compiles exactly once however
    requests join and leave; the cache is donated, so a warm loop
    allocates nothing on device. Each slot writes its new K/V row at
    ``pos[slot]`` then attends over its own lane masked to
    ``index <= pos`` — slots are fully independent, which is what lets
    the scheduler splice a freshly prefilled request into a running
    batch between steps. Free slots ride along with ``token 0 @ pos
    0`` (their lane row 0 is rewritten by the next prefill); their
    outputs are garbage the host never reads."""
    _check_decode_config(cfg)
    n_slots, max_len = int(n_slots), int(max_len)
    rows = jnp.arange(n_slots)
    idx = jnp.arange(max_len)

    def step(params, cache, tokens, pos):
        ck, cv, nxt, logits = _dense_step_body(
            params, cfg, cache["k"], cache["v"], tokens, pos, rows, idx)
        return {"k": ck, "v": cv}, nxt, logits

    kw = {}
    out_sh = _decode_out_shardings(cache_sharding)
    if out_sh is not None:
        kw["out_shardings"] = out_sh
    return jax.jit(step, donate_argnums=(1,) if donate else (), **kw)


def _dense_step_body(params, cfg: TransformerConfig, ck, cv, tokens,
                     pos, rows, idx):
    """One single-token step for every slot over the dense slot-lane
    cache — the body :func:`build_decode_step` jits and
    :func:`build_draft_propose` unrolls ``k`` times in one program."""
    scale = cfg.d_head ** -0.5
    x = params["embed"][tokens]                        # [N, D]
    mask = idx[None, None, :] <= pos[:, None, None]    # [N, 1, S]
    for l, bp in enumerate(_decode_block_params(params, cfg)):
        h = _rmsnorm(x, bp["ln1"])
        q = _rope_at(jnp.einsum("nd,dhk->nhk", h, bp["wq"]), pos)
        k = _rope_at(jnp.einsum("nd,dhk->nhk", h, bp["wk"]), pos)
        v = jnp.einsum("nd,dhk->nhk", h, bp["wv"])
        ck = ck.at[l, rows, pos].set(k)
        cv = cv.at[l, rows, pos].set(v)
        s = jnp.einsum("nhk,nshk->nhs", q, ck[l]) * scale
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("nhs,nshk->nhk", p, cv[l])
        x = x + jnp.einsum("nhk,hkd->nd", a, bp["wo"])
        x = x + _decode_ffn(bp, _rmsnorm(x, bp["ln2"]), cfg)
    h = _rmsnorm(x, params["final_norm"])
    logits = h @ params["head"]
    return ck, cv, jnp.argmax(logits, -1).astype(jnp.int32), logits


# ---------------------------------------------------------------------------
# paged KV cache: block-table layout
#
# The dense pool above reserves ``max_len`` rows per slot, so a short
# sequence wastes most of its lane — concurrency per device is capped
# by WORST-CASE length. The paged layout breaks the lane into fixed
# ``page_size``-row pages drawn from one shared pool
# ``[n_layers, n_pages, page_size, H, Dh]``; a per-slot **page table**
# (int32 page indices, virtual row r lives at
# ``pages[table[r // page_size], r % page_size]``) maps each slot's
# virtual lane onto whatever pages it has claimed, so HBM is spent on
# rows sequences actually occupy and the same pool holds
# ``~max_len / mean_len`` times more concurrent sessions. All shapes
# stay fixed (tables are ``[pages_per_slot]`` dense int arrays), the
# pool is donated through every call, and the compile-once contract is
# unchanged. Page index 0 is the SCRATCH page by convention: unclaimed
# table entries point at it, so writes past a slot's claimed region
# (bucket-padding tails, speculative overshoot, free slots riding the
# step) land harmlessly there and the position mask never reads them.


def init_paged_kv_cache(cfg: TransformerConfig, n_pages: int,
                        page_size: int) -> Dict[str, jax.Array]:
    """The shared page pool: ``{"k", "v"}`` arrays of shape
    ``[n_layers, n_pages, page_size, n_heads, d_head]`` (f32, like the
    dense pool — decode mirrors the reference numerics). Allocated
    once and donated through every prefill/step/verify call. Page 0
    is the scratch page (see module section comment); a pool of
    ``n_pages`` therefore holds ``n_pages - 1`` claimable pages."""
    _check_decode_config(cfg)
    shape = (cfg.n_layers, int(n_pages), int(page_size),
             cfg.n_heads, cfg.d_head)
    return {"k": jnp.zeros(shape, jnp.float32),
            "v": jnp.zeros(shape, jnp.float32)}


def build_paged_prefill(cfg: TransformerConfig, page_size: int,
                        pages_per_slot: int, donate: bool = True,
                        cache_sharding=None, attn_impl: str = "dense"):
    """Jitted ``prefill(params, cache, tokens, page_table, length) ->
    (cache, next_token, last_logits)`` — the paged analogue of
    :func:`build_prefill`.

    ``tokens`` is one bucket-padded prompt ``[S_pad]`` (one compile
    per bucket), ``page_table`` the slot's ``[pages_per_slot]`` table.
    Every layer's K/V rows land in the slot's claimed pages through
    the table: buckets >= ``page_size`` scatter whole page-shaped
    chunks, smaller buckets write one partial page. Chunks past the
    claimed page count ride the scratch-page convention (table entry
    0), so bucket padding never corrupts another slot's pages.
    ``attn_impl`` picks the in-flight attention engine (the cold
    prefill attends over the q/k/v it just computed, not the pool —
    see :func:`_make_inflight_attn`)."""
    _check_decode_config(cfg)
    page_size, pages_per_slot = int(page_size), int(pages_per_slot)
    attn = _make_inflight_attn(cfg, attn_impl, cache_sharding)

    def prefill(params, cache, tokens, page_table, length):
        S = tokens.shape[0]
        x = params["embed"][tokens][None]              # [1, S, D]
        pos = jnp.arange(S)
        ck, cv = cache["k"], cache["v"]
        for l, bp in enumerate(_decode_block_params(params, cfg)):
            h = _rmsnorm(x, bp["ln1"])
            q = _rope(jnp.einsum("bsd,dhk->bshk", h, bp["wq"]), pos)
            k = _rope(jnp.einsum("bsd,dhk->bshk", h, bp["wk"]), pos)
            v = jnp.einsum("bsd,dhk->bshk", h, bp["wv"])
            if S >= page_size:
                n_chunks = S // page_size
                kc = k[0].reshape(n_chunks, page_size,
                                  cfg.n_heads, cfg.d_head)
                vc = v[0].reshape(n_chunks, page_size,
                                  cfg.n_heads, cfg.d_head)
                ck = ck.at[l, page_table[:n_chunks]].set(kc)
                cv = cv.at[l, page_table[:n_chunks]].set(vc)
            else:
                # a sub-page bucket: one partial write into the first
                # claimed page, rows [0, S)
                ck = jax.lax.dynamic_update_slice(
                    ck, k[0][None, None], (l, page_table[0], 0, 0, 0))
                cv = jax.lax.dynamic_update_slice(
                    cv, v[0][None, None], (l, page_table[0], 0, 0, 0))
            a = attn(q, k, v)
            x = x + jnp.einsum("bshk,hkd->bsd", a, bp["wo"])
            x = x + _decode_ffn(bp, _rmsnorm(x, bp["ln2"]), cfg)
        h = _rmsnorm(x[0], params["final_norm"])       # [S, D]
        last = jax.lax.dynamic_index_in_dim(h, length - 1, axis=0,
                                            keepdims=False)
        logits = last @ params["head"]
        return ({"k": ck, "v": cv},
                jnp.argmax(logits, -1).astype(jnp.int32), logits)

    kw = {}
    out_sh = _decode_out_shardings(cache_sharding)
    if out_sh is not None:
        kw["out_shardings"] = out_sh
    return jax.jit(prefill, donate_argnums=(1,) if donate else (), **kw)


def build_paged_prefix_prefill(cfg: TransformerConfig, page_size: int,
                               pages_per_slot: int, donate: bool = True,
                               cache_sharding=None,
                               attn_impl: str = "dense"):
    """Jitted ``prefill(params, cache, tokens, page_table, length,
    hit_len) -> (cache, next_token, last_logits)`` — the **partial /
    offset** prefill behind the cross-request prefix cache
    (docs/serving.md "Prefix cache").

    When the radix index matched a prompt's first ``hit_len`` tokens
    (page-aligned) to cached pages, only the uncached suffix needs
    compute: ``tokens`` is the suffix ``prompt[hit_len:]`` padded to a
    bucket ``[S_pad]`` (one compile per SUFFIX bucket — the same pow2
    ladder as cold prefill), ``page_table`` the slot's full table whose
    first ``hit_len // page_size`` entries are the SHARED prefix pages
    and the rest the slot's private pages. Each suffix position ``j``
    embeds/ropes at virtual position ``hit_len + j`` (``hit_len`` is a
    traced scalar — hit depth is data, not shape), writes its K/V row
    through the table at that virtual row (hit_len is page-aligned, so
    suffix chunks start on a page boundary), and attends over the
    WHOLE virtual lane — prefix rows come straight from the shared
    pages, never recomputed — masked causally to ``index <= hit_len +
    j``. Exact, not approximate: the lane holds the same K/V a cold
    prefill would have produced (the shared pages ARE a previous cold
    prefill's output), so greedy/sampled/speculative decode from an
    offset prefill is token-for-token the cold path (test-pinned).

    Shared pages are READ-only here by construction: every write lands
    at virtual row ``>= hit_len``, i.e. pages ``>= hit_len //
    page_size`` — the immutability invariant the scheduler's sharing
    model rests on. ``next_token`` is the greedy argmax at virtual
    position ``length - 1`` (suffix row ``length - 1 - hit_len``;
    the cache layer caps ``hit_len < length``, so the last prompt
    position is always computed, never cached).

    ``attn_impl`` picks the virtual-lane attention engine: ``"dense"``
    gathers the whole lane through the table and softmaxes the [S, V]
    score matrix; ``"pallas"`` runs the fused block-table kernel
    (:func:`~mmlspark_tpu.parallel.pallas_attention.
    paged_prefix_prefill_attention` — page DMAs aimed by scalar
    prefetch, streaming softmax over (q-tile, page) steps, neither the
    gathered lane nor the [S, V] scores ever reach HBM);
    ``"pallas_interpret"`` is the CPU parity mode. Same scratch-page
    overshoot semantics on every engine."""
    _check_decode_config(cfg)
    page_size, pages_per_slot = int(page_size), int(pages_per_slot)
    V = page_size * pages_per_slot
    scale = cfg.d_head ** -0.5
    idx = jnp.arange(V)
    if attn_impl not in ("dense", "pallas", "pallas_interpret"):
        raise ValueError(f"unknown attn_impl {attn_impl!r}")
    use_flash = attn_impl in ("pallas", "pallas_interpret")
    tp_mesh = None
    if use_flash:
        from mmlspark_tpu.parallel.pallas_attention import (
            paged_prefix_prefill_attention)
        if cache_sharding is not None \
                and cache_sharding.mesh.shape.get(AXIS_MODEL, 1) > 1:
            tp_mesh = cache_sharding.mesh

    def _flash_lane_attn(q, k_pool, v_pool, page_table, hit_len):
        interp = attn_impl == "pallas_interpret"
        if tp_mesh is None:
            return paged_prefix_prefill_attention(
                q, k_pool, v_pool, page_table, hit_len, scale=scale,
                page_size=page_size, interpret=interp)
        from jax.sharding import PartitionSpec as P
        f = jax.shard_map(
            lambda q_, k_, v_, t_, h_: paged_prefix_prefill_attention(
                q_, k_, v_, t_, h_, scale=scale, page_size=page_size,
                interpret=interp),
            mesh=tp_mesh,
            in_specs=(P(None, AXIS_MODEL, None),
                      P(None, None, AXIS_MODEL, None),
                      P(None, None, AXIS_MODEL, None),
                      P(None), P()),
            out_specs=P(None, AXIS_MODEL, None),
            check_vma=False)
        return f(q, k_pool, v_pool, page_table, hit_len)

    def prefill(params, cache, tokens, page_table, length, hit_len):
        S = tokens.shape[0]
        x = params["embed"][tokens]                    # [S, D]
        pos = hit_len + jnp.arange(S)                  # virtual rows
        start_page = hit_len // page_size
        ck, cv = cache["k"], cache["v"]
        # query j at virtual row hit_len + j reads index <= hit_len + j
        # (the flash kernel masks inside its (q-tile, page) steps — on
        # that path no [S, V]-shaped value enters the jaxpr at all)
        mask = None if use_flash \
            else idx[None, None, :] <= pos[:, None, None]  # [S, 1, V]
        for l, bp in enumerate(_decode_block_params(params, cfg)):
            h = _rmsnorm(x, bp["ln1"])
            q = _rope_at(jnp.einsum("sd,dhk->shk", h, bp["wq"]), pos)
            k = _rope_at(jnp.einsum("sd,dhk->shk", h, bp["wk"]), pos)
            v = jnp.einsum("sd,dhk->shk", h, bp["wv"])
            if S >= page_size:
                # hit_len is page-aligned: suffix chunk c fills page
                # table[start_page + c] exactly. The bucket can
                # overshoot the lane end (start_page + n_chunks >
                # pages_per_slot when hit_len + S_pad > max_len) — a
                # clamped dynamic_slice would silently re-aim those
                # chunks at EARLIER table entries, i.e. write padding
                # over the SHARED prefix pages, so overflow chunks
                # route to the scratch page instead (the verify step's
                # overshoot convention).
                n_chunks = S // page_size
                cpos = start_page + jnp.arange(n_chunks)
                pgs = jnp.where(
                    cpos < pages_per_slot,
                    page_table[jnp.minimum(cpos, pages_per_slot - 1)],
                    0)
                ck = ck.at[l, pgs].set(
                    k.reshape(n_chunks, page_size,
                              cfg.n_heads, cfg.d_head))
                cv = cv.at[l, pgs].set(
                    v.reshape(n_chunks, page_size,
                              cfg.n_heads, cfg.d_head))
            else:
                # a sub-page suffix bucket: one partial write into the
                # first private page, rows [0, S)
                pg = jax.lax.dynamic_index_in_dim(
                    page_table, start_page, keepdims=False)
                ck = jax.lax.dynamic_update_slice(
                    ck, k[None, None], (l, pg, 0, 0, 0))
                cv = jax.lax.dynamic_update_slice(
                    cv, v[None, None], (l, pg, 0, 0, 0))
            # attend over the whole virtual lane: shared prefix rows
            # are read from their pages, suffix rows were just written
            if use_flash:
                a = _flash_lane_attn(q, ck[l], cv[l], page_table,
                                     hit_len)
            else:
                lk = ck[l, page_table].reshape(V, cfg.n_heads,
                                               cfg.d_head)
                lv = cv[l, page_table].reshape(V, cfg.n_heads,
                                               cfg.d_head)
                s = jnp.einsum("shk,vhk->shv", q, lk) * scale
                s = jnp.where(mask, s, -1e30)
                p = jax.nn.softmax(s, axis=-1)
                a = jnp.einsum("shv,vhk->shk", p, lv)
            x = x + jnp.einsum("shk,hkd->sd", a, bp["wo"])
            x = x + _decode_ffn(bp, _rmsnorm(x, bp["ln2"]), cfg)
        h = _rmsnorm(x, params["final_norm"])          # [S, D]
        last = jax.lax.dynamic_index_in_dim(
            h, length - 1 - hit_len, axis=0, keepdims=False)
        logits = last @ params["head"]
        return ({"k": ck, "v": cv},
                jnp.argmax(logits, -1).astype(jnp.int32), logits)

    kw = {}
    out_sh = _decode_out_shardings(cache_sharding)
    if out_sh is not None:
        kw["out_shardings"] = out_sh
    return jax.jit(prefill, donate_argnums=(1,) if donate else (), **kw)


def _gather_lane(c_l, page_tables, n_slots, virtual_len, cfg):
    """Assemble each slot's virtual lane from its pages:
    ``c_l [n_pages, page_size, H, Dh]`` gathered through
    ``page_tables [N, pages_per_slot]`` -> ``[N, virtual_len, H, Dh]``
    (virtual_len = pages_per_slot * page_size)."""
    lane = c_l[page_tables]        # [N, P, page, H, Dh]
    return lane.reshape(n_slots, virtual_len, cfg.n_heads, cfg.d_head)


def build_paged_decode_step(cfg: TransformerConfig, n_slots: int,
                            page_size: int, pages_per_slot: int,
                            donate: bool = True, cache_sharding=None,
                            attn_impl: str = "dense"):
    """Jitted ``step(params, cache, tokens, pos, page_tables) ->
    (cache, next_tokens, logits)`` — one token for every slot through
    the block-table layout (the paged :func:`build_decode_step`).

    Each slot writes its new K/V row at page
    ``page_tables[slot, pos // page_size]``, row ``pos % page_size``,
    then attends over its virtual lane masked to ``index <= pos``.
    ``page_tables`` is ``[n_slots, pages_per_slot]`` int32 — fixed
    shape, so occupancy churn and page churn alike reuse ONE
    executable. Free slots ride at token 0 / pos 0 with an all-scratch
    table.

    ``attn_impl`` picks the gather engine: ``"dense"`` (the
    CPU/fallback path — materialize each slot's lane via
    ``c_l[page_tables]`` then one masked attention), ``"pallas"``
    (the fused block-table kernel —
    :func:`~mmlspark_tpu.parallel.pallas_attention.
    paged_decode_attention`: the page table aims each page's DMA via
    scalar prefetch, streaming softmax in VMEM, no lane intermediate
    in HBM), or ``"pallas_interpret"`` (the kernel interpreted, for
    CPU parity tests). Token-for-token parity between the two is
    test-pinned."""
    _check_decode_config(cfg)
    if attn_impl not in ("dense", "pallas", "pallas_interpret"):
        raise ValueError(f"unknown attn_impl {attn_impl!r}")
    n_slots, page_size = int(n_slots), int(page_size)
    pages_per_slot = int(pages_per_slot)
    V = page_size * pages_per_slot
    scale = cfg.d_head ** -0.5
    rows = jnp.arange(n_slots)
    idx = jnp.arange(V)
    use_pallas = attn_impl in ("pallas", "pallas_interpret")
    tp_mesh = None
    if use_pallas:
        from mmlspark_tpu.parallel.pallas_attention import (
            paged_decode_attention)
        if cache_sharding is not None \
                and cache_sharding.mesh.shape.get(AXIS_MODEL, 1) > 1:
            # sharding-aware kernel dispatch: heads are independent in
            # paged attention, so under a TP mesh each model-axis
            # shard runs the SAME kernel on its own head slice (q
            # [N, H/t, Dh], pool [pages, page, H/t, Dh]) with the
            # page tables/positions replicated — per-shard head-slice
            # grids, no collective in either direction. check_vma is
            # irrelevant here (forward-only, nothing replicated is
            # produced); False keeps interpret-mode parity tests
            # runnable on pre-VMA jaxes.
            tp_mesh = cache_sharding.mesh

    def _paged_attn(q, k_pool, v_pool, page_tables, pos):
        interp = attn_impl == "pallas_interpret"
        if tp_mesh is None:
            return paged_decode_attention(
                q, k_pool, v_pool, page_tables, pos, scale=scale,
                page_size=page_size, interpret=interp)
        from jax.sharding import PartitionSpec as P
        f = jax.shard_map(
            lambda q_, k_, v_, t_, p_: paged_decode_attention(
                q_, k_, v_, t_, p_, scale=scale,
                page_size=page_size, interpret=interp),
            mesh=tp_mesh,
            in_specs=(P(None, AXIS_MODEL, None),
                      P(None, None, AXIS_MODEL, None),
                      P(None, None, AXIS_MODEL, None),
                      P(None, None), P(None)),
            out_specs=P(None, AXIS_MODEL, None),
            check_vma=False)
        return f(q, k_pool, v_pool, page_tables, pos)

    def step(params, cache, tokens, pos, page_tables):
        x = params["embed"][tokens]                    # [N, D]
        ck, cv = cache["k"], cache["v"]
        mask = idx[None, None, :] <= pos[:, None, None]  # [N, 1, V]
        pg = page_tables[rows, pos // page_size]       # [N]
        row = pos % page_size
        for l, bp in enumerate(_decode_block_params(params, cfg)):
            h = _rmsnorm(x, bp["ln1"])
            q = _rope_at(jnp.einsum("nd,dhk->nhk", h, bp["wq"]), pos)
            k = _rope_at(jnp.einsum("nd,dhk->nhk", h, bp["wk"]), pos)
            v = jnp.einsum("nd,dhk->nhk", h, bp["wv"])
            ck = ck.at[l, pg, row].set(k)
            cv = cv.at[l, pg, row].set(v)
            if use_pallas:
                a = _paged_attn(q, ck[l], cv[l], page_tables, pos)
            else:
                lk = _gather_lane(ck[l], page_tables, n_slots, V, cfg)
                lv = _gather_lane(cv[l], page_tables, n_slots, V, cfg)
                s = jnp.einsum("nhk,nshk->nhs", q, lk) * scale
                s = jnp.where(mask, s, -1e30)
                p = jax.nn.softmax(s, axis=-1)
                a = jnp.einsum("nhs,nshk->nhk", p, lv)
            x = x + jnp.einsum("nhk,hkd->nd", a, bp["wo"])
            x = x + _decode_ffn(bp, _rmsnorm(x, bp["ln2"]), cfg)
        h = _rmsnorm(x, params["final_norm"])
        logits = h @ params["head"]
        return ({"k": ck, "v": cv},
                jnp.argmax(logits, -1).astype(jnp.int32), logits)

    kw = {}
    out_sh = _decode_out_shardings(cache_sharding)
    if out_sh is not None:
        kw["out_shardings"] = out_sh
    return jax.jit(step, donate_argnums=(1,) if donate else (), **kw)


# ---------------------------------------------------------------------------
# speculative decoding: draft propose + target verify
#
# A small draft model proposes ``k`` tokens per slot (one fused device
# program — k chained single-token steps, one host round-trip instead
# of k), then ONE width-k verify step of the target model scores every
# proposal; the host accepts the longest agreeing prefix (exact argmax
# match for greedy slots, Leviathan rejection sampling for sampled
# slots — both in serving/decode.py). Per emitted token that's
# ~(1 draft + 1 verify) / m dispatches at acceptance m instead of one
# full target step each, which is where the tokens/s comes from; the
# verify's K/V writes for rejected positions are repaired for free by
# the next round's writes (every position is (re)written by the round
# that consumes its token — the same invariant as the single step).


def verify_ce_engine(cfg: TransformerConfig, n_slots: int, width: int,
                     sharded: bool = False) -> str:
    """Resolve the verify/score CE engine for ``cfg.ce_impl``:
    ``"fused"`` = the streaming Pallas CE kernel scores proposals
    straight off the hidden states (``ops/fused_ce.py`` — no second
    ``[N*W, vocab]`` log-prob materialization and a ``[N, W]`` fetch
    instead of ``[N, W, vocab]``), ``"xla"`` = logsumexp-minus-gold
    over the logits the verify computes anyway. ``"auto"`` picks fused
    exactly when the kernel is eligible (TPU backend, lane-aligned
    d_model, enough tokens to fill a tile) and the head is not
    mesh-sharded (the kernel is not partition-aware — XLA partitions
    the einsum path instead)."""
    impl = cfg.ce_impl
    if impl == "auto":
        from mmlspark_tpu.ops.fused_ce import fused_ce_available
        t = int(n_slots) * max(int(width) - 1, 1)
        # the VMEM budget is a compute-dtype question: an f32 model's
        # logit tiles are twice a bf16 model's (same guard the train
        # path applies at its call site)
        itemsize = jnp.dtype(_compute_dtype(cfg)).itemsize
        impl = ("fused" if not sharded
                and fused_ce_available(t, cfg.d_model, cfg.vocab,
                                       itemsize=itemsize)
                else "xla")
    return impl


def build_paged_verify_step(cfg: TransformerConfig, n_slots: int,
                            width: int, page_size: int,
                            pages_per_slot: int, donate: bool = True,
                            cache_sharding=None,
                            with_scores: bool = False,
                            ce_impl: Optional[str] = None):
    """Jitted ``verify(params, cache, tokens, pos, page_tables) ->
    (cache, greedy_tokens, logits[, scores])`` — the target model's
    batched scoring of ``width`` draft positions per slot over the
    paged cache.

    ``tokens`` is ``[n_slots, width]`` (column 0 = the slot's current
    input token, columns 1.. = draft proposals), ``pos`` the per-slot
    start positions: query ``j`` sits at ``pos + j``, writes its K/V
    row through the page table there, and attends its virtual lane
    masked causally to ``index <= pos + j``. Returns the greedy argmax
    ``[n_slots, width]`` (token at ``pos + j + 1`` per the target) and
    the full logits ``[n_slots, width, vocab]`` (fetched only when a
    sampled slot needs rejection sampling).

    ``with_scores`` adds a fourth output: ``[n_slots, width-1]`` f32
    target log-probs of the PROPOSED tokens (``tokens[:, j+1]`` scored
    by query ``j``) — the per-proposal acceptance-quality signal. The
    engine is :func:`verify_ce_engine`'s pick (override via
    ``ce_impl``: ``"fused"``/``"fused_interpret"``/``"xla"``): fused
    scores come off the hidden states through the streaming CE kernel
    (``log p = -ce``), the XLA path reuses the verify's own logits.
    Both are f32-accumulated and parity-pinned in
    tests/test_transformer.py."""
    _check_decode_config(cfg)
    n_slots, width = int(n_slots), int(width)
    page_size, pages_per_slot = int(page_size), int(pages_per_slot)
    V = page_size * pages_per_slot
    scale = cfg.d_head ** -0.5
    rows = jnp.arange(n_slots)
    idx = jnp.arange(V)
    offs = jnp.arange(width)
    if ce_impl is None:
        ce_impl = verify_ce_engine(cfg, n_slots, width,
                                   sharded=cache_sharding is not None)
    if ce_impl not in ("fused", "fused_interpret", "xla"):
        raise ValueError(f"unknown verify ce_impl {ce_impl!r}")

    def verify(params, cache, tokens, pos, page_tables):
        x = params["embed"][tokens]                    # [N, W, D]
        ck, cv = cache["k"], cache["v"]
        qpos = pos[:, None] + offs[None, :]            # [N, W]
        # causal over the virtual lane: query j reads index <= pos + j
        mask = idx[None, None, None, :] <= qpos[:, :, None, None]
        # a slot whose lane ends inside the window (pos + W > V — e.g.
        # a non-speculative slot riding the round near its lane end)
        # must not wrap its writes onto its own live pages: overflow
        # positions route to the scratch page instead
        safe = qpos < V
        pg = jnp.where(
            safe,
            page_tables[rows[:, None],
                        jnp.minimum(qpos // page_size,
                                    pages_per_slot - 1)], 0)  # [N, W]
        row = qpos % page_size
        for l, bp in enumerate(_decode_block_params(params, cfg)):
            h = _rmsnorm(x, bp["ln1"])
            q = _rope_at(jnp.einsum("nwd,dhk->nwhk", h, bp["wq"]), qpos)
            k = _rope_at(jnp.einsum("nwd,dhk->nwhk", h, bp["wk"]), qpos)
            v = jnp.einsum("nwd,dhk->nwhk", h, bp["wv"])
            ck = ck.at[l, pg, row].set(k)
            cv = cv.at[l, pg, row].set(v)
            lk = _gather_lane(ck[l], page_tables, n_slots, V, cfg)
            lv = _gather_lane(cv[l], page_tables, n_slots, V, cfg)
            s = jnp.einsum("nwhk,nshk->nwhs", q, lk) * scale
            s = jnp.where(mask, s, -1e30)              # [N, W, 1, V] bcast
            p = jax.nn.softmax(s, axis=-1)
            a = jnp.einsum("nwhs,nshk->nwhk", p, lv)
            x = x + jnp.einsum("nwhk,hkd->nwd", a, bp["wo"])
            x = x + _decode_ffn(bp, _rmsnorm(x, bp["ln2"]), cfg)
        h = _rmsnorm(x, params["final_norm"])          # [N, W, D]
        logits = jnp.einsum("nwd,dv->nwv", h, params["head"])
        out = ({"k": ck, "v": cv},
               jnp.argmax(logits, -1).astype(jnp.int32), logits)
        if not with_scores:
            return out
        labels = tokens[:, 1:].reshape(-1)             # proposals
        if ce_impl in ("fused", "fused_interpret"):
            # score straight off the hidden states: the streaming CE
            # kernel computes lse - gold per token with logit tiles in
            # VMEM — log p(proposal) = -ce, f32-accumulated
            from mmlspark_tpu.ops.fused_ce import fused_softmax_xent
            ce = fused_softmax_xent(
                h[:, :-1].reshape(-1, cfg.d_model), params["head"],
                labels, interpret=ce_impl == "fused_interpret")
            scores = -ce.reshape(n_slots, width - 1)
        else:
            lg = logits[:, :-1].astype(jnp.float32)    # [N, W-1, V]
            lse = jax.nn.logsumexp(lg, axis=-1)
            gold = jnp.take_along_axis(
                lg, tokens[:, 1:, None], axis=-1)[..., 0]
            scores = gold - lse
        return out + (scores,)

    kw = {}
    out_sh = _decode_out_shardings(cache_sharding)
    if out_sh is not None:
        if with_scores:
            out_sh = out_sh + (out_sh[-1],)   # scores: replicated too
        kw["out_shardings"] = out_sh
    return jax.jit(verify, donate_argnums=(1,) if donate else (), **kw)


def build_draft_propose(cfg: TransformerConfig, n_slots: int,
                        max_len: int, width: int, donate: bool = True):
    """Jitted ``propose(params, cache, tokens, pos) -> (cache,
    proposals)`` — ``width`` greedy draft steps chained INSIDE one
    device program (each step's argmax feeds the next), over the
    draft's dense slot-lane cache.

    One host round-trip proposes the whole block — the draft-side
    half of the speculative dispatch saving. Greedy only: sampled
    slots need per-step draft distributions on host, so the scheduler
    falls back to ``width`` separate draft steps when one is active."""
    _check_decode_config(cfg)
    n_slots, max_len, width = int(n_slots), int(max_len), int(width)
    rows = jnp.arange(n_slots)
    idx = jnp.arange(max_len)

    def propose(params, cache, tokens, pos):
        ck, cv = cache["k"], cache["v"]
        cur = tokens
        props = []
        for j in range(width):
            ck, cv, cur, _ = _dense_step_body(
                params, cfg, ck, cv, cur, pos + j, rows, idx)
            props.append(cur)
        return {"k": ck, "v": cv}, jnp.stack(props, axis=1)

    return jax.jit(propose, donate_argnums=(1,) if donate else ())


def layer_truncated_draft(params, cfg: TransformerConfig,
                          layers: int):
    """A self-speculative draft: the target's FIRST ``layers`` blocks
    with the shared embed/final-norm/head (LayerSkip-style early
    exit). The draft's step costs ``layers / n_layers`` of the
    target's while sharing its representation space — residual blocks
    refine, not replace, the embedding stream, so the early exit's
    argmax agrees with the full model's often enough to pay for
    verification. Returns ``(draft_params, draft_cfg)``; the params
    ALIAS the target's leaves (no copy — one set of weights serves
    both models)."""
    if cfg.n_stages != 1:
        raise ValueError("layer-truncated drafts need n_stages == 1 "
                         "(decode configs are single-stage)")
    if not 1 <= layers <= cfg.layers_per_stage:
        raise ValueError(f"draft layers must be in "
                         f"[1, {cfg.layers_per_stage}]")
    dcfg = dataclasses.replace(cfg, layers_per_stage=int(layers))
    dparams = {"embed": params["embed"], "head": params["head"],
               "final_norm": params["final_norm"],
               "blocks": params["blocks"][:int(layers)]}
    return dparams, dcfg
