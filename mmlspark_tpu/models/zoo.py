"""Model zoo: a repository of checkpointed NNFunctions with manifests.

Capability parity with `src/downloader/` (`ModelDownloader.scala`,
`Schema.scala:54-74`): models live in a repo (a directory or mount) with
per-model JSON metadata (name, dataset, sha256, input node/shape, layer
names); ``ModelDownloader`` fetches them into a local cache with hash
verification and bounded retry (`FaultToleranceUtils.retryWithTimeout`,
`ModelDownloader.scala:37`).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

from mmlspark_tpu.io import fs as _fs
from mmlspark_tpu.models.function import NNFunction


@dataclasses.dataclass
class ModelSchema:
    """Parity: downloader ModelSchema (`Schema.scala:54-74`)."""

    name: str
    dataset: str
    model_type: str
    uri: str
    hash: str
    input_shape: List[int]
    layer_names: List[str]
    num_classes: Optional[int] = None
    # scorer input convention: "uint8" = the net was trained on raw
    # bytes normalized on device — consumers must score with
    # NNModel(input_dtype="uint8"); None = pre-normalized floats
    input_dtype: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ModelSchema":
        return ModelSchema(**d)


def _dir_sha256(path: str) -> str:
    h = hashlib.sha256()
    for rel, full in _fs.walk_rel_files(path):
        h.update(rel.encode())
        with _fs.open_file(full, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
    return h.hexdigest()


def retry_with_timeout(fn, retries: int = 3, backoff: float = 0.5):
    """Parity: FaultToleranceUtils.retryWithTimeout."""
    last: Optional[Exception] = None
    for attempt in range(retries):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - retry any fetch failure
            last = e
            if attempt < retries - 1:
                time.sleep(backoff * (2 ** attempt))
    raise last  # type: ignore[misc]


class ModelRepo:
    """A directory of checkpoints + ``manifest.json`` describing them."""

    MANIFEST = "manifest.json"

    def __init__(self, root: str):
        self.root = root

    def _manifest_path(self) -> str:
        return _fs.join(self.root, self.MANIFEST)

    def models(self) -> Dict[str, ModelSchema]:
        if not _fs.exists(self._manifest_path()):
            return {}
        entries = json.loads(_fs.read_text(self._manifest_path()))
        out = {}
        for e in entries:
            meta = ModelSchema.from_json(e)
            # manifests store repo-relative uris so a zoo directory is
            # portable (committed checkpoints work from any clone path;
            # the same manifest works from a gs:// bucket); absolute
            # uris/URLs (e.g. a mount) pass through untouched
            if not _fs.isabs(meta.uri):
                meta = dataclasses.replace(
                    meta, uri=_fs.join(self.root, meta.uri))
            out[meta.name] = meta
        return out

    def publish(self, name: str, fn: NNFunction, dataset: str = "",
                model_type: str = "", input_shape: Optional[List[int]] = None,
                num_classes: Optional[int] = None,
                input_dtype: Optional[str] = None) -> ModelSchema:
        """Add a checkpoint to the repo and record its manifest entry."""
        model_dir = _fs.join(self.root, name)
        if _fs.is_remote(self.root):
            # NNFunction.save writes local files; stage locally, upload.
            # Hash the staged copy — walk_rel_files yields the same
            # rel-sorted order either side, and hashing the remote tree
            # would re-download every byte just published.
            import tempfile
            with tempfile.TemporaryDirectory() as tmp:
                staged = os.path.join(tmp, name)
                fn.save(staged)
                _fs.rm_tree(model_dir)
                _fs.copy_tree(staged, model_dir)
                tree_hash = _dir_sha256(staged)
        else:
            fn.save(model_dir)
            tree_hash = _dir_sha256(model_dir)
        meta = ModelSchema(
            name=name, dataset=dataset, model_type=model_type,
            uri=name,  # repo-relative: the manifest stays portable
            hash=tree_hash,
            input_shape=list(input_shape or []),
            layer_names=fn.layer_names,
            num_classes=num_classes,
            input_dtype=input_dtype)
        # rewrite from the RAW manifest: models() resolves uris against
        # self.root, and re-serializing resolved paths would bake this
        # machine's absolute paths into the portable manifest
        entries = []
        if _fs.exists(self._manifest_path()):
            entries = [e for e in
                       json.loads(_fs.read_text(self._manifest_path()))
                       if e["name"] != name]
        entries.append(meta.to_json())
        _fs.makedirs(self.root)
        _fs.write_text(self._manifest_path(), json.dumps(entries, indent=2))
        return dataclasses.replace(meta, uri=model_dir)  # resolved for use


class ModelDownloader:
    """Fetch models from a repo into a local cache, verifying hashes.

    Parity: `ModelDownloader.scala` (downloadByName/downloadModel with
    retry + hash check; HDFS repo analogue = any ``gs://``-style fsspec
    URL, `Schema.scala` HDFSRepo). The repo may be a local/NFS path or
    a remote URL; the cache is always local.
    """

    def __init__(self, local_cache: str, repo: Optional[str] = None):
        self.cache_dir = local_cache
        self.repo = ModelRepo(repo) if repo else None

    def list_models(self) -> Dict[str, ModelSchema]:
        if self.repo is None:
            raise ValueError("no repo configured")
        return self.repo.models()

    def download_by_name(self, name: str) -> ModelSchema:
        models = self.list_models()
        if name not in models:
            raise KeyError(f"model {name!r} not in repo; have {sorted(models)}")
        return self.download_model(models[name])

    def download_model(self, meta: ModelSchema) -> ModelSchema:
        dest = os.path.join(self.cache_dir, meta.name)

        if os.path.exists(dest) and _dir_sha256(dest) == meta.hash:
            return dataclasses.replace(meta, uri=dest)

        def fetch():  # only the transfer is retried; it can transiently fail
            if os.path.exists(dest):
                shutil.rmtree(dest)
            os.makedirs(self.cache_dir, exist_ok=True)
            _fs.copy_tree(meta.uri, dest)  # local or gs://-style source

        retry_with_timeout(fetch)
        actual = _dir_sha256(dest)
        if actual != meta.hash:
            # deterministic corruption: fail immediately, no retry
            shutil.rmtree(dest)
            raise IOError(f"hash mismatch for {meta.name}: "
                          f"{actual} != {meta.hash}")
        return dataclasses.replace(meta, uri=dest)

    def load(self, name: str) -> NNFunction:
        meta = self.download_by_name(name)
        return NNFunction.load(meta.uri)
