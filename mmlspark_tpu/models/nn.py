"""NNModel: deep-network scoring as a pipeline Transformer.

Capability parity with `cntk-model/src/main/scala/CNTKModel.scala` (the
reference's main deep-net stage): broadcast-once model, minibatched
evaluation, input coercion, output-layer selection, save/load inside
pipelines. The entire per-partition JNI loop (`CNTKModel.scala:131-138`:
row -> FloatVectorVector -> evaluate -> merge) collapses to: stack the
column, pad to a static minibatch shape, run ONE jitted forward per
minibatch on TPU, with the batch sharded over the mesh's ``data`` axis —
params live in HBM once per host instead of once per partition.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    Param, HasInputCol, HasOutputCol, in_set,
)
from mmlspark_tpu.core.stage import Model
from mmlspark_tpu.core import schema
from mmlspark_tpu.models.function import NNFunction
from mmlspark_tpu.parallel import (
    build_mesh, batch_sharding, replicated_sharding, padded_device_batch,
    unpad,
)


def _device_put(x, placement):
    """Host->device upload (module-level so tests can count uploads)."""
    import jax
    return jax.device_put(x, placement)


# -- device-resident input cache --------------------------------------------
#
# FindBestModel / TuneHyperparameters / ImageFeaturizer-over-N-models score
# the SAME frame through many models; without a cache every transform pays
# the full host->device upload again (the dominant cost on tunneled links).
# The cache keys the device-resident padded batches on the COLUMN OBJECT's
# identity plus a FULL content digest (blake2b over every buffer byte;
# object columns hash each element's bytes) — numpy arrays aren't
# weakref-able, so pure id() could alias a new array after gc, and
# anything short of the full buffer would let an in-place edit of a
# cached column return silently stale predictions (r4 advisor finding).
# Hashing runs at memory bandwidth (~GB/s), a rounding error next to the
# host->device upload it saves. A frame is only STORED on its second
# sighting (one-shot workloads like the serving batch loop never pin HBM
# for frames scored once), and the store is a bounded LRU (4 frames,
# 256 MB each).

import hashlib
import threading
from collections import OrderedDict

_FRAME_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()  # key -> batches
_FRAME_SEEN: "OrderedDict[tuple, None]" = OrderedDict()    # once-seen keys
_FRAME_LOCK = threading.Lock()
_FRAME_CACHE_MAX_ENTRIES = 4
_FRAME_SEEN_MAX_ENTRIES = 64
_FRAME_CACHE_MAX_BYTES = 256 << 20


def _frame_cache():
    return _FRAME_CACHE


def _content_digest(col) -> bytes:
    """Full-buffer blake2b of the column (every element for object
    columns): in-place mutations of a cached column are ALWAYS detected,
    at memory-bandwidth cost — negligible next to the upload a hit
    saves."""
    h = hashlib.blake2b(digest_size=16)
    if col.dtype == np.dtype("O"):
        for e in col:
            a = np.ascontiguousarray(np.asarray(e))
            h.update(str((a.shape, a.dtype.str)).encode())
            h.update(a.data if a.flags.c_contiguous else a.tobytes())
    else:
        a = col if col.flags.c_contiguous else np.ascontiguousarray(col)
        h.update(a.data if a.flags.c_contiguous else a.tobytes())
    return h.digest()


def _frame_cheap_key(col, transfer_dtype, bs: int, placement):
    """Hash-free first-stage key: one-shot frames (never stored by
    design) must not pay a full-buffer hash per transform — the digest
    is only computed once this cheap key has been SEEN (i.e. the frame
    is a store/lookup candidate)."""
    return (id(col), col.ctypes.data, col.shape, col.dtype.str,
            np.dtype(transfer_dtype).str, bs, placement)


def _frame_key(col, transfer_dtype, bs: int, placement):
    return _frame_cheap_key(col, transfer_dtype, bs, placement) + (
        _content_digest(col),)


def _frame_est_bytes(col, transfer_dtype) -> int:
    """Transfer-size estimate without stacking the column."""
    n = len(col)
    if n == 0:
        return 0
    itemsize = np.dtype(transfer_dtype).itemsize
    if col.dtype == np.dtype("O"):
        return n * int(np.asarray(col[0]).size) * itemsize
    return int(np.prod(col.shape, dtype=np.int64)) * itemsize


def _stack_column(col: np.ndarray) -> np.ndarray:
    """Stack a column to one array, preserving the source dtype (a uint8
    image column must reach the transfer-cast as uint8 — forcing f32
    here would quadruple host->device bytes for integer payloads)."""
    if col.dtype == np.dtype("O"):
        if len(col) == 0:
            return np.zeros((0,), dtype=np.float32)
        return np.stack([np.asarray(v) for v in col])
    return np.asarray(col)


class NNModel(Model, HasInputCol, HasOutputCol):
    """Score rows through a jitted deep-net forward pass."""

    input_col = Param("features", "input column (vectors or images)", ptype=str)
    output_col = Param("scores", "output column", ptype=str)
    model = Param(None, "the NNFunction to evaluate", complex=True)
    batch_size = Param(256, "minibatch size per device step", ptype=int)
    output_layer = Param(None, "truncate at this named layer", ptype=str)
    cut_output_layers = Param(0, "cut the last N layers instead of naming one",
                              ptype=int)
    data_parallel = Param(True, "shard minibatches over all local devices",
                          ptype=bool)
    tensor_parallel = Param(0, "tensor-parallel width (0/1 = off): params "
                            "are SHARDED over a 'model' mesh axis of this "
                            "size per parallel/dist rules — one model "
                            "spans devices instead of being replicated "
                            "per device — and minibatches shard over the "
                            "remaining 'data' axis; XLA inserts the "
                            "collectives. The serving tensor-parallel "
                            "dispatch mode: a ServingServer dispatching "
                            "this model runs sharded computations under "
                            "the same bucket/pipeline machinery, with "
                            "placement visible in /stats and dispatch "
                            "spans", ptype=int)
    pipeline_parallel = Param(0, "pipeline-parallel stage count (0/1 = "
                              "off): the layer chain is partitioned "
                              "into this many contiguous stages "
                              "(parallel/pipeline.plan_stages — "
                              "balanced by param bytes), each placed "
                              "on its own contiguous device slice, and "
                              "every transform drives micro-batched "
                              "frames through the stages with "
                              "device_put boundary transfers — a model "
                              "too big (or too slow) for one slice "
                              "still serves, with the fill/drain "
                              "bubble measured and visible in /stats. "
                              "Composes with tensor_parallel: each "
                              "stage's params shard over a 'model' "
                              "axis of that width WITHIN its slice",
                              ptype=int)
    pipeline_microbatches = Param(4, "micro-batches per pipelined "
                                  "frame: more fills the bubble "
                                  "((K-1)/(M+K-1)) but shrinks each "
                                  "dispatch; capped by the frame's "
                                  "rows / the stage data multiple",
                                  ptype=int)
    input_dtype = Param("auto", "host-side cast before transfer: auto casts "
                        "to bfloat16 for bfloat16 models (halves host->HBM "
                        "bytes; the first layer casts activations anyway) | "
                        "float32 | bfloat16 | uint8 | int8 (quantized wire "
                        "bytes: 2-4x fewer link bytes; dequantized ON "
                        "DEVICE via input_scale/input_offset, fused into "
                        "the first layer — the TPU shape of 'normalize "
                        "inside the pipeline', for integer payload "
                        "columns)",
                        validator=in_set("auto", "float32", "bfloat16",
                                         "uint8", "int8"))
    input_scale = Param(None, "on-device input scaling x*scale+offset "
                        "applied inside the jitted forward; default 1/255 "
                        "for uint8/int8 transfers (images -> [0,1]), 1.0 "
                        "otherwise", ptype=float)
    input_offset = Param(0.0, "on-device input offset (see input_scale)",
                         ptype=float)
    quantization = Param(None, "a serving.quant.QuantizationConfig: one "
                         "object carrying wire dtype + scale/zero_point "
                         "end-to-end — setting it overrides input_dtype/"
                         "input_scale/input_offset so the on-device "
                         "dequant always matches the wire the serving "
                         "plane casts to (see docs/serving.md 'The "
                         "quantized wire')", complex=True)
    fetch_batches = Param(32, "minibatches scored per device->host fetch: "
                          "outputs are unpadded and concatenated ON DEVICE, "
                          "so a whole group costs one round-trip (each fetch "
                          "pays full link latency on tunneled/remote "
                          "devices, which dominates scoring wall-clock)",
                          ptype=int)
    cache_inputs = Param(True, "keep the frame's padded minibatches "
                         "device-resident in a bounded LRU shared across "
                         "models, so scoring the SAME frame through N "
                         "models (FindBestModel / tuning / featurizer "
                         "sweeps) uploads it once more after its first "
                         "sighting and never again; frames scored only "
                         "once (e.g. serving request batches) are never "
                         "stored, and frames over 256 MB bypass the cache "
                         "entirely. Keys include a full content digest, "
                         "so in-place edits of a cached column are "
                         "detected (and re-uploaded), never served stale",
                         ptype=bool)

    # -- execution ----------------------------------------------------------

    def _transfer_dtype(self):
        mode = self.input_dtype
        if self.quantization is not None:
            wire = self.quantization.wire_dtype
            # "none" = compute-only quantization: payloads stay in the
            # model's native transfer dtype
            mode = "auto" if wire == "none" else wire
        if mode == "auto":
            arch = getattr(self.model, "arch", None) or {}
            mode = ("bfloat16" if arch.get("dtype") == "bfloat16"
                    else "float32")
        if mode == "uint8":
            return np.dtype(np.uint8)
        if mode == "int8":
            return np.dtype(np.int8)
        if mode == "bfloat16":
            import ml_dtypes
            return np.dtype(ml_dtypes.bfloat16)
        return np.dtype(np.float32)

    def _resolve_output_layer(self) -> Optional[str]:
        if self.output_layer is not None:
            return self.output_layer
        if self.cut_output_layers:
            return self.model.layer_name_for_cut(self.cut_output_layers)
        return None

    def _set_param(self, name, value):
        # param changes invalidate the compiled forward and device placement
        self.__dict__.pop("_jitted", None)
        self.__dict__.pop("_quant_state", None)
        self.__dict__.pop("_setup_sharded", None)
        self.__dict__.pop("_setup_single_cache", None)
        self.__dict__.pop("_setup_pipeline", None)
        self.__dict__.pop("_pipeline_out_shape", None)
        self.__dict__.pop("_pipeline_plan", None)
        self.__dict__.pop("_placement_mesh", None)
        self.__dict__.pop("_placement_label", None)
        self.__dict__.pop("_placement_single", None)
        super()._set_param(name, value)

    @property
    def batch_multiple(self) -> int:
        """The divisibility constraint this model's dispatch places on
        batch rows — the mesh data-axis size its batches shard over.
        Config-derived and cheap (no placement is forced): the serving
        plane's bucket ladder rounds every bucket up to this
        (``bucket_ladder(cap, multiple=...)``), so a bucketed frame
        placed by ``dist.put_batch``/``batch_sharding`` is already
        divisible and never re-pads inside the dispatch."""
        if not self.data_parallel:
            return 1
        import jax
        n_dev = len(jax.devices())
        pp = int(self.pipeline_parallel or 0)
        if pp > 1:
            # pipelined dispatch: rows shard over ONE stage slice's
            # data axis (each micro-batch visits every slice in turn)
            if n_dev % pp:
                return 1
            slice_n = n_dev // pp
            tp = int(self.tensor_parallel or 0)
            if tp > 1:
                return slice_n // tp if slice_n % tp == 0 else 1
            return max(slice_n, 1)
        tp = int(self.tensor_parallel or 0)
        if tp > 1:
            return n_dev // tp if n_dev % tp == 0 else 1
        return max(n_dev, 1)

    # -- placement visibility (the /stats + dispatch-span surface) ----------

    @property
    def placement_label(self) -> Optional[str]:
        """Compact mesh label (``"data=4,model=2"``) once placement has
        happened; None before the first dispatch (no device work is
        forced just to report). Cached — the dispatch stage reads this
        per batch (``_set_param`` invalidates with the mesh)."""
        label = self.__dict__.get("_placement_label")
        if label is not None:
            return label
        mesh = self.__dict__.get("_placement_mesh")
        if mesh is None:
            return None
        from mmlspark_tpu.parallel import dist
        label = dist.placement_label(mesh)
        self.__dict__["_placement_label"] = label
        return label

    def placement(self) -> Dict[str, Any]:
        """Per-device placement report: how (and whether) this model
        ACTUALLY spans the mesh — the mode comes from the mesh a
        dispatch really placed on, never from configuration alone
        (``tensor_parallel=2`` with ``data_parallel=False``, a
        1-device host, or a pinned single-device scope all serve
        single-device, and must say so). ``"unplaced"`` before the
        first dispatch. Cheap — shapes + sharding metadata, no device
        sync."""
        out: Dict[str, Any] = {"tensor_parallel":
                               int(self.tensor_parallel or 0),
                               "pipeline_parallel":
                               int(self.pipeline_parallel or 0)}
        if self._pipeline_active() and "_setup_pipeline" in self.__dict__:
            runner, _ = self.__dict__["_setup_pipeline"]
            out["mode"] = "pipeline_parallel"
            out["n_stages"] = runner.n_stages
            out["stages"] = [{"stage": k, "devices": list(devs)}
                             for k, (_, _, _, devs)
                             in enumerate(runner.stages)]
            out["n_devices"] = sum(len(s["devices"])
                                   for s in out["stages"])
            return out
        mesh = self.__dict__.get("_placement_mesh")
        if mesh is None:
            single = self.__dict__.get("_placement_single")
            if single is not None:
                # dispatched through the single-device path (pinned
                # scope, data_parallel off, 1-device host): say so —
                # distinguishable from a model that never dispatched
                out["mode"] = "single_device"
                out["devices"] = [single]
                out["n_devices"] = 1
            else:
                out["mode"] = "unplaced"
            return out
        from mmlspark_tpu.parallel import dist
        n_model = mesh.shape.get("model", 1)
        out["mode"] = ("tensor_parallel" if n_model > 1
                       else "data_parallel" if mesh.devices.size > 1
                       else "single_device")
        placed = self.__dict__.get("_setup_sharded")
        out.update(dist.placement_report(
            placed[0] if placed else self.model.params, mesh))
        return out

    def _dequant_constants(self):
        """(scale, offset, deq_dtype): the on-device input transform
        constants — shared by the fused single forward and the
        pipelined stage-0 forward, so a pipeline split can never
        change the dequant semantics."""
        import jax.numpy as jnp
        is_int = np.issubdtype(self._transfer_dtype(), np.integer)
        if self.quantization is not None:
            # ONE object carries wire dtype + dequant constants: the
            # jitted forward's x*scale+offset can never drift from
            # what the serving plane cast the wire to
            scale = self.quantization.scale
            offset = float(self.quantization.zero_point)
        else:
            scale = self.input_scale
            if scale is None:
                scale = (1.0 / 255.0) if is_int else 1.0
            offset = float(self.input_offset)
        arch = getattr(self.model, "arch", None) or {}
        deq_dtype = (jnp.bfloat16 if arch.get("dtype") == "bfloat16"
                     else jnp.float32)
        return scale, offset, deq_dtype

    @property
    def _compute_quant(self):
        """The :class:`~mmlspark_tpu.serving.quant.ComputeQuantization`
        riding this model's config, or None (f32 compute)."""
        return getattr(self.quantization, "compute", None) \
            if self.quantization is not None else None

    @functools.cached_property
    def _quant_state(self):
        """``(int8-kernel param tree, {leaf path: per-channel
        scales})`` — the scale-derivation step, run ONCE per configured
        model (rollout stage time: ``configure_model`` sets the config,
        the warmup's first placement lands here) and cached until a
        param changes; None without a compute section. The quantized
        tree keeps the f32 tree's exact structure — scales ride
        OUTSIDE it as constants of the jitted forward — so sharding
        and placement machinery see nothing new."""
        comp = self._compute_quant
        if comp is None:
            return None
        from mmlspark_tpu.serving.quant import quantize_param_tree
        return quantize_param_tree(self.model.params, comp)

    @property
    def _served_params(self):
        """The tree placement uploads: int8 kernels under compute
        quantization (4x less HBM and host->device link per kernel),
        the f32 tree otherwise."""
        qs = self._quant_state
        return self.model.params if qs is None else qs[0]

    @functools.cached_property
    def _jitted(self):
        import jax
        import jax.numpy as jnp
        out_layer = self._resolve_output_layer()
        module = self.model.module()
        scale, offset, deq_dtype = self._dequant_constants()
        comp = self._compute_quant
        if comp is not None:
            from mmlspark_tpu.serving.quant import (
                dequantize_param_tree)
            qscales = self._quant_state[1]
            act_dtype = jnp.dtype(comp.activation_dtype)

        def forward(params, x):
            if jnp.issubdtype(x.dtype, jnp.integer) \
                    or scale != 1.0 or offset != 0.0:
                # dequantize/normalize on device — XLA fuses this into
                # the first layer, so integer payloads cross the link raw
                x = x.astype(deq_dtype) * deq_dtype(scale) \
                    + deq_dtype(offset)
            if comp is not None:
                # int8-compute: kernels dequantize into their matmuls
                # (w_q -> f32 * scale -> activation dtype, fused by
                # XLA — no dequantized copy persists), activations
                # meet them as act_dtype with f32 MXU accumulation,
                # and the reply comes back f32 so downstream serving
                # surfaces never see a bf16 column
                params = dequantize_param_tree(params, qscales,
                                               comp.activation_dtype)
                x = x.astype(act_dtype)
                out = module.apply(params, x, output_layer=out_layer)
                return out.astype(jnp.float32)
            return module.apply(params, x, output_layer=out_layer)

        return jax.jit(forward)

    def quant_parity_report(self, df, rtol: Optional[float] = None
                            ) -> Dict[str, Any]:
        """Row-wise parity of the int8-compute forward against the f32
        reference on one frame — the rollout verify step's evidence
        (docs/serving.md "Quantization").

        Both forwards run the PURE function (``module.apply``) on the
        same dequantized input: the reference with the f32 tree, the
        candidate with the int8 tree dequantized exactly as the served
        forward does it. A row passes when every element satisfies
        ``|q - ref| <= tol + tol * |ref|`` (``np.isclose`` with
        ``atol = rtol = tol``): the tolerance bounds the RELATIVE
        error on large outputs and the ABSOLUTE error on near-zero
        ones — int8 weight error is additive at logit scale, so a
        purely relative bound would fail any logit near zero on
        noise. ``tol`` defaults to the config's ``tolerance``. The
        two throwaway executables compile at stage time and are
        dropped — the served forward's compile-once contract is
        untouched."""
        comp = self._compute_quant
        if comp is None:
            return {"passed": True, "rows": 0, "bad_rows": 0,
                    "max_rel": 0.0, "rtol": None}
        import jax.numpy as jnp
        from mmlspark_tpu.serving.quant import dequantize_param_tree
        out_layer = self._resolve_output_layer()
        module = self.model.module()
        scale, offset, deq_dtype = self._dequant_constants()
        x = _stack_column(df[self.input_col]).astype(
            self._transfer_dtype(), copy=False)
        xj = jnp.asarray(x)
        if jnp.issubdtype(xj.dtype, jnp.integer) \
                or scale != 1.0 or offset != 0.0:
            xj = xj.astype(deq_dtype) * deq_dtype(scale) \
                + deq_dtype(offset)
        ref = np.asarray(
            module.apply(self.model.params, xj,
                         output_layer=out_layer), np.float32)
        qparams, qscales = self._quant_state
        deq = dequantize_param_tree(qparams, qscales,
                                    comp.activation_dtype)
        got = np.asarray(
            module.apply(deq, xj.astype(jnp.dtype(
                comp.activation_dtype)), output_layer=out_layer),
            np.float32)
        tol = float(rtol if rtol is not None else comp.tolerance)
        ok = np.isclose(got, ref, rtol=tol, atol=tol)
        flat_ok = ok.reshape(len(ok), -1) if ok.ndim > 1 \
            else ok.reshape(-1, 1)
        row_ok = flat_ok.all(axis=1)
        denom = np.maximum(np.abs(ref), 1.0)
        max_rel = float(np.max(np.abs(got - ref) / denom)) \
            if ref.size else 0.0
        return {"passed": bool(row_ok.all()),
                "rows": int(len(row_ok)),
                "bad_rows": int((~row_ok).sum()),
                "max_rel": max_rel, "rtol": tol}

    @functools.cached_property
    def _setup_sharded(self):
        import jax
        tp = int(self.tensor_parallel or 0)
        if tp > 1:
            # tensor parallel: ONE copy of the params spans the mesh
            # (sharded over 'model' per the dist rule) instead of one
            # copy per device; batches shard over the leftover 'data'
            # axis and XLA inserts the TP collectives
            from mmlspark_tpu.parallel import MeshSpec, dist
            n_dev = len(jax.devices())
            if n_dev % tp:
                raise ValueError(
                    f"tensor_parallel={tp} does not divide the "
                    f"{n_dev}-device host")
            mesh = build_mesh(MeshSpec.from_dict(
                {"data": n_dev // tp, "model": tp}))
            self._placement_mesh = mesh
            return (dist.shard_state(self._served_params, mesh),
                    batch_sharding(mesh), mesh.shape["data"])
        mesh = build_mesh()
        self._placement_mesh = mesh
        return (jax.device_put(self._served_params,
                               replicated_sharding(mesh)),
                batch_sharding(mesh), mesh.shape["data"])

    @functools.cached_property
    def _setup_single_cache(self):
        return {}  # device -> (params-on-device, None, 1)

    # -- pipeline parallelism (parallel/pipeline.py) -------------------------

    def _pipeline_active(self) -> bool:
        """Pipelined dispatch really engages only when the stage
        split is placeable: >= 2 stages, devices divide into equal
        slices, data_parallel on, and no pinned single-device scope
        (config alone never forces it — same honesty rule as
        tensor_parallel)."""
        pp = int(self.pipeline_parallel or 0)
        if pp < 2 or not self.data_parallel:
            return False
        import jax
        from mmlspark_tpu.parallel.topology import in_single_device_scope
        if in_single_device_scope():
            return False
        n_dev = len(jax.devices())
        return n_dev >= pp and n_dev % pp == 0

    @functools.cached_property
    def _setup_pipeline(self):
        """(runner, stage_data_multiple): the staged model.

        The layer chain is cut by :func:`~mmlspark_tpu.parallel.
        pipeline.plan_stages` (costs = per-layer param bytes; the
        slowest stage paces the pipeline, so balance is the rule),
        each stage's sub-module + remapped params are placed on their
        device slice (sharded over a per-slice data x model mesh when
        ``tensor_parallel`` composes in), and stage inputs transfer
        via ``device_put`` to the slice's batch sharding. Stage
        forwards are jitted with the INPUT buffer donated — the
        boundary buffer is reused for same-shaped outputs instead of
        allocating per hop."""
        import re
        import jax
        import jax.numpy as jnp
        from mmlspark_tpu.parallel import MeshSpec, dist
        from mmlspark_tpu.parallel.pipeline import (
            PipelineRunner, plan_stages)
        from mmlspark_tpu.models.function import LayeredModel

        pp = int(self.pipeline_parallel)
        module = self.model.module()
        layers = list(module.layers)
        out_layer = self._resolve_output_layer()
        if out_layer is not None:
            names = [n for n, _ in layers]
            layers = layers[:names.index(out_layer) + 1]
        # per-layer param ownership: flax names the chain's modules by
        # tuple path ("layers_<i>_<j>"), across every collection
        pat = re.compile(r"layers_(\d+)(_.+)?$")
        per_layer: list = [dict() for _ in layers]
        for cname, cdict in (self.model.params or {}).items():
            for key, sub in cdict.items():
                m = pat.match(key)
                if m is None or int(m.group(1)) >= len(layers):
                    continue
                per_layer[int(m.group(1))].setdefault(cname, {})[key] = sub

        def _bytes(tree) -> float:
            import jax as _j
            return float(sum(
                int(np.prod(np.shape(x), dtype=np.int64))
                * np.dtype(getattr(x, "dtype", np.float32)).itemsize
                for x in _j.tree_util.tree_leaves(tree)))

        costs = [max(sum(_bytes(c) for c in coll.values()), 1.0)
                 for coll in per_layer]
        plan = plan_stages(costs, pp, jax.devices())
        tp = int(self.tensor_parallel or 0)
        scale, offset, deq_dtype = self._dequant_constants()
        stages = []
        stage_data = 1
        for k, ((a, b), devs) in enumerate(zip(plan.boundaries,
                                               plan.devices)):
            sub_module = LayeredModel(layers=tuple(layers[a:b]))
            sub_params: Dict[str, Any] = {}
            for i in range(a, b):
                for cname, keys in per_layer[i].items():
                    for key, sub in keys.items():
                        m = pat.match(key)
                        new = f"layers_{int(m.group(1)) - a}" \
                              f"{m.group(2) or ''}"
                        sub_params.setdefault(cname, {})[new] = sub
            slice_n = len(devs)
            if tp > 1 and slice_n % tp:
                raise ValueError(
                    f"tensor_parallel={tp} does not divide the "
                    f"{slice_n}-device pipeline slice")
            shape = ({"data": slice_n // tp, "model": tp} if tp > 1
                     else {"data": slice_n})
            mesh_k = build_mesh(MeshSpec.from_dict(shape),
                                devices=list(devs))
            stage_data = mesh_k.shape["data"]
            placed = dist.shard_state(sub_params, mesh_k)
            placement = batch_sharding(mesh_k)
            first = k == 0

            def make_fn(sub_module, first):
                def fwd(p, x):
                    if first and (jnp.issubdtype(x.dtype, jnp.integer)
                                  or scale != 1.0 or offset != 0.0):
                        x = x.astype(deq_dtype) * deq_dtype(scale) \
                            + deq_dtype(offset)
                    return sub_module.apply(p, x)
                # the boundary buffer is donated: a stage's input is
                # dead the moment its output exists, so XLA may reuse
                # it in place instead of allocating per hop
                return jax.jit(fwd, donate_argnums=(1,))

            stages.append((make_fn(sub_module, first), placed, placement,
                           tuple(str(d) for d in devs)))
        runner = PipelineRunner(stages,
                                microbatches=self.pipeline_microbatches)
        self.__dict__["_pipeline_plan"] = plan
        self.__dict__["_placement_label"] = \
            f"pipe={pp},data={stage_data},model={max(tp, 1)}"
        return runner, stage_data

    def pipeline_report(self) -> Optional[Dict[str, Any]]:
        """The ``/stats`` "pipeline" block: stages, per-stage
        placement, measured bubble ratio, in-flight micro-batches.
        None when pipelining is off or nothing has dispatched yet (no
        device work is forced just to report)."""
        if not self._pipeline_active() \
                or "_setup_pipeline" not in self.__dict__:
            return None
        runner, stage_data = self.__dict__["_setup_pipeline"]
        rep = runner.report()
        plan = self.__dict__.get("_pipeline_plan")
        if plan is not None:
            for entry, (bounds, cost) in zip(rep["stages"],
                                             zip(plan.boundaries,
                                                 plan.costs)):
                entry["layers"] = list(bounds)
                entry["param_bytes"] = int(cost)
        rep["stage_data_multiple"] = stage_data
        rep["tensor_parallel"] = int(self.tensor_parallel or 0)
        return rep

    def _transform_pipelined(self, df: DataFrame) -> DataFrame:
        """The pipelined dispatch: frame rows -> micro-batches ->
        staged forward with device_put boundary hops. One host thread
        (the serving executor, when dispatched from the serving
        plane) drives the whole schedule; async dispatch keeps every
        slice busy. The first frame also runs one *blocked* probe
        pass to measure per-stage service times — the bubble-ratio
        evidence — off the steady-state path.

        The ``cache_inputs`` device-frame LRU applies to the fused
        path only: pipelined micro-batches hop BETWEEN slices, so a
        cached single-placement copy could not serve them — repeated
        offline scoring of one frame through a pipelined model
        re-uploads per pass (documented tradeoff; serving frames are
        one-shot and never cached on either path)."""
        from mmlspark_tpu.core.tracing import ambient_tracer
        from mmlspark_tpu.parallel import pad_to_bucket, round_to_multiple
        from mmlspark_tpu.parallel.pipeline import split_rows

        if self._compute_quant is not None:
            raise NotImplementedError(
                "compute quantization with pipeline_parallel is not "
                "wired: the stage split remaps params per slice and "
                "would need per-stage scale trees — serve int8 compute "
                "on the fused or tensor-parallel paths")
        runner, stage_data = self._setup_pipeline
        col = df[self.input_col]
        tdtype = self._transfer_dtype()
        x = _stack_column(col).astype(tdtype, copy=False)
        n_rows = len(x)
        meta = schema.make_role_meta(schema.SCORES_KIND, self.uid)
        if n_rows == 0:
            if x.ndim > 1:
                # the output width is a fixed model property: probe it
                # once (stage_data rows = the ladder's smallest bucket,
                # so no off-ladder shape compiles), then empty frames
                # cost nothing
                width = self.__dict__.get("_pipeline_out_shape")
                if width is None:
                    dummy = np.zeros((stage_data, *x.shape[1:]), tdtype)
                    width = np.asarray(runner.run([dummy])[0]).shape[1:]
                    self.__dict__["_pipeline_out_shape"] = width
                return df.with_column(
                    self.output_col,
                    np.zeros((0, *width), np.float32),
                    metadata=meta)
            return df.with_column(self.output_col,
                                  np.zeros((0, 0), np.float32),
                                  metadata=meta)
        # bounded like the fused path: frames process in batch_size
        # chunks (a 10M-row offline frame must not device_put itself
        # whole), and the ragged last chunk pads on the bucket ladder
        # — the micro-batch shape set stays FIXED per model config, so
        # arbitrary offline frame sizes never grow the compiled set
        bs = round_to_multiple(max(self.batch_size, stage_data),
                               stage_data, up=False)
        tracer = ambient_tracer()
        outs = []
        for start in range(0, n_rows, bs):
            chunk = x[start:start + bs]
            padded, n = pad_to_bucket(chunk, cap=bs, pad_mode="edge",
                                      multiple=stage_data)
            ranges = split_rows(len(padded),
                                self.pipeline_microbatches, stage_data)
            mbs = [padded[a:b] for a, b in ranges]
            ys = runner.run(mbs, tracer=tracer)
            if not runner._probed:
                # warmup-time evidence pass: blocked per-stage timings
                # on an already-compiled shape (compilation just
                # happened in run above); never again on the live path
                runner.probe(mbs[0])
            got = (np.asarray(ys[0]) if len(ys) == 1
                   else np.concatenate([np.asarray(y) for y in ys]))
            outs.append(got[:n])
        result = outs[0] if len(outs) == 1 else np.concatenate(outs)
        return df.with_column(self.output_col,
                              np.asarray(result, dtype=np.float32),
                              metadata=meta)

    @property
    def _device_setup(self):
        """Placement: (device params, batch sharding, n shards).

        The sharded/single decision is re-made per call (the
        single-device scope is a dynamic thread-local — freezing it in
        one cache would either leak full-mesh collectives into pinned
        tuning trials or pin a shared model single-device forever).
        Single-device placement is cached PER DEVICE: Stage.copy is
        shallow, so trial copies pinned to different chips share this
        instance's cache, and a single cached tuple would silently route
        every pinned trial's forward to the first caller's chip.
        """
        import jax
        from mmlspark_tpu.parallel.topology import in_single_device_scope
        if self.data_parallel and len(jax.devices()) > 1 \
                and not in_single_device_scope():
            return self._setup_sharded
        dev = jax.config.jax_default_device or jax.local_devices()[0]
        cache = self._setup_single_cache
        if dev not in cache:
            cache[dev] = (jax.device_put(self._served_params, dev),
                          None, 1)
        # remember that dispatch really happened (single-device), so
        # placement() can distinguish "served on one device" from
        # "never dispatched" — a thread race on this plain attribute
        # is benign (last writer wins; every value is a real device)
        self.__dict__["_placement_single"] = str(dev)
        return cache[dev]

    def transform(self, df: DataFrame) -> DataFrame:
        if self._pipeline_active():
            return self._transform_pipelined(df)
        import jax
        from mmlspark_tpu.parallel import round_to_multiple
        col = df[self.input_col]
        tdtype = self._transfer_dtype()
        params, in_sharding, n_shards = self._device_setup
        # static per-device shapes: the same divisibility rounding the
        # serving bucket ladder applies (one helper, two layers)
        bs = round_to_multiple(max(self.batch_size, n_shards), n_shards,
                               up=False)
        placement = in_sharding if in_sharding is not None else \
            (jax.config.jax_default_device or jax.local_devices()[0])
        cache_key = None
        cached_batches = None
        store_this_pass = False
        if self.cache_inputs and isinstance(col, np.ndarray) \
                and 0 < _frame_est_bytes(col, tdtype) \
                <= _FRAME_CACHE_MAX_BYTES:
            cheap = _frame_cheap_key(col, tdtype, bs, placement)
            with _FRAME_LOCK:
                seen = cheap in _FRAME_SEEN
                if not seen:
                    _FRAME_SEEN[cheap] = None
                    while len(_FRAME_SEEN) > _FRAME_SEEN_MAX_ENTRIES:
                        _FRAME_SEEN.popitem(last=False)
            if seen:
                # candidate for lookup/store: NOW pay the content digest
                # (outside the lock; full-buffer, so in-place edits of a
                # cached column always miss instead of serving stale)
                cache_key = cheap + (_content_digest(col),)
                with _FRAME_LOCK:
                    cached_batches = _FRAME_CACHE.get(cache_key)
                    if cached_batches is not None:
                        _FRAME_CACHE.move_to_end(cache_key)
                    else:
                        store_this_pass = True
        if cached_batches is not None:
            x = None                         # hit: never stack the frame
            n_rows = cached_batches[1]
        else:
            x = _stack_column(col).astype(tdtype, copy=False)
            n_rows = len(x)

        # async pipeline with grouped fetches: JAX dispatch is
        # asynchronous, so every minibatch's host->device transfer and
        # compute overlap; the only sync points are the host fetches,
        # each of which pays the full link round-trip (~100 ms on a
        # tunneled device). Rather than draining per batch, outputs are
        # unpadded and concatenated ON DEVICE and a whole group comes
        # back in ONE fetch. The group is bounded by bytes (big-image
        # batches must not queue gigabytes of in-flight inputs), and one
        # sealed group stays in flight while the previous one is
        # fetched, so device compute overlaps host readback.
        import jax.numpy as jnp
        from collections import deque
        if cached_batches is not None:
            b0 = cached_batches[0][0][0]      # first padded device batch
            batch_bytes = max(int(np.prod(b0.shape, dtype=np.int64))
                              * b0.dtype.itemsize, 1)
        else:
            batch_bytes = max(bs * int(np.prod(x.shape[1:], dtype=np.int64))
                              * x.dtype.itemsize, 1)
        group = max(min(int(self.fetch_batches),
                        (256 << 20) // batch_bytes), 1)
        inflight = []                 # dispatched batches of this group
        ready: deque = deque()        # device-concat groups awaiting fetch
        outs = []
        out_sized = False             # group re-bounded by output bytes yet?

        def seal():
            if not inflight:
                return
            if len(inflight) == 1:
                ready.append(unpad(*inflight[0]))
            else:
                ready.append(jnp.concatenate(
                    [unpad(o, n) for o, n in inflight]))
            inflight.clear()

        def batch_iter():
            if cached_batches is not None:
                yield from cached_batches[0]    # zero uploads: HBM-resident
                return
            store = [] if store_this_pass else None
            for start in range(0, n_rows, bs):
                chunk = x[start:start + bs]
                padded, n = padded_device_batch(
                    chunk, bs,
                    placement=(placement if store is not None
                               or in_sharding is not None else None),
                    put=_device_put)
                if store is not None:
                    store.append((padded, n))
                yield padded, n
            if store is not None:
                with _FRAME_LOCK:
                    _FRAME_CACHE[cache_key] = (store, n_rows)
                    while len(_FRAME_CACHE) > _FRAME_CACHE_MAX_ENTRIES:
                        # LRU: frees the evicted frame's HBM copies
                        _FRAME_CACHE.popitem(last=False)

        for padded, n in batch_iter():
            inflight.append((self._jitted(params, padded), n))
            if not out_sized:
                # the input-byte cap alone under-counts when the model's
                # output is wider than its input (truncated conv layers
                # emit per-row activations orders of magnitude larger) —
                # re-bound the group by the dispatched output's aval
                # (shape/dtype known without a fetch) so at most ~256 MB
                # of outputs are pinned in HBM awaiting readback
                o = inflight[0][0]
                out_bytes = max(
                    int(np.prod(o.shape, dtype=np.int64)) * o.dtype.itemsize,
                    1)
                group = max(min(group, (256 << 20) // out_bytes), 1)
                out_sized = True
            if len(inflight) >= group:
                seal()
                while len(ready) > 1:   # keep one group in flight
                    outs.append(np.asarray(ready.popleft()))
        seal()
        while ready:
            outs.append(np.asarray(ready.popleft()))
        if outs:
            result = np.concatenate(outs)
        else:
            # empty input: score one dummy row to learn the output width so
            # downstream consumers still see (0, num_outputs).  x is never
            # None here — empty frames are below the cache's size floor
            if x.ndim > 1:
                # same dtype as real batches, or this compiles a second
                # (float32-input) variant of the forward just for width
                dummy, _ = padded_device_batch(
                    np.zeros((1, *x.shape[1:]), self._transfer_dtype()),
                    max(n_shards, 1), placement=in_sharding,
                    put=_device_put)
                width_out = np.asarray(self._jitted(params, dummy))
                result = np.zeros((0, *width_out.shape[1:]), dtype=np.float32)
            else:
                result = np.zeros((0, 0), dtype=np.float32)
        meta = schema.make_role_meta(schema.SCORES_KIND, self.uid)
        return df.with_column(self.output_col, result, metadata=meta)

    # -- persistence --------------------------------------------------------

    def _save_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        import json
        import os
        self.model.save(os.path.join(path, "nnfunction"))
        if self.quantization is not None:
            # complex params skip JSON persistence; the quant config is
            # a tiny dict and MUST survive save/load (a staged rollout
            # checkpoint carries its wire contract with it)
            with open(os.path.join(path, "quantization.json"), "w") as f:
                json.dump(self.quantization.to_dict(), f)

    def _load_extra(self, path: str, arrays: Dict[str, np.ndarray]) -> None:
        import json
        import os
        self.model = NNFunction.load(os.path.join(path, "nnfunction"))
        qpath = os.path.join(path, "quantization.json")
        if os.path.exists(qpath):
            from mmlspark_tpu.serving.quant import QuantizationConfig
            with open(qpath) as f:
                self.quantization = QuantizationConfig.from_value(
                    json.load(f))

    # -- conveniences (parity: python CNTKModel.py loadNativeModelFromFile) --

    @staticmethod
    def load_from_function(path: str, **params) -> "NNModel":
        return NNModel(model=NNFunction.load(path), **params)
