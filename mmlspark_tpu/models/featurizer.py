"""ImageFeaturizer: transfer-learning features from a truncated deep net.

Capability parity with `image-featurizer/src/main/scala/ImageFeaturizer.
scala:36,129-176`: resize images to the network's required input size,
run a pretrained net cut N output layers from the top, and emit feature
vectors — the front half of the reference's flowers transfer-learning
pipeline (notebook example 9).

TPU-native: resize happens as a batched jitted op, the truncated forward
is its own fused XLA program, and the whole path is one host->device
round trip per minibatch.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param, HasInputCol, HasOutputCol
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.models.function import NNFunction
from mmlspark_tpu.models.nn import NNModel
from mmlspark_tpu.stages.image import ImageTransformer


class ImageFeaturizer(Transformer, HasInputCol, HasOutputCol):
    input_col = Param("image", "image column", ptype=str)
    output_col = Param("features", "feature vector column", ptype=str)
    model = Param(None, "pretrained NNFunction", complex=True)
    cut_output_layers = Param(1, "layers to cut from the top", ptype=int)
    input_shape = Param(None, "(H, W, C) the net expects; taken from the "
                              "zoo manifest when present", ptype=list)
    batch_size = Param(256, "scoring minibatch size", ptype=int)
    drop_nulls = Param(True, "drop rows with missing images", ptype=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        if self.drop_nulls:
            df = df.drop_nulls(subset=[self.input_col])
        work = df
        if self.input_shape:
            h, w = int(self.input_shape[0]), int(self.input_shape[1])
            resizer = ImageTransformer(input_col=self.input_col,
                                       output_col="__feat_img").resize(h, w)
            work = resizer.transform(work)
            feed = "__feat_img"
        else:
            feed = self.input_col
        scorer = NNModel(model=self.model, input_col=feed,
                         output_col=self.output_col,
                         cut_output_layers=self.cut_output_layers,
                         batch_size=self.batch_size)
        out = scorer.transform(work)
        return out.drop("__feat_img") if feed == "__feat_img" else out

    def _save_extra(self, path, arrays):
        import os
        self.model.save(os.path.join(path, "nnfunction"))

    def _load_extra(self, path, arrays):
        import os
        self.model = NNFunction.load(os.path.join(path, "nnfunction"))
