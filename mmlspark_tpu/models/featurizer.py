"""ImageFeaturizer: transfer-learning features from a truncated deep net.

Capability parity with `image-featurizer/src/main/scala/ImageFeaturizer.
scala:36,129-176`: resize images to the network's required input size,
run a pretrained net cut N output layers from the top, and emit feature
vectors — the front half of the reference's flowers transfer-learning
pipeline (notebook example 9).

TPU-native: resize happens as a batched jitted op, the truncated forward
is its own fused XLA program, and the whole path is one host->device
round trip per minibatch.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param, HasInputCol, HasOutputCol
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.models.function import NNFunction
from mmlspark_tpu.models.nn import NNModel
from mmlspark_tpu.stages.image import ImageTransformer


class ImageFeaturizer(Transformer, HasInputCol, HasOutputCol):
    input_col = Param("image", "image column", ptype=str)
    output_col = Param("features", "feature vector column", ptype=str)
    model = Param(None, "pretrained NNFunction", complex=True)
    cut_output_layers = Param(1, "layers to cut from the top", ptype=int)
    input_shape = Param(None, "(H, W, C) the net expects; taken from the "
                              "zoo manifest when present", ptype=list)
    batch_size = Param(256, "scoring minibatch size", ptype=int)
    drop_nulls = Param(True, "drop rows with missing images", ptype=bool)

    import functools as _functools

    def _set_param(self, name, value):
        self.__dict__.pop("_scorer", None)  # params invalidate cached scorer
        super()._set_param(name, value)

    @_functools.cached_property
    def _scorer(self) -> NNModel:
        """One NNModel reused across transforms so the truncated forward
        compiles once (its own cache lives on the instance)."""
        return NNModel(model=self.model, output_col=self.output_col,
                       cut_output_layers=self.cut_output_layers,
                       batch_size=self.batch_size)

    def transform(self, df: DataFrame) -> DataFrame:
        from mmlspark_tpu.core.schema import find_unused_column_name
        if self.drop_nulls:
            df = df.drop_nulls(subset=[self.input_col])
        work = df
        feed = self.input_col
        tmp = None
        if self.input_shape:
            h, w = int(self.input_shape[0]), int(self.input_shape[1])
            tmp = find_unused_column_name("__feat_img", df)
            resizer = ImageTransformer(input_col=self.input_col,
                                       output_col=tmp).resize(h, w)
            work = resizer.transform(work)
            feed = tmp
        scorer = self._scorer
        if scorer.input_col != feed:  # avoid invalidating the compile cache
            scorer.input_col = feed
        out = scorer.transform(work)
        return out.drop(tmp) if tmp else out

    def _save_extra(self, path, arrays):
        import os
        self.model.save(os.path.join(path, "nnfunction"))

    def _load_extra(self, path, arrays):
        import os
        self.model = NNFunction.load(os.path.join(path, "nnfunction"))
