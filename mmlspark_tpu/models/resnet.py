"""Flagship architectures: CIFAR ResNet + ConvNet, built as LayeredModels.

These fill the role of the reference model zoo's CNTK networks (ResNet
for CIFAR-10 scoring in the CIFAR10 notebook; truncated nets for
ImageFeaturizer transfer learning). TPU-first choices: NHWC layouts,
bfloat16-friendly convs that tile onto the MXU, GroupNorm instead of
BatchNorm (no mutable running stats, so the same pure function serves
scoring, training, and feature extraction), and a linear top-level layer
chain so any block boundary is a named cut point.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from mmlspark_tpu.models.function import LayeredModel, NNFunction


class ResNetBlock(nn.Module):
    """Pre-activation residual block (GroupNorm + ReLU)."""

    features: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.GroupNorm(num_groups=min(32, x.shape[-1]))(x)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), strides=(self.stride, self.stride),
                    use_bias=False, dtype=self.dtype)(y)
        y = nn.GroupNorm(num_groups=min(32, self.features))(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), use_bias=False, dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False, dtype=self.dtype)(residual)
        return y + residual


class _BlockGroup(nn.Module):
    features: int
    n_blocks: int
    stride: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i in range(self.n_blocks):
            x = ResNetBlock(self.features, stride=self.stride if i == 0 else 1,
                            dtype=self.dtype)(x)
        return x


def _global_pool(x):
    return jnp.mean(x, axis=(1, 2))


@NNFunction.register_builder("cifar_resnet")
def cifar_resnet(depth: int = 20, num_classes: int = 10,
                 width: int = 16, dtype: str = "float32") -> nn.Module:
    """CIFAR-style ResNet (depth = 6n+2: 20/32/56/110).

    Layer names: conv_in, group1..3, pool, z (logits) — ``pool`` is the
    transfer-learning feature layer (cut_layers=1 in ImageFeaturizer
    terms cuts ``z``).
    """
    if (depth - 2) % 6:
        raise ValueError(f"depth must be 6n+2, got {depth}")
    n = (depth - 2) // 6
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    layers = (
        ("conv_in", nn.Conv(width, (3, 3), use_bias=False, dtype=dt)),
        ("group1", _BlockGroup(width, n, 1, dt)),
        ("group2", _BlockGroup(2 * width, n, 2, dt)),
        ("group3", _BlockGroup(4 * width, n, 2, dt)),
        ("pool", _global_pool),
        ("z", nn.Dense(num_classes)),
    )
    return LayeredModel(layers=layers)


@NNFunction.register_builder("cifar_convnet")
def cifar_convnet(num_classes: int = 10, dtype: str = "float32") -> nn.Module:
    """Small CIFAR conv net (the CNTK ConvNet notebook analogue).

    conv/pool stack -> dense features -> logits; ``h2`` is the feature
    layer.
    """
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

    def pool2(x):
        return nn.max_pool(x, (2, 2), strides=(2, 2))

    layers = (
        ("conv1", nn.Conv(32, (3, 3), dtype=dt)),
        ("relu1", nn.relu),
        ("pool1", pool2),
        ("conv2", nn.Conv(64, (3, 3), dtype=dt)),
        ("relu2", nn.relu),
        ("pool2", pool2),
        ("flatten", lambda x: x.reshape(x.shape[0], -1)),
        ("h1", nn.Dense(256)),
        ("relu3", nn.relu),
        ("h2", nn.Dense(128)),
        ("relu4", nn.relu),
        ("z", nn.Dense(num_classes)),
    )
    return LayeredModel(layers=layers)


@NNFunction.register_builder("mlp")
def mlp(hidden: Sequence[int] = (128, 64), num_outputs: int = 1,
        activation: str = "relu") -> nn.Module:
    """Plain MLP for tabular heads (BrainScript one-hidden-layer parity)."""
    act = {"relu": nn.relu, "tanh": jnp.tanh, "gelu": nn.gelu}[activation]
    layers = []
    for i, h in enumerate(hidden):
        layers.append((f"h{i + 1}", nn.Dense(h)))
        layers.append((f"act{i + 1}", act))
    layers.append(("z", nn.Dense(num_outputs)))
    return LayeredModel(layers=tuple(layers))


# aliases used around the framework
ResNet = cifar_resnet
ConvNet = cifar_convnet
