"""Flagship architectures: CIFAR ResNet + ConvNet, built as LayeredModels.

These fill the role of the reference model zoo's CNTK networks (ResNet
for CIFAR-10 scoring in the CIFAR10 notebook; truncated nets for
ImageFeaturizer transfer learning). TPU-first choices: NHWC layouts,
bfloat16-friendly convs that tile onto the MXU, GroupNorm instead of
BatchNorm (no mutable running stats, so the same pure function serves
scoring, training, and feature extraction), and a linear top-level layer
chain so any block boundary is a named cut point.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from mmlspark_tpu.models.function import LayeredModel, NNFunction


def _group_norm(channels: int) -> nn.GroupNorm:
    """GroupNorm with the largest group count <= 32 that divides channels
    (num_groups must divide evenly; widths like 12 -> 48 channels would
    otherwise crash at init)."""
    g = min(32, channels)
    while channels % g:
        g -= 1
    return nn.GroupNorm(num_groups=g)


class ResNetBlock(nn.Module):
    """Pre-activation residual block (GroupNorm + ReLU)."""

    features: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = _group_norm(x.shape[-1])(x)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), strides=(self.stride, self.stride),
                    use_bias=False, dtype=self.dtype)(y)
        y = _group_norm(self.features)(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), use_bias=False, dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False, dtype=self.dtype)(residual)
        return y + residual


class _BlockGroup(nn.Module):
    features: int
    n_blocks: int
    stride: int
    dtype: Any = jnp.float32
    block_cls: Callable[..., nn.Module] = ResNetBlock

    @nn.compact
    def __call__(self, x):
        for i in range(self.n_blocks):
            x = self.block_cls(self.features,
                               stride=self.stride if i == 0 else 1,
                               dtype=self.dtype)(x)
        return x


def _global_pool(x):
    return jnp.mean(x, axis=(1, 2))


@NNFunction.register_builder("cifar_resnet")
def cifar_resnet(depth: int = 20, num_classes: int = 10,
                 width: int = 16, dtype: str = "float32") -> nn.Module:
    """CIFAR-style ResNet (depth = 6n+2: 20/32/56/110).

    Layer names: conv_in, group1..3, pool, z (logits) — ``pool`` is the
    transfer-learning feature layer (cut_layers=1 in ImageFeaturizer
    terms cuts ``z``).
    """
    if (depth - 2) % 6:
        raise ValueError(f"depth must be 6n+2, got {depth}")
    n = (depth - 2) // 6
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    layers = (
        ("conv_in", nn.Conv(width, (3, 3), use_bias=False, dtype=dt)),
        ("group1", _BlockGroup(width, n, 1, dt)),
        ("group2", _BlockGroup(2 * width, n, 2, dt)),
        ("group3", _BlockGroup(4 * width, n, 2, dt)),
        ("pool", _global_pool),
        ("z", nn.Dense(num_classes)),
    )
    return LayeredModel(layers=layers)


class BottleneckBlock(nn.Module):
    """Pre-activation 1-3-1 bottleneck (ResNet-50-family)."""

    features: int                 # inner width; output is 4x
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        out_f = 4 * self.features
        y = _group_norm(x.shape[-1])(x)
        y = nn.relu(y)
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = _group_norm(self.features)(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3),
                    strides=(self.stride, self.stride),
                    use_bias=False, dtype=self.dtype)(y)
        y = _group_norm(self.features)(y)
        y = nn.relu(y)
        y = nn.Conv(out_f, (1, 1), use_bias=False, dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(out_f, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False, dtype=self.dtype)(residual)
        return y + residual


_IMAGENET_LAYOUTS = {
    18: ((2, 2, 2, 2), ResNetBlock),
    34: ((3, 4, 6, 3), ResNetBlock),
    50: ((3, 4, 6, 3), BottleneckBlock),
    101: ((3, 4, 23, 3), BottleneckBlock),
}


@NNFunction.register_builder("imagenet_resnet")
def imagenet_resnet(depth: int = 50, num_classes: int = 1000,
                    width: int = 64, dtype: str = "float32") -> nn.Module:
    """ImageNet-class ResNet (18/34/50/101) — the model-zoo ResNet parity
    (`ModelDownloader` nets like ResNet50, `Schema.scala:54-74`).

    7x7/2 stem + maxpool, four groups (stride 2 between), global pool,
    logits. ``pool`` is the transfer-learning feature layer (2048-dim at
    depth 50), as in the reference's ImageFeaturizer cut.
    """
    if depth not in _IMAGENET_LAYOUTS:
        raise ValueError(f"depth must be one of {sorted(_IMAGENET_LAYOUTS)}")
    blocks, block_cls = _IMAGENET_LAYOUTS[depth]
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

    def stem_pool(x):
        return nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

    layers = [
        ("conv_in", nn.Conv(width, (7, 7), strides=(2, 2),
                            use_bias=False, dtype=dt)),
        ("stem_pool", stem_pool),
    ]
    for g, n_blocks in enumerate(blocks):
        layers.append((f"group{g + 1}",
                       _BlockGroup(width * (2 ** g), n_blocks,
                                   1 if g == 0 else 2, dt,
                                   block_cls=block_cls)))
    layers += [("pool", _global_pool), ("z", nn.Dense(num_classes))]
    return LayeredModel(layers=tuple(layers))


@NNFunction.register_builder("cifar_convnet")
def cifar_convnet(num_classes: int = 10, dtype: str = "float32") -> nn.Module:
    """Small CIFAR conv net (the CNTK ConvNet notebook analogue).

    conv/pool stack -> dense features -> logits; ``h2`` is the feature
    layer.
    """
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

    def pool2(x):
        return nn.max_pool(x, (2, 2), strides=(2, 2))

    layers = (
        ("conv1", nn.Conv(32, (3, 3), dtype=dt)),
        ("relu1", nn.relu),
        ("pool1", pool2),
        ("conv2", nn.Conv(64, (3, 3), dtype=dt)),
        ("relu2", nn.relu),
        ("pool2", pool2),
        ("flatten", lambda x: x.reshape(x.shape[0], -1)),
        ("h1", nn.Dense(256)),
        ("relu3", nn.relu),
        ("h2", nn.Dense(128)),
        ("relu4", nn.relu),
        ("z", nn.Dense(num_classes)),
    )
    return LayeredModel(layers=layers)


@NNFunction.register_builder("mlp")
def mlp(hidden: Sequence[int] = (128, 64), num_outputs: int = 1,
        activation: str = "relu") -> nn.Module:
    """Plain MLP for tabular heads (BrainScript one-hidden-layer parity)."""
    act = {"relu": nn.relu, "tanh": jnp.tanh, "gelu": nn.gelu}[activation]
    layers = []
    for i, h in enumerate(hidden):
        layers.append((f"h{i + 1}", nn.Dense(h)))
        layers.append((f"act{i + 1}", act))
    layers.append(("z", nn.Dense(num_outputs)))
    return LayeredModel(layers=tuple(layers))


# aliases used around the framework
ResNet = cifar_resnet
ConvNet = cifar_convnet
